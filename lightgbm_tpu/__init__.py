"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch re-design of the capabilities of LightGBM (reference at
/root/reference, v3.2.1.99) for TPU hardware: JAX/XLA for the training
dataflow (binning -> per-leaf histograms -> split search -> partition ->
score update as jitted programs), jax.sharding/shard_map for distributed
training over device meshes, and a Python API mirroring the reference's
python-package surface (Dataset/Booster/train/cv/sklearn wrappers).
"""

from . import checkpoint, distributed, supervisor
from .basic import Dataset
from .booster import Booster
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       print_evaluation, record_evaluation, reset_parameter)
# the checkpoint CALLBACK exports as checkpoint_callback: the bare name
# `checkpoint` is bound (by the explicit submodule import above) to the
# lightgbm_tpu.checkpoint submodule (CheckpointManager and friends)
from .callback import checkpoint as checkpoint_callback
from .config import Config
from .distributed import DistributedTimeoutError
from .engine import CVBooster, cv, train
from .serving import (ServeFrontend, ServeOverloadError, ServeSwapError,
                      ServeTimeoutError)
from .utils.log import register_logger

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "Config", "train", "cv", "CVBooster",
    "register_logger", "early_stopping", "print_evaluation", "log_evaluation",
    "record_evaluation", "reset_parameter", "EarlyStopException",
    "checkpoint_callback", "DistributedTimeoutError",
    "ServeFrontend", "ServeTimeoutError", "ServeOverloadError",
    "ServeSwapError",
]


def __getattr__(name):
    # lazy sklearn-API exports (mirrors python-package/lightgbm/__init__.py)
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in ("plot_importance", "plot_metric", "plot_tree",
                "plot_split_value_histogram", "create_tree_digraph"):
        from . import plotting as _pl
        return getattr(_pl, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
