"""Unified telemetry layer: flight recorder, trace capture, exposition.

Before this module the repo had five disconnected telemetry surfaces —
the TIMETAG scopes/counters/gauges in ``utils/profiling.py``, the
dispatch/transfer hook, ``distributed.health_snapshot()``, the
supervisor/divergence diagnosis JSONs, and ad-hoc snapshot spellings in
``bench.py`` — with no shared schema, no time axis, and nothing that
survived a crash (BENCH_r04/r05 published CPU numbers under a TPU
filename precisely because nothing recorded WHY the TPU probe died).
This module is the one subsystem every layer reports into:

- :func:`snapshot` — the ONE versioned schema over all of the above
  (scopes + counters + gauges + dispatch + health, which itself carries
  the degradation log and the serve gauges), consumed by ``bench.py``,
  the Prometheus-style ``ServeFrontend`` metrics endpoint
  (:func:`prometheus_text`), and rank-0 gang aggregation
  (:func:`gang_snapshot` over ``distributed.exchange_host``).

- :class:`FlightRecorder` — a bounded in-memory ring of per-iteration
  structured records (phase wall-time deltas, dispatch/transfer deltas,
  sentinel verdicts, OOM-degradation rungs, heartbeat ages) that flushes
  to JSONL atomically on watchdog fire / divergence verdict /
  OOM-ladder exhaustion / training error / fault-harness kill, so any
  dead gang or failed TPU round leaves a self-describing post-mortem.
  The recorder reads ONLY already-fetched host values — it rides the
  lazy sentinel drain and never forces a device sync, so recorder-on
  training keeps the fused path's 2-dispatches-per-iteration budget
  (asserted in tests/test_telemetry.py).

- :func:`trace_window` — windowed device-trace capture driving
  ``jax.profiler`` start/stop around N boosting iterations; the
  ``TraceAnnotation`` scopes profiling.timer already opens mean the
  grower phases land labeled in the perfetto trace for free. Exposed as
  ``bench.py --trace-dir/--trace-iters`` so a TPU BENCH round ships
  real device timings instead of the modeled ``mfu_est``.

Crash-durability model: the injected kill faults (``utils/faults.py``
``_hard_exit``) flush the ring before ``os._exit`` — the testable
stand-in for preemption. A REAL ``SIGKILL`` cannot flush anything, so
runs with a durable telemetry directory configured (``telemetry_dir``
param, the supervisor's diag-dir env, or ``checkpoint_path``) also
flush periodically (``telemetry_flush_period``), bounding the loss to
one flush period. Watchdog and divergence diagnoses embed the flushed
path by reference (``"flight_recorder"``), as does
``health_snapshot()`` — and therefore every checkpoint manifest's
health section.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

from .utils import log

# Version of BOTH the snapshot schema and the flight-recorder JSONL
# schema. Bump on any breaking field change; consumers (the smoke
# script, the supervisor, offline tooling) match on it.
SCHEMA_VERSION = 1

# record types a flight-recorder JSONL may contain, with their required
# fields (the machine-checkable half of the schema;
# validate_flight_jsonl enforces it)
FLIGHT_RECORD_FIELDS: Dict[str, tuple] = {
    # one per flushed file, always the first line: run identity + the
    # resolved execution context (backend, hist_method, split_fusion...)
    "run": ("schema", "rank", "pid", "context"),
    # one per boosting update() (a K-block counts as one record covering
    # ``iters`` iterations starting at ``iteration``)
    "iter": ("t", "iteration", "iters", "completed", "wall_s", "phases",
             "dispatch", "sentinel", "oom_level"),
    # one per flush event, appended in order (every later flush rewrites
    # the file with the full ring + ALL flush events so far, so an
    # oom-exhaustion flush survives into the final train-error flush)
    "flush": ("t", "reason", "health"),
}


def _utcnow() -> float:
    return time.time()


# ============================================================ snapshot

def snapshot() -> Dict[str, Any]:
    """The unified telemetry snapshot — every surface in one versioned
    document:

    - ``scopes``/``counters``: the TIMETAG wall-time table and work
      counters (empty unless profiling is enabled — measurement mode);
    - ``gauges``: the always-on health gauges (supervisor restarts,
      heartbeat ages, serve queue/latency, OOM rungs);
    - ``dispatch``: cumulative compiled-program dispatch / transfer
      counters (zero until ``profiling.install_dispatch_hook``);
    - ``memory``: :func:`memory_snapshot` — device HBM in-use/peak and
      host RSS (null fields on backends without ``memory_stats()``),
      plus the per-phase HBM watermarks TIMETAG mode accumulates;
    - ``health``: ``distributed.health_snapshot()`` — progress,
      heartbeat table, degradation log, serve gauges, and (when a
      flight recorder is live) the post-mortem JSONL path.

    Reads only host-side state — never forces a device sync — so it is
    safe to call from serving threads and the metrics endpoint."""
    from . import distributed
    from .utils import profiling
    return {
        "schema": SCHEMA_VERSION,
        "time": _utcnow(),
        "scopes": profiling.scopes(),
        "counters": profiling.counters(),
        "gauges": profiling.gauges(),
        "dispatch": profiling.dispatch_stats(),
        "memory": memory_snapshot(),
        "health": distributed.health_snapshot(),
    }


def memory_snapshot() -> Dict[str, Any]:
    """The memory plane in one dict: the current
    ``profiling.sample_memory()`` fields (``hbm_bytes_in_use`` /
    ``hbm_peak_bytes`` / ``host_rss_bytes``, each null where the backend
    or /proc doesn't supply it — the None-tolerance contract), the
    process host-RSS peak (VmHWM), and — under TIMETAG measurement mode
    — the per-phase HBM watermarks (``phase_hbm_peak``: scope name ->
    peak allocator bytes observed at that scope's exits)."""
    from .utils import profiling
    out: Dict[str, Any] = dict(profiling.sample_memory())
    out["host_rss_peak_bytes"] = profiling.host_rss_peak_bytes()
    marks = profiling.memory_watermarks()
    if marks:
        out["phase_hbm_peak"] = marks
    return out


def construct_snapshot() -> Dict[str, Any]:
    """Construct-phase telemetry in one dict — the single spelling the
    flight-recorder header, ``bench.py``'s construct fields and the
    smoke scripts all read. Sources: the always-on gauges the streaming
    construct records (``construct_sketch_s`` / ``construct_bin_s`` /
    ``construct_h2d_overlap_s`` / ``construct_peak_bytes`` /
    ``construct_rows``, basic.py ``_construct_streaming`` and
    ``distributed.load_partitioned_chunks``). Process-level semantics:
    describes the LAST streaming construct in this process (each one
    drops the family first) — bench/smoke read it right after
    constructing; per-DATASET attribution (what the flight-recorder
    header uses) lives on ``Dataset.construct_stats`` instead. Empty
    dict when no streaming construct ran in this process.
    ``rows_per_sec`` is rows / (sketch + bin) wall."""
    from .utils import profiling
    g = profiling.gauges()
    out: Dict[str, Any] = {}
    for gauge, key in (("construct_sketch_s", "sketch_pass"),
                       ("construct_bin_s", "bin_pass"),
                       ("construct_h2d_overlap_s", "h2d_overlap")):
        if gauge in g:
            out[key] = round(float(g[gauge]), 6)
    if "construct_peak_bytes" in g:
        out["peak_host_bytes"] = int(g["construct_peak_bytes"])
    if "construct_rows" in g:
        out["rows"] = int(g["construct_rows"])
        wall = float(g.get("construct_sketch_s", 0.0)
                     + g.get("construct_bin_s", 0.0))
        if wall > 0:
            out["rows_per_sec"] = round(out["rows"] / wall, 1)
    return out


_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str) -> str:
    return "lightgbm_tpu_" + _METRIC_NAME_RE.sub("_", str(name))


def _metric_value(value) -> str:
    """Full-precision exposition value: '%g'-style 6-digit rounding
    would freeze monotonic counters past ~1e6 (rate()/increase() then
    read zero forever). Integral values print as integers; the rest use
    repr's shortest round-trip form."""
    v = float(value)
    if v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def prometheus_text(snap: Optional[Dict[str, Any]] = None) -> str:
    """Render a :func:`snapshot` in the Prometheus text exposition
    format (one metric per line, ``lightgbm_tpu_`` prefix): gauges
    become first-class metrics (``lightgbm_tpu_serve_p99_ms``), scopes
    and counters become labeled totals, the dispatch counters and the
    health scalars ride along. The ``ServeFrontend`` ``/metrics``
    endpoint serves exactly this."""
    if snap is None:
        snap = snapshot()
    lines: List[str] = [
        f"# lightgbm_tpu telemetry schema {snap.get('schema', '?')}"]
    for name, value in sorted((snap.get("gauges") or {}).items()):
        lines.append(f"{_metric_name(name)} {_metric_value(value)}")
    for name, sc in sorted((snap.get("scopes") or {}).items()):
        base = _metric_name("scope")
        lines.append(f'{base}_seconds_total{{scope="{name}"}} '
                     f'{_metric_value(sc["total_s"])}')
        lines.append(f'{base}_calls_total{{scope="{name}"}} '
                     f'{int(sc["calls"])}')
    for name, value in sorted((snap.get("counters") or {}).items()):
        lines.append(f'{_metric_name("counter_total")}{{name="{name}"}} '
                     f"{_metric_value(value)}")
    for name, value in sorted((snap.get("dispatch") or {}).items()):
        lines.append(f"{_metric_name(name + '_total')} {int(value)}")
    health = snap.get("health") or {}
    for key in ("restart_count", "last_iteration"):
        if key in health:
            lines.append(f"{_metric_name(key)} {int(health[key])}")
    lines.append(f"{_metric_name('degradations_total')} "
                 f"{len(health.get('degradations') or [])}")
    for rank, entry in sorted((health.get("heartbeat") or {}).items()):
        lines.append(f'{_metric_name("heartbeat_age_seconds")}'
                     f'{{rank="{rank}"}} '
                     f'{_metric_value(entry.get("age", -1))}')
    return "\n".join(lines) + "\n"


def gang_snapshot(tag: str = "telemetry") -> List[Dict[str, Any]]:
    """Allgather every rank's :func:`snapshot` over the coordination
    service (``distributed.exchange_host`` — pure gRPC, works where
    cross-process XLA collectives don't), returning them in rank order
    on EVERY rank. Must be called in lockstep on all ranks, like any
    exchange. Single-process: ``[snapshot()]``. Rank 0 typically embeds
    the result in its reports (bench JSON, supervisor smoke)."""
    from . import distributed
    mine = snapshot()
    payloads = distributed.exchange_host(tag, json.dumps(mine))
    out = []
    for p in payloads:
        try:
            out.append(json.loads(p))
        except ValueError:
            out.append({"schema": SCHEMA_VERSION, "error": "unparseable"})
    return out


# ====================================================== flight recorder

class FlightRecorder:
    """Bounded ring of per-iteration structured records + flush events.

    Training (``GBDT.train_one_iter``) appends one record per update()
    from values the host ALREADY holds — wall time, dispatch-counter
    deltas, TIMETAG scope deltas (empty unless profiling is enabled),
    the OOM-ladder rung, heartbeat ages — so recording costs a dict
    build, never a device sync or an extra dispatch. Sentinel verdicts
    arrive LATE by design: the fused path judges its in-program NaN/Inf
    flag words lazily (the sentinel drain), and ``note_sentinel``
    back-fills the covering record when the verdict lands.

    ``flush(reason)`` serializes header + ring + every flush event so
    far to ``flight_rank{r}.jsonl`` atomically (``utils/atomic_write``:
    a kill mid-flush leaves the previous complete file, never a
    truncated hybrid). Thread-safe: the watchdog thread flushes
    concurrently with the training thread recording."""

    def __init__(self, capacity: int = 256, directory: Optional[str] = None,
                 rank: int = 0, flush_period: int = 0,
                 incarnation: int = 0):
        self.capacity = max(1, int(capacity))
        self.directory = directory or None
        self.rank = int(rank)
        self.flush_period = max(0, int(flush_period))
        # supervised relaunches must not overwrite the DEAD incarnation's
        # post-mortem: incarnation > 0 gets its own file
        self.incarnation = int(incarnation)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        # retained flush EVENTS (watchdog/divergence/OOM/error/kill/end)
        # — bounded like the ring: rare by nature, but a pathological
        # repeat-flusher must not grow memory or the file without limit
        self._flushes: deque = deque(maxlen=64)
        self._context: Dict[str, Any] = {}
        self._last_path: Optional[str] = None
        self._last_periodic = 0

    # ------------------------------------------------------- recording
    def set_context(self, **fields) -> None:
        """Merge resolved run context (backend, hist_method,
        split_fusion, rounds-per-dispatch...) into the header record."""
        with self._lock:
            self._context.update(fields)

    @property
    def has_context(self) -> bool:
        return bool(self._context)

    def record(self, iteration: int, iters: int = 1, completed: bool = True,
               wall_s: float = 0.0, phases: Optional[Dict[str, float]] = None,
               dispatch: Optional[Dict[str, int]] = None,
               sentinel: str = "off", oom_level: int = 0,
               **fields) -> None:
        """Append one per-iteration record (a K-block passes iters=K).
        Extra keyword fields ride along verbatim (coll_bytes, heartbeat
        ages...). Values must already be host-side."""
        rec = {"type": "iter", "t": _utcnow(), "iteration": int(iteration),
               "iters": int(iters), "completed": bool(completed),
               "wall_s": round(float(wall_s), 6),
               "phases": dict(phases or {}),
               "dispatch": {k: int(v) for k, v in (dispatch or {}).items()},
               "sentinel": sentinel, "oom_level": int(oom_level)}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._ring.append(rec)
        if (self.flush_period and self.directory
                and iteration // self.flush_period != self._last_periodic):
            # durable-dir runs flush every flush_period iterations so a
            # REAL SIGKILL (which cannot flush) loses at most one
            # period. Transient: a periodic event is just a checkpoint
            # of the same ring — retaining each one would grow the file
            # and the event list linearly with run length (quadratic
            # total I/O), so only EVENT flushes are kept permanently.
            self._last_periodic = iteration // self.flush_period
            self.flush("periodic", retain_event=False)

    def note_sentinel(self, iteration: int, flags: int) -> None:
        """Back-fill a lazily-judged sentinel verdict into the record
        covering ``iteration`` (the fused path judges its in-program
        flag words iterations after the step dispatched). ``flags`` is
        the judged word: 0 = clean."""
        verdict = "ok" if not flags else f"flags=0b{int(flags):05b}"
        with self._lock:
            for rec in reversed(self._ring):
                if rec["type"] != "iter":
                    continue
                if rec["iteration"] <= iteration \
                        < rec["iteration"] + max(rec["iters"], 1):
                    rec["sentinel"] = verdict
                    return

    def records(self) -> List[dict]:
        """Current ring contents (oldest first; copies)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    # --------------------------------------------------------- flushing
    @property
    def _filename(self) -> str:
        if self.incarnation > 0:
            return f"flight_rank{self.rank}.r{self.incarnation}.jsonl"
        return f"flight_rank{self.rank}.jsonl"

    def _resolve_path(self) -> str:
        d = self.directory
        if not d:
            # event flushes must land SOMEWHERE even when no durable dir
            # was configured — a temp dir beats losing the post-mortem
            import tempfile
            d = tempfile.mkdtemp(prefix="lgbm_flight_")
            self.directory = d
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, self._filename)

    def path(self) -> Optional[str]:
        """Where this recorder flushes (None until a directory is known
        — i.e. configured, or created by the first event flush)."""
        if self._last_path:
            return self._last_path
        if self.directory:
            return os.path.join(self.directory, self._filename)
        return None

    def flush(self, reason: str, retain_event: bool = True) -> Optional[str]:
        """Write header + ring + flush events to the JSONL atomically
        and return the path (best-effort: a flush must never turn a
        crash diagnosis into a crash of its own — on failure it warns
        and returns None). Each flush appends its own event record
        first, carrying the reason and the health/scope state at flush
        time, so the LAST line of the file names what killed the run
        and which iteration was in flight. ``retain_event=False``
        (periodic checkpoint flushes) writes the event into THIS file
        but does not keep it for later flushes — retained events are
        the rare diagnostic ones (bounded at 64, oldest dropped)."""
        from . import distributed
        from .utils import profiling
        from .utils.atomic_write import atomic_write_text
        try:
            health = distributed.health_snapshot()
        except Exception:
            health = {}
        event = {"type": "flush", "t": _utcnow(), "reason": str(reason),
                 "health": health, "scopes": profiling.scopes(),
                 "gauges": profiling.gauges(),
                 "dispatch": profiling.dispatch_stats()}
        try:
            # the WHOLE flush — event append, directory resolution (which
            # may create the fallback temp dir), write, _last_path — runs
            # under the lock: the watchdog thread and the training
            # thread's error flush fire together by design, and racing
            # _resolve_path would mint two temp dirs and split the
            # post-mortem across divergent files
            with self._lock:
                if retain_event:
                    self._flushes.append(event)
                header = {"type": "run", "schema": SCHEMA_VERSION,
                          "rank": self.rank, "pid": os.getpid(),
                          "capacity": self.capacity,
                          "context": dict(self._context)}
                lines = [header] + [dict(r) for r in self._ring] \
                    + [dict(f) for f in self._flushes]
                if not retain_event:
                    lines.append(event)
                path = self._resolve_path()
                atomic_write_text(path, "\n".join(
                    json.dumps(r, sort_keys=True, default=str)
                    for r in lines) + "\n")
                self._last_path = path
            return path
        except Exception as e:       # noqa: BLE001 — see docstring
            try:
                log.warning(f"flight recorder flush failed ({reason}): {e}")
            except Exception:
                pass
            return None


# process-level recorder: ONE per process (the training plane is
# process-wide — heartbeats, watchdog, degradation log all are), rebuilt
# by configure() whenever a new training run initializes
_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def configure(config=None) -> Optional[FlightRecorder]:
    """(Re)build the process flight recorder from config — called by
    ``GBDT._init_train`` so every training run starts with a fresh ring
    (like ``distributed.reset_degradations``). Returns the recorder, or
    None (and clears any previous one) when
    ``telemetry_flight_recorder`` is off.

    Flush directory resolution: explicit ``telemetry_dir`` param > the
    supervisor's diag-dir env (supervised gang children inherit it, so
    their post-mortems land next to the watchdog/divergence diagnoses)
    > ``checkpoint_path``/telemetry > none (event flushes then fall
    back to a temp dir)."""
    global _recorder
    get = (lambda k, d: getattr(config, k, d)) if config is not None \
        else (lambda k, d: d)
    if not bool(get("telemetry_flight_recorder", True)):
        with _recorder_lock:
            _recorder = None
        return None
    from . import distributed
    directory = str(get("telemetry_dir", "") or "")
    if not directory:
        directory = os.environ.get(distributed._DIAG_DIR_ENV, "") or ""
    if not directory:
        ck = str(get("checkpoint_path", "") or "")
        if ck:
            directory = os.path.join(ck, "telemetry")
    rec = FlightRecorder(
        capacity=int(get("telemetry_ring_size", 256)),
        directory=directory or None,
        rank=distributed.jax_rank(),
        flush_period=int(get("telemetry_flush_period", 64)),
        incarnation=int(os.environ.get(distributed._RESTART_COUNT_ENV,
                                       "0") or 0))
    with _recorder_lock:
        _recorder = rec
    return rec


def recorder() -> Optional[FlightRecorder]:
    """The live process recorder (None when disabled/never configured)."""
    return _recorder


def recorder_path() -> Optional[str]:
    """The live recorder's JSONL path, for embedding BY REFERENCE in
    health snapshots, checkpoint manifests and watchdog/divergence
    diagnoses. None when no recorder is live or no directory is known
    yet."""
    rec = _recorder
    return rec.path() if rec is not None else None


def flush_recorder(reason: str) -> Optional[str]:
    """Flush the process recorder (no-op None when there isn't one).
    For CONTEXT-FREE event paths only — the watchdog thread, the
    divergence verdict, ``faults._hard_exit`` — which have no booster
    in hand; booster-scoped paths (engine train-error/train-end, the
    OOM ladder) flush ``GBDT._flight`` directly so a multi-booster
    process (cv folds, bench probes) never flushes the wrong ring."""
    rec = _recorder
    if rec is None:
        return None
    return rec.flush(reason)


# ------------------------------------------------- JSONL validation

def validate_flight_record(rec: Dict[str, Any]) -> List[str]:
    """Schema-check one flight-recorder record; returns the list of
    violations (empty = valid)."""
    errs = []
    rtype = rec.get("type")
    if rtype not in FLIGHT_RECORD_FIELDS:
        return [f"unknown record type {rtype!r}"]
    for f in FLIGHT_RECORD_FIELDS[rtype]:
        if f not in rec:
            errs.append(f"{rtype} record missing field {f!r}")
    if rtype == "run" and rec.get("schema") != SCHEMA_VERSION:
        errs.append(f"schema {rec.get('schema')!r} != {SCHEMA_VERSION}")
    return errs


def validate_flight_jsonl(path: str):
    """Parse + schema-validate a flushed flight-recorder JSONL. Returns
    ``(records, errors)``; a valid file has a ``run`` header first, at
    least one ``flush`` event, and no per-record violations."""
    records: List[dict] = []
    errors: List[str] = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                errors.append(f"line {i + 1}: unparseable JSON ({e})")
                continue
            errors.extend(f"line {i + 1}: {m}"
                          for m in validate_flight_record(rec))
            records.append(rec)
    if not records or records[0].get("type") != "run":
        errors.append("first record is not a 'run' header")
    if not any(r.get("type") == "flush" for r in records):
        errors.append("no 'flush' event record")
    return records, errors


# ==================================================== trace capture

class TraceResult:
    """Outcome of a :func:`trace_window` capture."""

    def __init__(self, trace_dir: str, iters: Optional[int]):
        self.trace_dir = trace_dir
        self.iters = iters
        self.ok = False
        self.error: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return {"dir": self.trace_dir, "iters": self.iters,
                "ok": self.ok, "error": self.error}


@contextmanager
def trace_window(trace_dir: str,
                 iters: Optional[int] = None) -> Iterator[TraceResult]:
    """Capture a device trace around a window of boosting iterations::

        with telemetry.trace_window(d, iters=N) as tw:
            for _ in range(N):
                booster.update()

    Drives ``jax.profiler.start_trace``/``stop_trace``; the
    ``TraceAnnotation`` scopes ``profiling.timer`` opens mean the
    grower phases (hist_pass / split_search / apply_split under TIMETAG,
    grow_tree/score_update always) arrive labeled in the perfetto trace
    for free. ``iters`` is metadata recorded in the result (bench.py
    writes it into the BENCH JSON).

    Tolerant by design: a backend whose profiler cannot start (or a
    wedged stop) records ``tw.error`` instead of raising — trace
    capture is measurement, and measurement must never kill the run
    being measured. ``tw.ok`` is True only when both start and stop
    succeeded."""
    tw = TraceResult(trace_dir, iters)
    import jax
    started = False
    try:
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception as e:       # noqa: BLE001 — tolerance contract above
        tw.error = f"start_trace failed: {e}"
        log.warning(f"trace_window: {tw.error}")
    try:
        yield tw
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                tw.ok = True
            except Exception as e:   # noqa: BLE001
                tw.error = f"stop_trace failed: {e}"
                log.warning(f"trace_window: {tw.error}")


def trace_files(trace_dir: str) -> List[str]:
    """Trace artifacts under a capture directory (the ``.pb``/
    ``.json.gz`` event files jax's profiler writes) — what the smoke
    test asserts non-empty to call a capture loadable."""
    out = []
    for root, _dirs, files in os.walk(trace_dir):
        for f in files:
            if f.endswith((".pb", ".json.gz", ".trace.json.gz", ".xplane.pb")):
                out.append(os.path.join(root, f))
    return sorted(out)
