"""Automated gang post-mortem: merge every per-rank breadcrumb a failed
run leaves behind into ONE timeline and classify what killed it.

Before this module a dead gang left its evidence scattered: per-rank,
incarnation-suffixed ``flight_rank*.jsonl`` rings (telemetry.py),
``watchdog_rank*.json`` stall diagnoses and ``divergence_rank*.json``
integrity verdicts (distributed.py), the supervisor's ``GangFailure``
history (exit codes per rank), and checkpoint-manifest health sections —
five artifact families an operator had to correlate by hand (and the
BENCH_r04/r05 rounds died with all of it unread). This module is the
correlator:

- :func:`analyze` gathers every artifact it can find (directories +
  an optional ``GangFailure`` list + checkpoint manifests), merges them
  into a wall-clock-ordered timeline, and auto-classifies the failure
  into one of the :data:`VERDICTS` — naming the first-bad rank, the
  iteration, and (for OOM) the memory trend leading up to it from the
  flight records' per-iteration memory samples.

- :class:`Postmortem` renders both ways: ``render()`` is the
  human-readable report, ``to_json()`` the machine document
  (``scripts/postmortem.py`` writes both; ``supervisor.run_supervised``
  runs the analysis on gang failure and embeds the report path in
  ``SupervisorReport.postmortem`` / ``GangFailedError.postmortem``).

Classification is evidence-ranked, not first-match-on-files: a hung gang
produces watchdog exits on its HEALTHY ranks (the watchdog exit is the
symptom, the suspect list is the evidence), a killed rank exits 137 with
a ``fault-kill`` flush, a diverged rank writes its own verdict before
exiting 95, NaN runs leave a ``train-error`` flush naming the poisoned
iteration, and OOM runs leave the ladder's rung history plus an
``oom-exhausted`` flush. Priority: divergence (a majority vote is hard
evidence) > kill > OOM > NaN > hang > unknown.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# verdicts in evidence-priority order (strongest first); "unknown" when
# nothing classifiable was found
VERDICTS = ("divergence", "kill", "oom", "nan", "hang", "unknown")

# exit codes (mirrors distributed.py — re-declared so offline analysis
# of copied artifact dirs needs no jax import)
KILL_EXIT_CODE = 137
DIVERGENCE_EXIT_CODE = 95
SPAWN_FAIL_EXIT_CODE = 96
WATCHDOG_EXIT_CODE = 97

_FLIGHT_RE = re.compile(r"flight_rank(\d+)(?:\.r(\d+))?\.jsonl$")

REPORT_JSON = "postmortem.json"
REPORT_TEXT = "postmortem.txt"


# ============================================================ gathering

@dataclass
class RankFlight:
    """One rank's parsed flight-recorder JSONL."""
    rank: int
    incarnation: int
    path: str
    context: Dict[str, Any] = field(default_factory=dict)
    iters: List[dict] = field(default_factory=list)
    flushes: List[dict] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    @property
    def last_iteration(self) -> int:
        done = [r["iteration"] + r.get("iters", 1) - 1
                for r in self.iters if r.get("completed")]
        return max(done) if done else -1


def _parse_flight(path: str, rank: int, incarnation: int) -> RankFlight:
    from . import telemetry
    fl = RankFlight(rank=rank, incarnation=incarnation, path=path)
    try:
        records, errors = telemetry.validate_flight_jsonl(path)
    except OSError as e:
        fl.errors.append(str(e))
        return fl
    fl.errors.extend(errors)
    for rec in records:
        t = rec.get("type")
        if t == "run":
            fl.context = rec.get("context") or {}
        elif t == "iter":
            fl.iters.append(rec)
        elif t == "flush":
            fl.flushes.append(rec)
    return fl


def gather_flights(dirs: List[str]) -> List[RankFlight]:
    """Find and parse every ``flight_rank*.jsonl`` (including the
    ``.rN`` incarnation-suffixed ones a supervised relaunch writes)
    under the given directories, newest incarnation last per rank."""
    out: List[RankFlight] = []
    seen = set()
    for d in dirs:
        for path in sorted(glob.glob(os.path.join(d, "flight_rank*.jsonl"))):
            if path in seen:
                continue
            seen.add(path)
            m = _FLIGHT_RE.search(os.path.basename(path))
            if not m:
                continue
            out.append(_parse_flight(path, int(m.group(1)),
                                     int(m.group(2) or 0)))
    out.sort(key=lambda f: (f.incarnation, f.rank))
    return out


def gather_diags(dirs: List[str]) -> List[dict]:
    """Watchdog / divergence diagnosis JSONs still on disk. (The
    supervisor CONSUMES these into ``GangFailure.watchdog`` as it reads
    them — pass the failure history to :func:`analyze` to cover the
    consumed ones.)"""
    out = []
    for d in dirs:
        for pat in ("watchdog_rank*.json", "divergence_rank*.json"):
            for path in sorted(glob.glob(os.path.join(d, pat))):
                try:
                    with open(path) as fh:
                        diag = json.load(fh)
                except (OSError, ValueError):
                    continue
                if "kind" not in diag:
                    # pre-PR watchdog diags carried no kind marker
                    diag["kind"] = ("divergence" if "divergence" in
                                    os.path.basename(path) else "watchdog")
                diag.setdefault("_path", path)
                out.append(diag)
    return out


def gather_manifests(checkpoint_dir: Optional[str]) -> List[dict]:
    """Health sections of every published checkpoint manifest (iteration
    + the health snapshot at write time) — the "last known good" marks
    on the timeline."""
    if not checkpoint_dir:
        return []
    out = []
    for path in sorted(glob.glob(os.path.join(checkpoint_dir, "ckpt_*",
                                              "MANIFEST.json"))):
        if path.split(os.sep)[-2].endswith(".tmp"):
            continue
        try:
            with open(path) as fh:
                man = json.load(fh)
        except (OSError, ValueError):
            continue
        out.append({"iteration": man.get("iteration"),
                    "health": man.get("health") or {}, "_path": path})
    return out


def _normalize_failures(failures) -> List[dict]:
    """Accept ``GangFailure`` objects or equivalent dicts; emit dicts
    with incarnation / failed_ranks / exit_codes / reason / watchdog."""
    out = []
    for f in failures or []:
        if isinstance(f, dict):
            d = dict(f)
        else:
            d = {"incarnation": getattr(f, "incarnation", 0),
                 "failed_ranks": list(getattr(f, "failed_ranks", [])),
                 "exit_codes": dict(getattr(f, "exit_codes", {}) or {}),
                 "reason": getattr(f, "reason", ""),
                 "watchdog": list(getattr(f, "watchdog", []) or []),
                 "world_size": getattr(f, "world_size", 0)}
        d["exit_codes"] = {int(r): c for r, c in
                           (d.get("exit_codes") or {}).items()
                           if c is not None}
        out.append(d)
    return out


# ============================================================= timeline

def _event(t, rank, kind, iteration, detail) -> dict:
    return {"t": t, "rank": rank, "kind": kind,
            "iteration": iteration, "detail": detail}


def build_timeline(flights: List[RankFlight], diags: List[dict],
                   failures: List[dict],
                   manifests: List[dict]) -> List[dict]:
    """Merge every artifact into one wall-clock-ordered event list.
    Per-iteration records are summarized (only state CHANGES make the
    timeline: OOM rung steps, incomplete steps, bad sentinel verdicts,
    plus each rank's last completed record) — the full rings stay in the
    JSONLs the report references. Events without a wall timestamp
    (exit codes) sort last."""
    events: List[dict] = []
    for fl in flights:
        prev_oom = 0
        for i, rec in enumerate(fl.iters):
            oom = int(rec.get("oom_level", 0))
            interesting = (oom != prev_oom
                           or not rec.get("completed", True)
                           or str(rec.get("sentinel", "")).startswith(
                               "flags=")
                           or i == len(fl.iters) - 1)
            prev_oom = oom
            if not interesting:
                continue
            bits = []
            if not rec.get("completed", True):
                bits.append("IN-FLIGHT (never completed)")
            if oom:
                bits.append(f"oom_level={oom}")
            sent = rec.get("sentinel")
            if str(sent).startswith("flags="):
                bits.append(f"sentinel {sent}")
            mem = rec.get("mem") or {}
            hbm = mem.get("hbm_bytes_in_use")
            rss = mem.get("host_rss_bytes")
            if hbm is not None:
                bits.append(f"hbm={hbm / 1e9:.2f}GB")
            if rss is not None:
                bits.append(f"rss={rss / 1e9:.2f}GB")
            events.append(_event(
                rec.get("t"), fl.rank, "iter", rec.get("iteration"),
                f"iteration {rec.get('iteration')} "
                + (" ".join(bits) if bits else "completed")))
        degr_seen = set()
        for flush in fl.flushes:
            events.append(_event(flush.get("t"), fl.rank, "flush", None,
                                 f"flush: {flush.get('reason')}"))
            for d in (flush.get("health") or {}).get("degradations") or []:
                key = (d.get("seq"), d.get("kind"), d.get("level"))
                if key in degr_seen:
                    continue
                degr_seen.add(key)
                extra = ""
                pb = d.get("predicted_hist_bytes")
                if pb:
                    extra += f" predicted_hist_bytes={pb}"
                hbm = (d.get("memory") or {}).get("hbm_bytes_in_use")
                if hbm is not None:
                    extra += f" hbm={hbm / 1e9:.2f}GB"
                events.append(_event(
                    d.get("t"), fl.rank, "degradation", d.get("iteration"),
                    f"degradation {d.get('kind')} level "
                    f"{d.get('level')}: {d.get('action')}{extra}"))
    for diag in diags:
        kind = diag.get("kind", "watchdog")
        if kind == "divergence":
            detail = (f"divergence verdict: rank {diag.get('rank')} voted "
                      f"corrupt (corrupt_ranks="
                      f"{diag.get('corrupt_ranks')})")
        else:
            detail = (f"watchdog fired on rank {diag.get('rank')}: phase "
                      f"{diag.get('phase')!r} stalled "
                      f"{diag.get('elapsed')}s (deadline "
                      f"{diag.get('deadline')}s), suspects "
                      f"{diag.get('suspects')}")
        events.append(_event(diag.get("t"), diag.get("rank"), kind,
                             diag.get("iteration"), detail))
    for man in manifests:
        h = man.get("health") or {}
        events.append(_event(None, None, "checkpoint", man.get("iteration"),
                             f"checkpoint published at iteration "
                             f"{man.get('iteration')} (restart_count "
                             f"{h.get('restart_count')})"))
    for f in failures:
        for rank, code in sorted((f.get("exit_codes") or {}).items()):
            label = {KILL_EXIT_CODE: "killed (137)",
                     DIVERGENCE_EXIT_CODE: "diverged (95)",
                     SPAWN_FAIL_EXIT_CODE: "spawn failed (96)",
                     WATCHDOG_EXIT_CODE: "watchdog exit (97)"}.get(
                         code, f"exit {code}")
            events.append(_event(None, rank, "exit", None,
                                 f"incarnation {f.get('incarnation')}: "
                                 f"rank {rank} {label}"))
        if f.get("reason"):
            events.append(_event(None, None, "failure", None,
                                 f"incarnation {f.get('incarnation')}: "
                                 f"{f['reason']}"))
    events.sort(key=lambda e: (e["t"] is None, e["t"] or 0.0))
    return events


# ======================================================== classification

def _memory_trend(fl: Optional[RankFlight]) -> Optional[dict]:
    """First->last memory readings over a rank's flight ring (the trend
    BEFORE the failure): per source (hbm/rss), first/last bytes and a
    coarse direction. None when no record carried a sample."""
    if fl is None:
        return None
    series: Dict[str, List[Tuple[int, int]]] = {"hbm": [], "rss": []}
    for rec in fl.iters:
        mem = rec.get("mem") or {}
        it = int(rec.get("iteration", -1))
        if mem.get("hbm_bytes_in_use") is not None:
            series["hbm"].append((it, int(mem["hbm_bytes_in_use"])))
        if mem.get("host_rss_bytes") is not None:
            series["rss"].append((it, int(mem["host_rss_bytes"])))
    out = {}
    for name, pts in series.items():
        if len(pts) < 1:
            continue
        first, last = pts[0][1], pts[-1][1]
        if len(pts) >= 2 and last > first * 1.05:
            direction = "rising"
        elif len(pts) >= 2 and last < first * 0.95:
            direction = "falling"
        else:
            direction = "flat"
        out[name] = {"first_bytes": first, "last_bytes": last,
                     "first_iteration": pts[0][0],
                     "last_iteration": pts[-1][0],
                     "samples": len(pts), "trend": direction}
    return out or None


def _iter_from_reason(reason: str) -> Optional[int]:
    m = re.search(r"iteration (\d+)", reason or "")
    return int(m.group(1)) if m else None


_NAN_TOKENS = ("non-finite", "nan", "check_numerics", "sentinel")
_OOM_TOKENS = ("resource_exhausted", "out of memory", "oom-exhausted",
               "resource exhausted")


def classify(flights: List[RankFlight], diags: List[dict],
             failures: List[dict]) -> Tuple[str, Optional[int],
                                            Optional[int], str, List[str]]:
    """Rank the evidence and return
    ``(verdict, rank, iteration, cause, evidence_lines)``.

    Priority (strongest evidence first): divergence (the gang's own
    majority vote names the corrupt rank) > kill (exit 137 / fault-kill
    flush) > OOM (ladder exhaustion / RESOURCE_EXHAUSTED error) > NaN
    (sentinel or check_numerics verdict) > hang (watchdog diagnosis —
    the FIRING rank is healthy; the suspect list names the stalled one)
    > unknown."""
    evidence: List[str] = []
    flight_by_rank = {fl.rank: fl for fl in flights}

    # every flush reason across ranks, with its rank
    flushes = [(fl.rank, fl_f.get("reason") or "", fl_f)
               for fl in flights for fl_f in fl.flushes]
    all_exits: Dict[int, int] = {}
    for f in failures:
        for rank, code in (f.get("exit_codes") or {}).items():
            all_exits.setdefault(int(rank), int(code))
    diag_pool = list(diags)
    for f in failures:
        diag_pool.extend(f.get("watchdog") or [])

    # ---- divergence
    div_diags = [d for d in diag_pool if d.get("kind") == "divergence"
                 or d.get("corrupt_ranks")]
    div_exits = [r for r, c in all_exits.items()
                 if c == DIVERGENCE_EXIT_CODE]
    if div_diags or div_exits:
        if div_diags:
            d = div_diags[0]
            corrupt = d.get("corrupt_ranks") or [d.get("rank")]
            rank = int(corrupt[0]) if corrupt else d.get("rank")
            it = d.get("iteration")
            evidence.append(
                f"divergence diagnosis: corrupt_ranks={corrupt} at "
                f"iteration {it} (majority fingerprint vote)")
        else:
            rank, it = div_exits[0], None
            evidence.append(f"rank {rank} exited with the divergence "
                            f"code ({DIVERGENCE_EXIT_CODE})")
        for r in div_exits:
            evidence.append(f"rank {r} exit code {DIVERGENCE_EXIT_CODE} "
                            f"(diverged)")
        cause = (f"rank {rank} held model state that diverged from the "
                 f"gang's majority (silent corruption); the integrity "
                 f"vote named it and it exited for a checkpoint restore")
        return "divergence", rank, it, cause, evidence

    # ---- kill
    kill_flush = [(r, reason) for r, reason, _ in flushes
                  if reason.startswith("fault-kill")]
    kill_exits = [r for r, c in all_exits.items() if c == KILL_EXIT_CODE]
    if kill_flush or kill_exits:
        if kill_flush:
            rank, reason = kill_flush[0]
            it = _iter_from_reason(reason)
            evidence.append(f"rank {rank} flight recorder flushed "
                            f"{reason!r}")
        else:
            rank, it = kill_exits[0], None
        for r in kill_exits:
            evidence.append(f"rank {r} exit code {KILL_EXIT_CODE} "
                            f"(SIGKILL shape: preemption / oom-kill / "
                            f"harness kill)")
        if it is None and rank in flight_by_rank:
            it = flight_by_rank[rank].last_iteration + 1
        cause = (f"rank {rank} was hard-killed"
                 + (f" at iteration {it}" if it is not None else "")
                 + " (exit 137 — the preemption/oom-kill shape)")
        return "kill", rank, it, cause, evidence

    # ---- oom
    oom_flush = [(r, reason) for r, reason, _ in flushes
                 if reason.startswith("oom-exhausted")
                 or (reason.startswith("train-error")
                     and any(tok in reason.lower()
                             for tok in _OOM_TOKENS))]
    oom_degr = []
    for fl in flights:
        for fl_f in fl.flushes:
            for d in (fl_f.get("health") or {}).get("degradations") or []:
                if "oom" in str(d.get("kind", "")):
                    oom_degr.append((fl.rank, d))
    if oom_flush:
        rank, reason = oom_flush[0]
        it = _iter_from_reason(reason)
        evidence.append(f"rank {rank} flushed {reason!r}")
        for r, d in oom_degr:
            line = (f"rank {r} degradation rung {d.get('level')}: "
                    f"{d.get('action')}")
            if d.get("predicted_hist_bytes"):
                line += (f" (traffic model predicted "
                         f"{d['predicted_hist_bytes']} bytes/pass)")
            evidence.append(line)
        cause = (f"rank {rank} exhausted device memory"
                 + (f" at iteration {it}" if it is not None else "")
                 + (f" after stepping down "
                    f"{len([1 for r, _ in oom_degr if r == rank])} "
                    f"degradation rung(s)" if oom_degr else ""))
        return "oom", rank, it, cause, evidence

    # ---- nan
    nan_flush = [(r, reason) for r, reason, _ in flushes
                 if reason.startswith("train-error")
                 and any(tok in reason.lower() for tok in _NAN_TOKENS)]
    nan_iters = [(fl.rank, rec) for fl in flights for rec in fl.iters
                 if str(rec.get("sentinel", "")).startswith("flags=")]
    if nan_flush or nan_iters:
        if nan_flush:
            rank, reason = nan_flush[0]
            it = _iter_from_reason(reason)
            evidence.append(f"rank {rank} flushed {reason!r}")
        else:
            rank, rec = nan_iters[0]
            it = rec.get("iteration")
            evidence.append(f"rank {rank} iteration {it} sentinel "
                            f"verdict {rec.get('sentinel')!r}")
        for r, rec in nan_iters:
            evidence.append(f"rank {r} iteration {rec.get('iteration')} "
                            f"carried sentinel {rec.get('sentinel')!r}")
        cause = (f"rank {rank} hit non-finite values"
                 + (f" at iteration {it}" if it is not None else "")
                 + " (NaN/Inf sentinel — check the objective, "
                   "learning_rate, and input features)")
        return "nan", rank, it, cause, evidence

    # ---- hang
    wd_diags = [d for d in diag_pool if d.get("kind") != "divergence"
                and (d.get("suspects") is not None
                     or d.get("phase") is not None)]
    wd_exits = [r for r, c in all_exits.items()
                if c == WATCHDOG_EXIT_CODE]
    if wd_diags or wd_exits:
        # the watchdog fires on HEALTHY ranks: the stalled rank is in
        # the suspect lists (majority across diags), or — fallback —
        # the rank whose flight ring stopped earliest
        from collections import Counter
        votes = Counter(s for d in wd_diags
                        for s in (d.get("suspects") or []))
        if votes:
            rank = int(votes.most_common(1)[0][0])
            evidence.append(f"watchdog suspect vote: {dict(votes)}")
        elif flights:
            # judge only each rank's NEWEST incarnation ring
            # (flight_by_rank keeps the last per rank — flights sort by
            # incarnation): a stale ring from a restarted-away
            # incarnation always stops early and would misname the rank
            rank = min(flight_by_rank.values(),
                       key=lambda fl: fl.last_iteration).rank
            evidence.append(
                f"no heartbeat suspects; rank {rank} has the earliest "
                f"last completed iteration "
                f"({flight_by_rank[rank].last_iteration})")
        else:
            rank = wd_diags[0].get("rank") if wd_diags else (
                wd_exits[0] if wd_exits else None)
        it = max((d.get("iteration") for d in wd_diags
                  if d.get("iteration") is not None), default=None)
        for d in wd_diags:
            evidence.append(
                f"rank {d.get('rank')} watchdog: phase "
                f"{d.get('phase')!r} stalled {d.get('elapsed')}s "
                f"(deadline {d.get('deadline')}s)")
        for r in wd_exits:
            evidence.append(f"rank {r} exit code {WATCHDOG_EXIT_CODE} "
                            f"(watchdog — symptom, not the stalled rank)")
        cause = (f"the gang stalled"
                 + (f" at iteration {it}" if it is not None else "")
                 + (f"; rank {rank} is the first-stalled suspect"
                    if rank is not None else ""))
        return "hang", rank, it, cause, evidence

    # ---- unknown
    spawn = [r for r, c in all_exits.items() if c == SPAWN_FAIL_EXIT_CODE]
    if spawn:
        evidence.append(f"rank(s) {spawn} never came up "
                        f"(exit {SPAWN_FAIL_EXIT_CODE})")
        return ("unknown", spawn[0], None,
                f"rank {spawn[0]}'s process failed to spawn", evidence)
    for f in failures:
        if f.get("reason"):
            evidence.append(f"incarnation {f.get('incarnation')}: "
                            f"{f['reason']}")
    return ("unknown", None, None,
            "no classifiable evidence found in the artifacts", evidence)


# =============================================================== report

@dataclass
class Postmortem:
    """The analyzed outcome: verdict + named rank + evidence + the
    merged timeline. ``to_json`` is the machine document, ``render``
    the human one."""
    verdict: str
    rank: Optional[int]
    iteration: Optional[int]
    cause: str
    evidence: List[str]
    timeline: List[dict]
    memory: Optional[dict]
    sources: Dict[str, Any]
    generated_at: float = 0.0
    schema: int = 1

    def to_json(self) -> Dict[str, Any]:
        return {"schema": self.schema, "generated_at": self.generated_at,
                "verdict": self.verdict, "rank": self.rank,
                "iteration": self.iteration, "cause": self.cause,
                "evidence": self.evidence, "memory": self.memory,
                "timeline": self.timeline, "sources": self.sources}

    def render(self, max_timeline: int = 40) -> str:
        lines = ["== lightgbm_tpu gang post-mortem =="]
        head = f"VERDICT: {self.verdict.upper()}"
        if self.rank is not None:
            head += f"  (rank {self.rank}"
            if self.iteration is not None:
                head += f", iteration {self.iteration}"
            head += ")"
        elif self.iteration is not None:
            head += f"  (iteration {self.iteration})"
        lines.append(head)
        lines.append(f"cause: {self.cause}")
        if self.evidence:
            lines.append("evidence:")
            lines.extend(f"  - {e}" for e in self.evidence)
        if self.memory:
            lines.append("memory trend before failure:")
            for name, tr in sorted(self.memory.items()):
                lines.append(
                    f"  - {name}: {tr['first_bytes'] / 1e9:.3f} GB "
                    f"(iter {tr['first_iteration']}) -> "
                    f"{tr['last_bytes'] / 1e9:.3f} GB "
                    f"(iter {tr['last_iteration']}), {tr['trend']} over "
                    f"{tr['samples']} samples")
        tl = self.timeline
        if tl:
            shown = tl[-max_timeline:]
            lines.append(f"timeline ({len(shown)} of {len(tl)} events, "
                         f"oldest first):")
            for e in shown:
                t = (time.strftime("%H:%M:%S", time.localtime(e["t"]))
                     if e.get("t") else "--:--:--")
                rank = f"rank {e['rank']}" if e.get("rank") is not None \
                    else "gang"
                lines.append(f"  {t} [{rank:>7}] {e['detail']}")
        src = self.sources
        lines.append(
            f"sources: {len(src.get('flights', []))} flight JSONL(s), "
            f"{len(src.get('diags', []))} diagnosis JSON(s), "
            f"{src.get('failures', 0)} supervisor failure record(s), "
            f"{len(src.get('manifests', []))} checkpoint manifest(s)")
        return "\n".join(lines) + "\n"


def analyze(dirs, checkpoint_dir: Optional[str] = None,
            failures=None) -> Postmortem:
    """Gather every artifact under ``dirs`` (a path or list of paths:
    the supervisor diag dir, telemetry dirs, ...), plus optional
    checkpoint manifests and a ``GangFailure`` history, and classify the
    failure. Never raises on malformed artifacts — they are skipped (and
    noted in ``sources``); an empty artifact set yields verdict
    ``unknown``."""
    if isinstance(dirs, str):
        dirs = [dirs]
    dirs = [d for d in (dirs or []) if d]
    # a checkpoint dir brings its supervisor_diag + telemetry subdirs
    # along for free (the default artifact layout)
    scan = list(dirs)
    if checkpoint_dir:
        for sub in ("supervisor_diag", "telemetry"):
            p = os.path.join(checkpoint_dir, sub)
            if os.path.isdir(p) and p not in scan:
                scan.append(p)
    flights = gather_flights(scan)
    diags = gather_diags(scan)
    fails = _normalize_failures(failures)
    manifests = gather_manifests(checkpoint_dir)
    verdict, rank, iteration, cause, evidence = classify(
        flights, diags, fails)
    timeline = build_timeline(flights, diags, fails, manifests)
    fl = next((f for f in reversed(flights) if f.rank == rank), None) \
        if rank is not None else (flights[-1] if flights else None)
    memory = _memory_trend(fl)
    parse_errors = [e for f in flights for e in f.errors]
    sources = {
        "dirs": scan, "checkpoint_dir": checkpoint_dir,
        "flights": [f.path for f in flights],
        "diags": [d.get("_path", "(from supervisor history)")
                  for d in diags],
        "failures": len(fails),
        "manifests": [m["_path"] for m in manifests],
    }
    if parse_errors:
        sources["parse_errors"] = parse_errors[:20]
    return Postmortem(verdict=verdict, rank=rank, iteration=iteration,
                      cause=cause, evidence=evidence, timeline=timeline,
                      memory=memory, sources=sources,
                      generated_at=time.time())


def write_report(pm: Postmortem, directory: str) -> str:
    """Write the machine JSON + human text reports into ``directory``
    and return the JSON path (what the supervisor embeds in
    ``SupervisorReport.postmortem``)."""
    os.makedirs(directory, exist_ok=True)
    from .utils.atomic_write import atomic_write_text
    json_path = os.path.join(directory, REPORT_JSON)
    atomic_write_text(json_path, json.dumps(pm.to_json(), indent=1,
                                            sort_keys=True,
                                            default=str) + "\n")
    atomic_write_text(os.path.join(directory, REPORT_TEXT), pm.render())
    return json_path
