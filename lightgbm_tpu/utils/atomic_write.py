"""Crash-safe file writes: tmp + fsync + rename.

Every model/checkpoint write in the package routes through here so a kill
at ANY byte offset leaves either the old file or the new file — never a
truncated hybrid that parses into a silently shorter model (the failure
mode of the reference's in-place ``ofstream`` saves, gbdt.cpp:277-281).

``os.replace`` is atomic on POSIX (rename(2) within a filesystem) and on
Windows (MoveFileEx with MOVEFILE_REPLACE_EXISTING). The directory fsync
after the rename makes the new directory entry itself durable — without
it a power loss can roll back the rename even though the data blocks were
flushed.
"""

from __future__ import annotations

import contextlib
import os
import tempfile


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "wb"):
    """Context manager yielding a tmp-file handle that atomically replaces
    ``path`` on clean exit (flush + fsync + rename + dir-fsync) and is
    discarded on error. For STREAMING writers (np.savez, chunked dumps)
    that must not materialize the whole payload in memory first."""
    path = os.fspath(path)
    dirname = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=f".{os.path.basename(path)}.",
                               suffix=".tmp", dir=dirname)
    try:
        with os.fdopen(fd, mode) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(dirname)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp file in the same
    directory -> flush -> fsync -> rename -> fsync dir)."""
    with atomic_open(path, "wb") as fh:
        fh.write(data)


def atomic_write_text(path: str, text: str, encoding: str = "utf-8") -> None:
    """Text-mode wrapper over :func:`atomic_write_bytes`."""
    atomic_write_bytes(path, text.encode(encoding))


def _fsync_dir(dirname: str) -> None:
    """Durably record a rename in its directory (best-effort: some
    platforms/filesystems refuse O_RDONLY opens of directories)."""
    try:
        dfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)
