"""Training-phase profiling: named timer scopes + aggregated table.

The TPU analog of the reference's ``Common::Timer`` / ``FunctionTimer`` RAII
scopes around every training phase and the ``global_timer`` table printed at
exit under ``USE_TIMETAG`` (reference: include/LightGBM/utils/common.h:953-1037,
src/boosting/gbdt.cpp:20). Here each scope also opens a
``jax.profiler.TraceAnnotation`` so the phases show up in device traces
captured with ``jax.profiler.trace``.

Enabled via the ``LIGHTGBM_TPU_TIMETAG`` env var or
``profiling.enable()``. When enabled, scope exit BLOCKS on the values passed
to ``sync`` (host wall time of an async dispatch is meaningless otherwise) —
like USE_TIMETAG, profiling adds overhead.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

_enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
# One lock over every aggregate table below. The scopes/counters used to
# be bare defaultdict read-modify-writes, which was fine while only the
# training thread touched them — but the serve dispatcher thread, the
# watchdog thread and the flight recorder all read/update these now, and
# a racing `_acc[k] += v` can lose an update (the read and the store are
# separate bytecodes). RLock because table()/scopes() may be called from
# a flush that already holds it via the recorder.
_lock = threading.RLock()
_acc: Dict[str, float] = defaultdict(float)
_cnt: Dict[str, int] = defaultdict(int)
# named value counters (work counts rather than wall time): the analog of
# the reference's global_timer also carrying histogram-construction counts;
# used for the compaction telemetry (rows streamed per histogram pass)
_counters: Dict[str, float] = defaultdict(float)
_counter_cnt: Dict[str, int] = defaultdict(int)


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Clear the timer scopes, work counters and gauges.

    Deliberately does NOT touch the dispatch/transfer counters
    (``_disp``): those are MONOTONIC by contract — concurrent readers
    scope their measurements by diffing two ``dispatch_stats()``
    snapshots, and a reset between their snapshots would corrupt every
    in-flight delta. Tests that need a clean origin use
    :func:`reset_dispatch` (nothing else may)."""
    with _lock:
        _acc.clear()
        _cnt.clear()
        _counters.clear()
        _counter_cnt.clear()
        _gauges.clear()
        _mem_marks.clear()


def counter(name: str, value: float) -> None:
    """Accumulate a named work counter (e.g. ``hist_rows_streamed``).
    Cheap no-op when profiling is disabled; callers should avoid forcing a
    device sync just to record one (fetch an already-synced value)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] += float(value)
        _counter_cnt[name] += 1


def counters() -> Dict[str, float]:
    """Accumulated named counters (empty when profiling is disabled)."""
    with _lock:
        return dict(_counters)


def scopes() -> Dict[str, Dict[str, float]]:
    """Accumulated timer scopes as data: ``{name: {"total_s", "calls",
    "mean_ms"}}`` — what ``table()`` prints, machine-readable (bench.py's
    phase sub-scope probe and the flight recorder's per-iteration phase
    deltas both read hist_pass / split_search / apply_split out of
    this)."""
    with _lock:
        return {name: {"total_s": _acc[name], "calls": _cnt[name],
                       "mean_ms": 1e3 * _acc[name] / max(_cnt[name], 1)}
                for name in _acc}


# Health gauges: last-value-wins instruments (heartbeat age, supervisor
# restart count, per-rank last iteration) — unlike the timers/counters
# these are ALWAYS on (a restart count that only records under TIMETAG
# would be useless for postmortems) and cost one dict store.
_gauges: Dict[str, float] = {}


def set_gauge(name: str, value: float) -> None:
    """Record the current value of a named health gauge."""
    with _lock:
        _gauges[name] = float(value)


def inc_gauge(name: str, delta: float = 1.0) -> float:
    """Increment a counting gauge (serve shed/timeout counts) and return
    the new value. Runs under the module lock, so racing increments from
    serve caller threads no longer lose counts (the authoritative counts
    still live on the ServeFrontend, behind its own lock — these gauges
    mirror them into health snapshots)."""
    with _lock:
        v = _gauges.get(name, 0.0) + float(delta)
        _gauges[name] = v
        return v


def gauges() -> Dict[str, float]:
    """Current gauge values (supervisor restarts, heartbeat ages, ...)."""
    with _lock:
        return dict(_gauges)


def drop_gauges(prefix: str) -> None:
    """Remove every gauge whose name starts with ``prefix``. Gauges are
    last-value-wins and process-global, so a measurement family scoped
    to an EVENT (e.g. the ``construct_*`` gauges of one dataset
    construction) must be dropped when the next event starts — otherwise
    consumers (the flight-recorder header, ``telemetry
    .construct_snapshot``) attribute a previous event's values to the
    current one."""
    with _lock:
        for k in [k for k in _gauges if k.startswith(prefix)]:
            del _gauges[k]


# --------------------------------------------------------------- memory
# Host-side memory sampling: the device allocator's view (HBM bytes in
# use / peak, via ``Device.memory_stats()`` — a local runtime query, NOT
# a dispatch) and this process's resident set (``/proc/self/status``).
# Every reader is None-tolerant BY CONTRACT: the CPU backend returns no
# memory_stats, containers may lack /proc — a missing source records
# null, never a crash, and never disables the telemetry that carries it.

_mem_device = None              # cached default device (resolved lazily)
_mem_device_ok: Optional[bool] = None   # None = never probed
# per-scope HBM high-water marks, sampled at TIMETAG scope exits (the
# scope already synced, so the allocator state reflects the phase's work)
_mem_marks: Dict[str, int] = {}


def device_memory() -> Optional[Dict[str, int]]:
    """One sample of the default device's allocator stats:
    ``{"bytes_in_use", "peak_bytes_in_use"}`` (whichever keys the
    backend exposes). None on backends without ``memory_stats()`` (CPU
    returns None) — the failed probe is cached so the per-iteration
    caller pays one attribute check, not a rebuild per record."""
    global _mem_device, _mem_device_ok
    if _mem_device_ok is False:
        return None
    try:
        if _mem_device is None:
            import jax
            _mem_device = jax.local_devices()[0]
        stats = _mem_device.memory_stats()
    except Exception:
        _mem_device_ok = False
        return None
    if not stats:
        _mem_device_ok = False
        return None
    _mem_device_ok = True
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use"):
        if key in stats:
            try:
                out[key] = int(stats[key])
            except (TypeError, ValueError):
                pass
    return out or None


def _proc_status_kb(field: str) -> Optional[int]:
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith(field + ":"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return None


def host_rss_bytes() -> Optional[int]:
    """This process's current resident set size (bytes), or None where
    /proc is unavailable."""
    kb = _proc_status_kb("VmRSS")
    return kb * 1024 if kb is not None else None


def host_rss_peak_bytes() -> Optional[int]:
    """This process's peak resident set size (VmHWM, bytes) — the
    process-lifetime host-memory watermark bench.py reports."""
    kb = _proc_status_kb("VmHWM")
    return kb * 1024 if kb is not None else None


def sample_memory() -> Dict[str, Optional[int]]:
    """The memory snapshot the flight recorder records per iteration and
    the OOM ladder attaches to every degradation event: device HBM in
    use / peak plus host RSS, each field null when its source is
    unavailable (CPU backend, no /proc). One cached-device call + one
    /proc read — no dispatch, no device sync."""
    dev = device_memory()
    return {
        "hbm_bytes_in_use": dev.get("bytes_in_use") if dev else None,
        "hbm_peak_bytes": dev.get("peak_bytes_in_use") if dev else None,
        "host_rss_bytes": host_rss_bytes(),
    }


def _mark_scope_memory(name: str) -> None:
    """Record a TIMETAG scope's HBM high-water mark: sampled at scope
    exit (after the sync fetch, so the allocator reflects the phase's
    buffers). No-op on backends without memory_stats."""
    dev = device_memory()
    if not dev:
        return
    cur = dev.get("peak_bytes_in_use", dev.get("bytes_in_use"))
    if cur is None:
        return
    with _lock:
        if cur > _mem_marks.get(name, -1):
            _mem_marks[name] = cur


def memory_watermarks() -> Dict[str, int]:
    """Per-phase HBM high-water marks (scope name -> peak bytes seen at
    that scope's exits), accumulated only under TIMETAG measurement mode
    — empty on CPU and when profiling is off. Cleared by :func:`reset`
    with the scopes they annotate."""
    with _lock:
        return dict(_mem_marks)


def _sync_fetch(value) -> None:
    """Block on ``value`` (an array or pytree) and fetch one scalar of it
    — the scope-exit barrier both ``timer`` and ``timer_sync`` use so a
    measured scope covers the device work dispatched inside it. A host
    fetch is the only reliable barrier through some TPU tunnels, hence
    the scalar read on top of block_until_ready. Best-effort: a failed
    fetch must not fail the scope."""
    if value is None:
        return
    import jax
    try:
        jax.block_until_ready(value)
        leaves = jax.tree_util.tree_leaves(value)
        if leaves:
            _ = float(leaves[0].ravel()[0])
    except Exception:
        pass


@contextmanager
def timer(name: str, sync=None) -> Iterator[None]:
    """Named scope. ``sync``: optional array (or pytree) whose value is
    fetched at scope exit so the measured time covers the device work
    dispatched inside the scope."""
    if not _enabled:
        yield
        return
    import jax
    with jax.profiler.TraceAnnotation(name):
        t0 = time.time()
        try:
            yield
        finally:
            _sync_fetch(sync)
            with _lock:
                _acc[name] += time.time() - t0
                _cnt[name] += 1
            # per-phase HBM watermark (measurement mode only — the scope
            # just synced, so the sample attributes to this phase)
            _mark_scope_memory(name)


class timer_sync:
    """Like ``timer`` but the sync value is produced inside the scope:
    ``with timer_sync("x") as t: ...; t.sync(arr)``."""

    def __init__(self, name: str):
        self.name = name
        self._sync = None

    def sync(self, value) -> None:
        self._sync = value

    def __enter__(self):
        self._cm = timer(self.name, None)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        # the fetch happens BEFORE the inner timer closes, so the scope's
        # recorded wall time covers the synced device work
        if _enabled:
            _sync_fetch(self._sync)
        return self._cm.__exit__(*exc)


# ------------------------------------------------- dispatch / host-sync
# Always-on (TIMETAG-independent) counters for compiled-program dispatches
# and explicit host<->device transfers — the telemetry behind bench.py's
# ``dispatches_per_iter`` / ``host_bytes_per_iter`` JSON fields and the
# fused-iteration regression tests. Each dispatch and each device_get is a
# transport round trip through a TPU tunnel (~75-93 ms RTT observed), so
# the per-iteration counts ARE the non-histogram overhead budget.
#
# Installed by hooking the funnels every dispatch/transfer goes through:
#   - ``pxla.ExecuteReplicated.__call__``: every compiled-program execution
#     (jitted calls AND eager op dispatches both end here);
#   - ``jax.device_get``: explicit device->host fetches (the tree-mirror
#     and score-cache reads in this codebase all use it);
#   - ``pxla.batched_device_put``: host->device array uploads (bytes are
#     counted only for host-resident inputs; device-to-device moves are
#     not transfers).
# jax's C++ pjit fastpath executes cached programs WITHOUT entering
# Python, so installing the hook also forces every call back through the
# Python dispatch path (``_get_fastpath_data -> None`` + a cache clear).
# That adds a small per-dispatch Python overhead (tens of µs — noise next
# to the ms-scale iterations this instrument measures, but NOT free):
# telemetry is a measurement MODE, installed explicitly by bench.py and
# the regression tests, never by library code.
# The hooks are version-guarded: on a jax without these internals
# ``install_dispatch_hook`` returns False and the counters stay at zero.

_disp: Dict[str, int] = {"dispatches": 0, "device_gets": 0,
                         "d2h_bytes": 0, "h2d_bytes": 0}
_hook_state: Optional[bool] = None   # None = never attempted
_hook_originals: Optional[tuple] = None


def install_dispatch_hook() -> bool:
    """Install the dispatch/transfer counting hooks (idempotent). Returns
    whether the counters are live. ``uninstall_dispatch_hook`` restores
    the originals (tests use it so the fastpath bypass doesn't tax the
    rest of the suite)."""
    global _hook_state, _hook_originals
    if _hook_state is not None:
        return _hook_state
    try:
        import jax
        from jax._src.interpreters import pxla

        orig_call = pxla.ExecuteReplicated.__call__

        def _counting_call(self, *args):
            # locked like the other aggregates: concurrent dispatches
            # (serve threads + training) must not lose increments — the
            # dispatch-budget assertions diff these counters
            with _lock:
                _disp["dispatches"] += 1
            return orig_call(self, *args)

        orig_get = jax.device_get

        def _counting_get(x):
            bytes_ = 0
            try:
                for leaf in jax.tree_util.tree_leaves(x):
                    if isinstance(leaf, jax.Array):
                        bytes_ += int(leaf.nbytes)
            except Exception:
                pass
            with _lock:
                _disp["device_gets"] += 1
                _disp["d2h_bytes"] += bytes_
            return orig_get(x)

        orig_bdp = pxla.batched_device_put

        def _counting_bdp(*args, **kwargs):
            # signature-tolerant passthrough (private jax API): count
            # bytes only when the shard-list operand is recognizable, so
            # signature drift degrades the counter, never the upload
            try:
                xs = kwargs.get("xs", args[2] if len(args) > 2 else ())
                bytes_ = sum(int(getattr(x, "nbytes", 0)) for x in xs
                             if not isinstance(x, jax.Array))
                with _lock:
                    _disp["h2d_bytes"] += bytes_
            except Exception:
                pass
            return orig_bdp(*args, **kwargs)

        # disable the C++ pjit fastpath so cached executions re-enter
        # Python (and thus ExecuteReplicated); clear caches so fastpath
        # entries established before the hook don't bypass it
        from jax._src import pjit as pjit_mod
        if not hasattr(pjit_mod, "_get_fastpath_data"):
            raise AttributeError("no _get_fastpath_data")

        def _no_fastpath(*args, **kwargs):
            return None

        _hook_originals = (orig_call, orig_get, orig_bdp,
                           pjit_mod._get_fastpath_data)
        try:
            pxla.ExecuteReplicated.__call__ = _counting_call
            jax.device_get = _counting_get
            pxla.batched_device_put = _counting_bdp
            pjit_mod._get_fastpath_data = _no_fastpath
            jax.clear_caches()
        except Exception:
            # unwind a partial install: leaving the fastpath bypass (or
            # any hook) behind while reporting "not live" would tax every
            # dispatch for the process lifetime with no way to remove it
            orig = _hook_originals
            pxla.ExecuteReplicated.__call__ = orig[0]
            jax.device_get = orig[1]
            pxla.batched_device_put = orig[2]
            pjit_mod._get_fastpath_data = orig[3]
            _hook_originals = None
            raise
        _hook_state = True
    except Exception:
        _hook_state = False
    return _hook_state


def uninstall_dispatch_hook() -> None:
    """Restore the hooked jax internals (and clear the jit caches so
    entries established WITHOUT fastpath data don't keep paying the
    Python round trip). Counter values are preserved."""
    global _hook_state, _hook_originals
    if not _hook_state or _hook_originals is None:
        return
    import jax
    from jax._src.interpreters import pxla
    from jax._src import pjit as pjit_mod
    orig_call, orig_get, orig_bdp, orig_fp = _hook_originals
    pxla.ExecuteReplicated.__call__ = orig_call
    jax.device_get = orig_get
    pxla.batched_device_put = orig_bdp
    pjit_mod._get_fastpath_data = orig_fp
    jax.clear_caches()
    _hook_state = None
    _hook_originals = None


def dispatch_stats() -> Dict[str, int]:
    """Current cumulative counter values (all zero until
    ``install_dispatch_hook`` succeeds). Monotonic BY CONTRACT — diff two
    snapshots to scope a measurement; ``reset()`` deliberately leaves
    these alone so concurrent readers' deltas never get clobbered. Tests
    that need a clean origin use :func:`reset_dispatch`."""
    with _lock:
        return dict(_disp)


def reset_dispatch() -> None:
    """Zero the dispatch/transfer counters. FOR TESTS ONLY: library and
    measurement code must scope with ``dispatch_stats()`` deltas instead
    (``reset()`` keeps these monotonic by contract) — zeroing while any
    other reader holds a snapshot corrupts that reader's delta."""
    with _lock:
        for k in _disp:
            _disp[k] = 0


def dispatch_delta(before: Dict[str, int],
                   after: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """Counter deltas since a ``dispatch_stats()`` snapshot."""
    if after is None:
        after = dispatch_stats()
    return {k: after[k] - before.get(k, 0) for k in after}


@contextmanager
def dispatch_scope() -> Iterator[Dict[str, int]]:
    """Scoped dispatch/transfer deltas: ``with dispatch_scope() as d:
    ...`` — after the block ``d`` holds the counter deltas for the work
    dispatched inside it (all zero unless ``install_dispatch_hook`` is
    live). The one-liner bench.py and the predict-engine regression
    tests both wrap their measured region in."""
    before = dispatch_stats()
    d: Dict[str, int] = {}
    try:
        yield d
    finally:
        d.update(dispatch_delta(before))


def table() -> str:
    """Aggregated per-scope wall-time table (reference: the USE_TIMETAG
    summary printed by ~Timer, common.h:970-990), followed by the named
    work counters."""
    with _lock:
        return _table_locked()


def _table_locked() -> str:
    if not _acc and not _counters:
        return "(no timer scopes recorded)"
    lines = []
    if _acc:
        width = max(len(k) for k in _acc)
        lines.append(f"{'scope'.ljust(width)}  {'calls':>7}  "
                     f"{'total s':>10}  {'mean ms':>10}")
        for name in sorted(_acc, key=lambda k: -_acc[k]):
            n = _cnt[name]
            lines.append(f"{name.ljust(width)}  {n:>7}  "
                         f"{_acc[name]:>10.3f}  "
                         f"{1e3 * _acc[name] / max(n, 1):>10.2f}")
    if _counters:
        width = max(len(k) for k in _counters)
        lines.append(f"{'counter'.ljust(width)}  {'calls':>7}  "
                     f"{'total':>14}  {'mean':>14}")
        for name in sorted(_counters, key=lambda k: -_counters[k]):
            n = _counter_cnt[name]
            lines.append(f"{name.ljust(width)}  {n:>7}  "
                         f"{_counters[name]:>14.0f}  "
                         f"{_counters[name] / max(n, 1):>14.1f}")
    return "\n".join(lines)


def print_table() -> None:
    from . import log
    for line in table().splitlines():
        log.info(line)
