"""Training-phase profiling: named timer scopes + aggregated table.

The TPU analog of the reference's ``Common::Timer`` / ``FunctionTimer`` RAII
scopes around every training phase and the ``global_timer`` table printed at
exit under ``USE_TIMETAG`` (reference: include/LightGBM/utils/common.h:953-1037,
src/boosting/gbdt.cpp:20). Here each scope also opens a
``jax.profiler.TraceAnnotation`` so the phases show up in device traces
captured with ``jax.profiler.trace``.

Enabled via the ``LIGHTGBM_TPU_TIMETAG`` env var or
``profiling.enable()``. When enabled, scope exit BLOCKS on the values passed
to ``sync`` (host wall time of an async dispatch is meaningless otherwise) —
like USE_TIMETAG, profiling adds overhead.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

_enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
_acc: Dict[str, float] = defaultdict(float)
_cnt: Dict[str, int] = defaultdict(int)
# named value counters (work counts rather than wall time): the analog of
# the reference's global_timer also carrying histogram-construction counts;
# used for the compaction telemetry (rows streamed per histogram pass)
_counters: Dict[str, float] = defaultdict(float)
_counter_cnt: Dict[str, int] = defaultdict(int)


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    _acc.clear()
    _cnt.clear()
    _counters.clear()
    _counter_cnt.clear()


def counter(name: str, value: float) -> None:
    """Accumulate a named work counter (e.g. ``hist_rows_streamed``).
    Cheap no-op when profiling is disabled; callers should avoid forcing a
    device sync just to record one (fetch an already-synced value)."""
    if not _enabled:
        return
    _counters[name] += float(value)
    _counter_cnt[name] += 1


def counters() -> Dict[str, float]:
    """Accumulated named counters (empty when profiling is disabled)."""
    return dict(_counters)


@contextmanager
def timer(name: str, sync=None) -> Iterator[None]:
    """Named scope. ``sync``: optional array (or pytree) whose value is
    fetched at scope exit so the measured time covers the device work
    dispatched inside the scope."""
    if not _enabled:
        yield
        return
    import jax
    with jax.profiler.TraceAnnotation(name):
        t0 = time.time()
        try:
            yield
        finally:
            if sync is not None:
                try:
                    jax.block_until_ready(sync)
                    # a host fetch is the only reliable barrier through some
                    # TPU tunnels; fetch one scalar
                    leaves = jax.tree_util.tree_leaves(sync)
                    if leaves:
                        _ = float(leaves[0].ravel()[0])
                except Exception:
                    pass
            _acc[name] += time.time() - t0
            _cnt[name] += 1


class timer_sync:
    """Like ``timer`` but the sync value is produced inside the scope:
    ``with timer_sync("x") as t: ...; t.sync(arr)``."""

    def __init__(self, name: str):
        self.name = name
        self._sync = None

    def sync(self, value) -> None:
        self._sync = value

    def __enter__(self):
        self._cm = timer(self.name, None)
        self._cm.__enter__()
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        if _enabled and self._sync is not None:
            import jax
            try:
                jax.block_until_ready(self._sync)
                leaves = jax.tree_util.tree_leaves(self._sync)
                if leaves:
                    _ = float(leaves[0].ravel()[0])
            except Exception:
                pass
        return self._cm.__exit__(*exc)


def table() -> str:
    """Aggregated per-scope wall-time table (reference: the USE_TIMETAG
    summary printed by ~Timer, common.h:970-990), followed by the named
    work counters."""
    if not _acc and not _counters:
        return "(no timer scopes recorded)"
    lines = []
    if _acc:
        width = max(len(k) for k in _acc)
        lines.append(f"{'scope'.ljust(width)}  {'calls':>7}  "
                     f"{'total s':>10}  {'mean ms':>10}")
        for name in sorted(_acc, key=lambda k: -_acc[k]):
            n = _cnt[name]
            lines.append(f"{name.ljust(width)}  {n:>7}  "
                         f"{_acc[name]:>10.3f}  "
                         f"{1e3 * _acc[name] / max(n, 1):>10.2f}")
    if _counters:
        width = max(len(k) for k in _counters)
        lines.append(f"{'counter'.ljust(width)}  {'calls':>7}  "
                     f"{'total':>14}  {'mean':>14}")
        for name in sorted(_counters, key=lambda k: -_counters[k]):
            n = _counter_cnt[name]
            lines.append(f"{name.ljust(width)}  {n:>7}  "
                         f"{_counters[name]:>14.0f}  "
                         f"{_counters[name] / max(n, 1):>14.1f}")
    return "\n".join(lines)


def print_table() -> None:
    from . import log
    for line in table().splitlines():
        log.info(line)
