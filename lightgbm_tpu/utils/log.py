"""Logging for lightgbm_tpu.

Mirrors the reference's ``Log::Debug/Info/Warning/Fatal`` with verbosity levels
(reference: include/LightGBM/utils/log.h) and the Python-side logger redirection
hook ``register_logger`` (reference: python-package/lightgbm/basic.py:32-79).
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

_logger: Optional[logging.Logger] = None
_verbosity: int = 1  # matches Config.verbosity default (reference: config.h "verbosity = 1")


class LightGBMError(Exception):
    """Error raised by the framework (analog of Log::Fatal's std::runtime_error)."""


def register_logger(logger: logging.Logger) -> None:
    """Redirect all framework log output into a user-supplied ``logging.Logger``."""
    if not isinstance(logger, logging.Logger):
        raise TypeError("logger should be an instance of logging.Logger")
    global _logger
    _logger = logger


def set_verbosity(verbosity: int) -> None:
    global _verbosity
    _verbosity = verbosity


def _emit(level: int, msg: str) -> None:
    if _logger is not None:
        _logger.log(level, msg)
    else:
        print(msg, file=sys.stderr)


def debug(msg: str) -> None:
    if _verbosity >= 2:
        _emit(logging.DEBUG, f"[LightGBM-TPU] [Debug] {msg}")


def info(msg: str) -> None:
    if _verbosity >= 1:
        _emit(logging.INFO, f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    if _verbosity >= 0:
        _emit(logging.WARNING, f"[LightGBM-TPU] [Warning] {msg}")


def fatal(msg: str) -> None:
    raise LightGBMError(msg)
