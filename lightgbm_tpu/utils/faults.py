"""Fault-injection harness for resilience testing.

Deterministic, opt-in failure points threaded through the training loop so
the fault-tolerance suite (tests/test_fault_tolerance.py) and the gang
supervisor suite (tests/test_supervisor.py) can exercise the
checkpoint/resume, watchdog and gang-restart machinery against REAL failure
shapes — a hard kill mid-run (preemptible TPU fleets), a rank that hangs
and stalls every collective, a writer killed mid-checkpoint, a checkpoint
truncated/corrupted on disk, and NaN gradients poisoning histograms —
instead of only happy paths.

Faults are driven by params (``fault_kill_at_iter`` etc. on Config) or
environment variables (which override params, so a test can arm a fault in
a child process without touching its config):

  LGBM_TPU_FAULT_KILL_AT_ITER=k       hard-exit (os._exit(137), no cleanup,
                                      like SIGKILL) at the START of 0-based
                                      boosting iteration k
  LGBM_TPU_FAULT_HANG_AT_ITER=k       hang (interruptible sleep loop,
                                      forever) at the start of iteration k
  LGBM_TPU_FAULT_KILL_RANK_AT_ITER=r:k   kill ONLY process rank r at
                                      iteration k (multi-process gangs)
  LGBM_TPU_FAULT_HANG_RANK_AT_ITER=r:k   hang ONLY process rank r at
                                      iteration k
  LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE=k hard-exit in the MIDDLE of the
                                      checkpoint write for iteration k
                                      (payload files written, manifest not)
  LGBM_TPU_FAULT_NAN_GRAD_AT_ITER=k   overwrite the first
                                      LGBM_TPU_FAULT_NAN_GRAD_COUNT (default
                                      8) gradient values with NaN at
                                      iteration k
  LGBM_TPU_FAULT_CORRUPT_CHECKPOINT=1 flip bytes in every checkpoint's
                                      model text right after it is written
                                      (simulates on-disk corruption)
  LGBM_TPU_FAULT_KILL_IN_SHARD_WRITE=r:k  hard-exit rank r between writing
                                      its score-cache shard and the shard-
                                      metadata exchange of the SHARDED
                                      checkpoint write for iteration k
                                      (pre-partitioned gangs; the stale
                                      ckpt_N.tmp must stay harmless)
  LGBM_TPU_FAULT_CORRUPT_SHARD=r      flip bytes in rank r's shard file of
                                      every sharded checkpoint right after
                                      publication (manifest stays intact,
                                      so only checksum validation catches
                                      it)
  LGBM_TPU_FAULT_SPAWN_FAIL_RANK=r    make spawned child rank r exit with
                                      SPAWN_FAIL_EXIT_CODE (96) before any
                                      bootstrap — the "machine cannot
                                      start" shape the supervisor answers
                                      with a gang SHRINK (env-driven only:
                                      it fires before a config exists)

The rank-targeted forms resolve the process rank lazily through
``jax.process_index()`` so the plan can be built before distributed init.
With no fault armed the plan is ``None`` and every hook is a single
attribute check — zero cost on the training path.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass
from typing import Optional, Tuple

_KILL_EXIT_CODE = 137   # 128 + SIGKILL: what a preemption/oom kill reports


@dataclass
class FaultPlan:
    kill_at_iter: int = -1
    hang_at_iter: int = -1
    kill_rank_at_iter: Optional[Tuple[int, int]] = None   # (rank, iter)
    hang_rank_at_iter: Optional[Tuple[int, int]] = None   # (rank, iter)
    kill_in_ckpt_write: int = -1
    kill_in_shard_write: Optional[Tuple[int, int]] = None  # (rank, iter)
    corrupt_shard: int = -1                               # rank
    nan_grad_at_iter: int = -1
    nan_grad_count: int = 8
    corrupt_checkpoint: bool = False

    @property
    def wants_nan_grad(self) -> bool:
        return self.nan_grad_at_iter >= 0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    try:
        return int(v) if v != "" else default
    except ValueError:
        return default


def _env_rank_iter(name: str,
                   default: str = "") -> Optional[Tuple[int, int]]:
    """Parse an "r:k" rank-targeted fault env var (falling back to the
    config-param twin's string value); None when unset or malformed (a
    malformed value must not silently kill rank 0)."""
    v = os.environ.get(name, "") or str(default or "")
    if not v:
        return None
    try:
        r, _, k = v.partition(":")
        return (int(r), int(k))
    except ValueError:
        sys.stderr.write(f"[faults] ignoring malformed {name}={v!r} "
                         f"(want rank:iter)\n")
        return None


def plan_from(config=None) -> Optional[FaultPlan]:
    """Build the active fault plan from config fields overridden by the
    LGBM_TPU_FAULT_* environment; None when nothing is armed."""
    get = (lambda k, d: getattr(config, k, d)) if config is not None \
        else (lambda k, d: d)
    plan = FaultPlan(
        kill_at_iter=_env_int("LGBM_TPU_FAULT_KILL_AT_ITER",
                              int(get("fault_kill_at_iter", -1))),
        hang_at_iter=_env_int("LGBM_TPU_FAULT_HANG_AT_ITER",
                              int(get("fault_hang_at_iter", -1))),
        kill_rank_at_iter=_env_rank_iter(
            "LGBM_TPU_FAULT_KILL_RANK_AT_ITER",
            get("fault_kill_rank_at_iter", "")),
        hang_rank_at_iter=_env_rank_iter(
            "LGBM_TPU_FAULT_HANG_RANK_AT_ITER",
            get("fault_hang_rank_at_iter", "")),
        kill_in_ckpt_write=_env_int("LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE",
                                    int(get("fault_kill_in_ckpt_write", -1))),
        kill_in_shard_write=_env_rank_iter(
            "LGBM_TPU_FAULT_KILL_IN_SHARD_WRITE",
            get("fault_kill_in_shard_write", "")),
        corrupt_shard=_env_int("LGBM_TPU_FAULT_CORRUPT_SHARD",
                               int(get("fault_corrupt_shard", -1))),
        nan_grad_at_iter=_env_int("LGBM_TPU_FAULT_NAN_GRAD_AT_ITER",
                                  int(get("fault_nan_grad_at_iter", -1))),
        nan_grad_count=_env_int("LGBM_TPU_FAULT_NAN_GRAD_COUNT", 8),
        corrupt_checkpoint=(
            # env, when set, OVERRIDES the param (in both directions, like
            # the integer faults): "1" arms, anything else disarms
            os.environ["LGBM_TPU_FAULT_CORRUPT_CHECKPOINT"] == "1"
            if "LGBM_TPU_FAULT_CORRUPT_CHECKPOINT" in os.environ
            else bool(get("fault_corrupt_checkpoint", False))),
    )
    if (plan.kill_at_iter < 0 and plan.hang_at_iter < 0
            and plan.kill_rank_at_iter is None
            and plan.hang_rank_at_iter is None
            and plan.kill_in_ckpt_write < 0
            and plan.kill_in_shard_write is None
            and plan.corrupt_shard < 0
            and plan.nan_grad_at_iter < 0
            and not plan.corrupt_checkpoint):
        return None
    return plan


def _process_rank() -> int:
    import jax
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def _hard_exit(context: str) -> None:
    """``os._exit`` skips atexit/finally so nothing gets the chance to
    'finish' a write (the SIGKILL shape a preempted worker actually sees)."""
    sys.stderr.write(f"[faults] killing process {context}\n")
    sys.stderr.flush()
    os._exit(_KILL_EXIT_CODE)


def maybe_kill(plan: Optional[FaultPlan], iteration: int) -> None:
    """Hard-exit at the armed iteration (optionally rank-targeted)."""
    if plan is None:
        return
    if plan.kill_at_iter == iteration:
        _hard_exit(f"at iteration {iteration}")
    if plan.kill_rank_at_iter is not None \
            and plan.kill_rank_at_iter[1] == iteration \
            and plan.kill_rank_at_iter[0] == _process_rank():
        _hard_exit(f"(rank {plan.kill_rank_at_iter[0]}) at iteration "
                   f"{iteration}")


def maybe_hang(plan: Optional[FaultPlan], iteration: int) -> None:
    """Hang forever at the armed iteration (optionally rank-targeted) in an
    INTERRUPTIBLE short-sleep loop: the loop re-enters Python bytecode
    every tick, so the watchdog's asynchronous DistributedTimeoutError can
    land, and a supervisor SIGTERM still kills the process."""
    if plan is None:
        return
    hang = plan.hang_at_iter == iteration
    if not hang and plan.hang_rank_at_iter is not None \
            and plan.hang_rank_at_iter[1] == iteration:
        hang = plan.hang_rank_at_iter[0] == _process_rank()
    if not hang:
        return
    sys.stderr.write(f"[faults] hanging rank {_process_rank()} at "
                     f"iteration {iteration}\n")
    sys.stderr.flush()
    while True:
        time.sleep(0.05)


def maybe_kill_in_ckpt_write(plan: Optional[FaultPlan],
                             iteration: int) -> None:
    """Kill the checkpoint WRITER between the payload writes and the
    manifest write — the mid-write crash the manifest-last protocol and the
    .tmp staging directory must make harmless."""
    if plan is not None and plan.kill_in_ckpt_write == iteration:
        _hard_exit(f"inside checkpoint write for iteration {iteration}")


def maybe_nan_grad(plan: Optional[FaultPlan], iteration: int, g, h):
    """Overwrite the first ``nan_grad_count`` gradient entries with NaN at
    the armed iteration (returns possibly-modified (g, h))."""
    if plan is None or plan.nan_grad_at_iter != iteration:
        return g, h
    import jax.numpy as jnp
    n = min(plan.nan_grad_count, g.shape[0])
    flat = g.reshape(-1)
    flat = flat.at[:n].set(jnp.nan)
    return flat.reshape(g.shape), h


def corrupt_file(path: str, offset: Optional[int] = None,
                 nbytes: int = 16, truncate: bool = False) -> None:
    """Damage a file in place: XOR-flip ``nbytes`` at ``offset`` (middle of
    the file by default), or truncate it there. Shared by the
    corrupt-checkpoint injection point and the tests."""
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    offset = max(0, min(offset, max(size - 1, 0)))
    if truncate:
        with open(path, "r+b") as fh:
            fh.truncate(offset)
        return
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(nbytes)
        fh.seek(offset)
        fh.write(bytes(b ^ 0xA5 for b in chunk))


def maybe_corrupt_checkpoint(plan: Optional[FaultPlan], path: str) -> None:
    """Corruption injection point the checkpoint writer calls after a
    successful save (damages the payload but leaves the manifest intact,
    so only checksum validation can catch it)."""
    if plan is not None and plan.corrupt_checkpoint:
        corrupt_file(path)


def maybe_kill_in_shard_write(plan: Optional[FaultPlan],
                              iteration: int) -> None:
    """Kill rank r between writing its score-cache shard into the staging
    directory and the shard-metadata exchange — mid-protocol death of ONE
    participant in the sharded checkpoint write. The manifest never lands,
    so the stale ``ckpt_N.tmp`` must be ignored by readers and reclaimed
    by the next write."""
    if plan is None or plan.kill_in_shard_write is None:
        return
    if plan.kill_in_shard_write[1] == iteration \
            and plan.kill_in_shard_write[0] == _process_rank():
        _hard_exit(f"(rank {plan.kill_in_shard_write[0]}) inside sharded "
                   f"checkpoint write for iteration {iteration}")


def maybe_corrupt_shard(plan: Optional[FaultPlan], path: str,
                        rank: int) -> None:
    """Corrupt ONE rank's published shard file (manifest intact): only the
    per-shard sha256 in MANIFEST.json can catch it, and the checkpoint
    must then be treated as invalid by the prune/fallback logic."""
    if plan is not None and plan.corrupt_shard == rank:
        corrupt_file(path)


def maybe_fail_spawn(rank: int) -> None:
    """Spawn-failure injection point, called at the very top of spawned
    children (before jax/distributed bootstrap, so it is env-driven only):
    exits with SPAWN_FAIL_EXIT_CODE so the supervisor classifies the rank
    as permanently lost and shrinks the gang."""
    v = os.environ.get("LGBM_TPU_FAULT_SPAWN_FAIL_RANK", "")
    if not v:
        return
    try:
        target = int(v)
    except ValueError:
        sys.stderr.write(f"[faults] ignoring malformed "
                         f"LGBM_TPU_FAULT_SPAWN_FAIL_RANK={v!r}\n")
        return
    if target == rank:
        from .. import distributed
        sys.stderr.write(f"[faults] failing spawn of rank {rank}\n")
        sys.stderr.flush()
        os._exit(distributed.SPAWN_FAIL_EXIT_CODE)
