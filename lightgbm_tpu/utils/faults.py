"""Fault-injection harness for resilience testing.

Deterministic, opt-in failure points threaded through the training loop so
the fault-tolerance suite (tests/test_fault_tolerance.py) can exercise the
checkpoint/resume and numerics guard-rail machinery against REAL failure
shapes — a hard kill mid-run (preemptible TPU fleets), a checkpoint
truncated/corrupted on disk, and NaN gradients poisoning histograms —
instead of only happy paths.

Faults are driven by params (``fault_kill_at_iter`` etc. on Config) or
environment variables (which override params, so a test can arm a fault in
a child process without touching its config):

  LGBM_TPU_FAULT_KILL_AT_ITER=k       hard-exit (os._exit(137), no cleanup,
                                      like SIGKILL) at the START of 0-based
                                      boosting iteration k
  LGBM_TPU_FAULT_NAN_GRAD_AT_ITER=k   overwrite the first
                                      LGBM_TPU_FAULT_NAN_GRAD_COUNT (default
                                      8) gradient values with NaN at
                                      iteration k
  LGBM_TPU_FAULT_CORRUPT_CHECKPOINT=1 flip bytes in every checkpoint's
                                      model text right after it is written
                                      (simulates on-disk corruption)

With no fault armed the plan is ``None`` and every hook is a single
attribute check — zero cost on the training path.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Optional

_KILL_EXIT_CODE = 137   # 128 + SIGKILL: what a preemption/oom kill reports


@dataclass
class FaultPlan:
    kill_at_iter: int = -1
    nan_grad_at_iter: int = -1
    nan_grad_count: int = 8
    corrupt_checkpoint: bool = False

    @property
    def wants_nan_grad(self) -> bool:
        return self.nan_grad_at_iter >= 0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    try:
        return int(v) if v != "" else default
    except ValueError:
        return default


def plan_from(config=None) -> Optional[FaultPlan]:
    """Build the active fault plan from config fields overridden by the
    LGBM_TPU_FAULT_* environment; None when nothing is armed."""
    get = (lambda k, d: getattr(config, k, d)) if config is not None \
        else (lambda k, d: d)
    plan = FaultPlan(
        kill_at_iter=_env_int("LGBM_TPU_FAULT_KILL_AT_ITER",
                              int(get("fault_kill_at_iter", -1))),
        nan_grad_at_iter=_env_int("LGBM_TPU_FAULT_NAN_GRAD_AT_ITER",
                                  int(get("fault_nan_grad_at_iter", -1))),
        nan_grad_count=_env_int("LGBM_TPU_FAULT_NAN_GRAD_COUNT", 8),
        corrupt_checkpoint=(
            # env, when set, OVERRIDES the param (in both directions, like
            # the integer faults): "1" arms, anything else disarms
            os.environ["LGBM_TPU_FAULT_CORRUPT_CHECKPOINT"] == "1"
            if "LGBM_TPU_FAULT_CORRUPT_CHECKPOINT" in os.environ
            else bool(get("fault_corrupt_checkpoint", False))),
    )
    if (plan.kill_at_iter < 0 and plan.nan_grad_at_iter < 0
            and not plan.corrupt_checkpoint):
        return None
    return plan


def maybe_kill(plan: Optional[FaultPlan], iteration: int) -> None:
    """Hard-exit at the armed iteration — ``os._exit`` skips atexit/finally
    so nothing gets the chance to 'finish' a write (the SIGKILL shape a
    preempted worker actually sees)."""
    if plan is not None and plan.kill_at_iter == iteration:
        sys.stderr.write(
            f"[faults] killing process at iteration {iteration}\n")
        sys.stderr.flush()
        os._exit(_KILL_EXIT_CODE)


def maybe_nan_grad(plan: Optional[FaultPlan], iteration: int, g, h):
    """Overwrite the first ``nan_grad_count`` gradient entries with NaN at
    the armed iteration (returns possibly-modified (g, h))."""
    if plan is None or plan.nan_grad_at_iter != iteration:
        return g, h
    import jax.numpy as jnp
    n = min(plan.nan_grad_count, g.shape[0])
    flat = g.reshape(-1)
    flat = flat.at[:n].set(jnp.nan)
    return flat.reshape(g.shape), h


def corrupt_file(path: str, offset: Optional[int] = None,
                 nbytes: int = 16, truncate: bool = False) -> None:
    """Damage a file in place: XOR-flip ``nbytes`` at ``offset`` (middle of
    the file by default), or truncate it there. Shared by the
    corrupt-checkpoint injection point and the tests."""
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    offset = max(0, min(offset, max(size - 1, 0)))
    if truncate:
        with open(path, "r+b") as fh:
            fh.truncate(offset)
        return
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(nbytes)
        fh.seek(offset)
        fh.write(bytes(b ^ 0xA5 for b in chunk))


def maybe_corrupt_checkpoint(plan: Optional[FaultPlan], path: str) -> None:
    """Corruption injection point the checkpoint writer calls after a
    successful save (damages the payload but leaves the manifest intact,
    so only checksum validation can catch it)."""
    if plan is not None and plan.corrupt_checkpoint:
        corrupt_file(path)
