"""Fault-injection harness for resilience testing.

Deterministic, opt-in failure points threaded through the training loop so
the fault-tolerance suite (tests/test_fault_tolerance.py) and the gang
supervisor suite (tests/test_supervisor.py) can exercise the
checkpoint/resume, watchdog and gang-restart machinery against REAL failure
shapes — a hard kill mid-run (preemptible TPU fleets), a rank that hangs
and stalls every collective, a writer killed mid-checkpoint, a checkpoint
truncated/corrupted on disk, and NaN gradients poisoning histograms —
instead of only happy paths.

Faults are driven by params (``fault_kill_at_iter`` etc. on Config) or
environment variables (which override params, so a test can arm a fault in
a child process without touching its config):

  LGBM_TPU_FAULT_KILL_AT_ITER=k       hard-exit (os._exit(137), no cleanup,
                                      like SIGKILL) at the START of 0-based
                                      boosting iteration k
  LGBM_TPU_FAULT_HANG_AT_ITER=k       hang (interruptible sleep loop,
                                      forever) at the start of iteration k
  LGBM_TPU_FAULT_KILL_RANK_AT_ITER=r:k   kill ONLY process rank r at
                                      iteration k (multi-process gangs)
  LGBM_TPU_FAULT_HANG_RANK_AT_ITER=r:k   hang ONLY process rank r at
                                      iteration k
  LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE=k hard-exit in the MIDDLE of the
                                      checkpoint write for iteration k
                                      (payload files written, manifest not)
  LGBM_TPU_FAULT_NAN_GRAD_AT_ITER=k   overwrite the first
                                      LGBM_TPU_FAULT_NAN_GRAD_COUNT (default
                                      8) gradient values with NaN at
                                      iteration k
  LGBM_TPU_FAULT_CORRUPT_CHECKPOINT=1 flip bytes in every checkpoint's
                                      model text right after it is written
                                      (simulates on-disk corruption)
  LGBM_TPU_FAULT_KILL_IN_SHARD_WRITE=r:k  hard-exit rank r between writing
                                      its score-cache shard and the shard-
                                      metadata exchange of the SHARDED
                                      checkpoint write for iteration k
                                      (pre-partitioned gangs; the stale
                                      ckpt_N.tmp must stay harmless)
  LGBM_TPU_FAULT_CORRUPT_SHARD=r      flip bytes in rank r's shard file of
                                      every sharded checkpoint right after
                                      publication (manifest stays intact,
                                      so only checksum validation catches
                                      it)
  LGBM_TPU_FAULT_SPAWN_FAIL_RANK=r    make spawned child rank r exit with
                                      SPAWN_FAIL_EXIT_CODE (96) before any
                                      bootstrap — the "machine cannot
                                      start" shape the supervisor answers
                                      with a gang SHRINK (env-driven only:
                                      it fires before a config exists)
  LGBM_TPU_FAULT_FLIP_SCORE_RANK=r:k  flip ONE bit of rank r's train-score
                                      cache right after iteration k
                                      completes — the silent-corruption
                                      shape (cosmic ray / bad DIMM / kernel
                                      bug) the cross-rank divergence check
                                      (distributed.check_model_integrity)
                                      exists to catch
  LGBM_TPU_FAULT_NAN_HIST_AT_ITER=k   poison one gradient value with NaN
                                      INSIDE the compiled program at
                                      iteration k — unlike NAN_GRAD (which
                                      materializes gradients on host and
                                      so unfuses the iteration), this one
                                      is a traced injection the fused
                                      path's in-program numerics sentinels
                                      must catch
  LGBM_TPU_FAULT_OOM_AT_ITER=k        raise a simulated RESOURCE_EXHAUSTED
                                      from the boosting step at iteration
                                      k, LGBM_TPU_FAULT_OOM_COUNT times
                                      consecutively (default 1) — drives
                                      the OOM degradation ladder
                                      (models/gbdt.py _maybe_degrade_oom)
                                      one rung per raise
  LGBM_TPU_FAULT_SLOW_PREDICT_MS=ms   sleep ``ms`` milliseconds inside
                                      every predict dispatch (the slow-
                                      dispatch shape — tunnel stall, noisy
                                      neighbor — the serving layer's
                                      per-request deadlines and admission
                                      control must answer; serving.py's
                                      deadline/shed tests arm it)
  LGBM_TPU_FAULT_OOM_AT_PREDICT=c     raise a simulated RESOURCE_EXHAUSTED
                                      from the next ``c`` predict
                                      dispatches PROCESS-WIDE (the fired
                                      count persists across the fresh
                                      fault plans each predict call
                                      builds, so the ladder's retry loop
                                      terminates) — drives the serve-side
                                      predict-chunk degradation rung
                                      (models/gbdt.py
                                      _maybe_degrade_predict_oom) without
                                      touching the training rungs

The rank-targeted forms resolve the process rank lazily through
``jax.process_index()`` so the plan can be built before distributed init.
With no fault armed the plan is ``None`` and every hook is a single
attribute check — zero cost on the training path.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

_KILL_EXIT_CODE = 137   # 128 + SIGKILL: what a preemption/oom kill reports


@dataclass
class FaultPlan:
    kill_at_iter: int = -1
    hang_at_iter: int = -1
    kill_rank_at_iter: Optional[Tuple[int, int]] = None   # (rank, iter)
    hang_rank_at_iter: Optional[Tuple[int, int]] = None   # (rank, iter)
    kill_in_ckpt_write: int = -1
    kill_in_shard_write: Optional[Tuple[int, int]] = None  # (rank, iter)
    corrupt_shard: int = -1                               # rank
    nan_grad_at_iter: int = -1
    nan_grad_count: int = 8
    corrupt_checkpoint: bool = False
    flip_score_rank: Optional[Tuple[int, int]] = None     # (rank, iter)
    nan_hist_at_iter: int = -1
    oom_at_iter: int = -1
    oom_count: int = 1            # consecutive simulated OOM raises left
                                  # (mutated by maybe_oom as they fire)

    @property
    def wants_nan_grad(self) -> bool:
        return self.nan_grad_at_iter >= 0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    try:
        return int(v) if v != "" else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    try:
        return float(v) if v != "" else default
    except ValueError:
        return default


def _env_rank_iter(name: str,
                   default: str = "") -> Optional[Tuple[int, int]]:
    """Parse an "r:k" rank-targeted fault env var (falling back to the
    config-param twin's string value); None when unset or malformed (a
    malformed value must not silently kill rank 0)."""
    v = os.environ.get(name, "") or str(default or "")
    if not v:
        return None
    try:
        r, _, k = v.partition(":")
        return (int(r), int(k))
    except ValueError:
        sys.stderr.write(f"[faults] ignoring malformed {name}={v!r} "
                         f"(want rank:iter)\n")
        return None


def plan_from(config=None) -> Optional[FaultPlan]:
    """Build the active fault plan from config fields overridden by the
    LGBM_TPU_FAULT_* environment; None when nothing is armed."""
    get = (lambda k, d: getattr(config, k, d)) if config is not None \
        else (lambda k, d: d)
    plan = FaultPlan(
        kill_at_iter=_env_int("LGBM_TPU_FAULT_KILL_AT_ITER",
                              int(get("fault_kill_at_iter", -1))),
        hang_at_iter=_env_int("LGBM_TPU_FAULT_HANG_AT_ITER",
                              int(get("fault_hang_at_iter", -1))),
        kill_rank_at_iter=_env_rank_iter(
            "LGBM_TPU_FAULT_KILL_RANK_AT_ITER",
            get("fault_kill_rank_at_iter", "")),
        hang_rank_at_iter=_env_rank_iter(
            "LGBM_TPU_FAULT_HANG_RANK_AT_ITER",
            get("fault_hang_rank_at_iter", "")),
        kill_in_ckpt_write=_env_int("LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE",
                                    int(get("fault_kill_in_ckpt_write", -1))),
        kill_in_shard_write=_env_rank_iter(
            "LGBM_TPU_FAULT_KILL_IN_SHARD_WRITE",
            get("fault_kill_in_shard_write", "")),
        corrupt_shard=_env_int("LGBM_TPU_FAULT_CORRUPT_SHARD",
                               int(get("fault_corrupt_shard", -1))),
        nan_grad_at_iter=_env_int("LGBM_TPU_FAULT_NAN_GRAD_AT_ITER",
                                  int(get("fault_nan_grad_at_iter", -1))),
        nan_grad_count=_env_int("LGBM_TPU_FAULT_NAN_GRAD_COUNT", 8),
        flip_score_rank=_env_rank_iter(
            "LGBM_TPU_FAULT_FLIP_SCORE_RANK",
            get("fault_flip_score_rank", "")),
        nan_hist_at_iter=_env_int("LGBM_TPU_FAULT_NAN_HIST_AT_ITER",
                                  int(get("fault_nan_hist_at_iter", -1))),
        oom_at_iter=_env_int("LGBM_TPU_FAULT_OOM_AT_ITER",
                             int(get("fault_oom_at_iter", -1))),
        oom_count=_env_int("LGBM_TPU_FAULT_OOM_COUNT",
                           int(get("fault_oom_count", 1))),
        corrupt_checkpoint=(
            # env, when set, OVERRIDES the param (in both directions, like
            # the integer faults): "1" arms, anything else disarms
            os.environ["LGBM_TPU_FAULT_CORRUPT_CHECKPOINT"] == "1"
            if "LGBM_TPU_FAULT_CORRUPT_CHECKPOINT" in os.environ
            else bool(get("fault_corrupt_checkpoint", False))),
    )
    if (plan.kill_at_iter < 0 and plan.hang_at_iter < 0
            and plan.kill_rank_at_iter is None
            and plan.hang_rank_at_iter is None
            and plan.kill_in_ckpt_write < 0
            and plan.kill_in_shard_write is None
            and plan.corrupt_shard < 0
            and plan.nan_grad_at_iter < 0
            and plan.flip_score_rank is None
            and plan.nan_hist_at_iter < 0
            and plan.oom_at_iter < 0
            and not plan.corrupt_checkpoint):
        return None
    return plan


def _process_rank() -> int:
    from .. import distributed
    return distributed.jax_rank()


def _hard_exit(context: str) -> None:
    """``os._exit`` skips atexit/finally so nothing gets the chance to
    'finish' a write (the SIGKILL shape a preempted worker actually sees).

    One deliberate exception: the flight recorder flushes first. A real
    SIGKILL cannot flush anything — for that shape, durable-dir runs
    rely on the recorder's periodic flush — but the harness kill is the
    TESTABLE stand-in for preemption, and the whole point of the
    post-mortem ring is that a killed gang leaves one; the flush is a
    single atomic file write, so it cannot 'finish' any in-flight
    checkpoint the way skipping atexit is meant to prevent."""
    try:
        from .. import telemetry
        telemetry.flush_recorder(f"fault-kill {context}")
    except Exception:
        pass
    sys.stderr.write(f"[faults] killing process {context}\n")
    sys.stderr.flush()
    os._exit(_KILL_EXIT_CODE)


def maybe_kill(plan: Optional[FaultPlan], iteration: int) -> None:
    """Hard-exit at the armed iteration (optionally rank-targeted)."""
    if plan is None:
        return
    if plan.kill_at_iter == iteration:
        _hard_exit(f"at iteration {iteration}")
    if plan.kill_rank_at_iter is not None \
            and plan.kill_rank_at_iter[1] == iteration \
            and plan.kill_rank_at_iter[0] == _process_rank():
        _hard_exit(f"(rank {plan.kill_rank_at_iter[0]}) at iteration "
                   f"{iteration}")


def maybe_hang(plan: Optional[FaultPlan], iteration: int) -> None:
    """Hang forever at the armed iteration (optionally rank-targeted) in an
    INTERRUPTIBLE short-sleep loop: the loop re-enters Python bytecode
    every tick, so the watchdog's asynchronous DistributedTimeoutError can
    land, and a supervisor SIGTERM still kills the process."""
    if plan is None:
        return
    hang = plan.hang_at_iter == iteration
    if not hang and plan.hang_rank_at_iter is not None \
            and plan.hang_rank_at_iter[1] == iteration:
        hang = plan.hang_rank_at_iter[0] == _process_rank()
    if not hang:
        return
    sys.stderr.write(f"[faults] hanging rank {_process_rank()} at "
                     f"iteration {iteration}\n")
    sys.stderr.flush()
    while True:
        time.sleep(0.05)


def maybe_kill_in_ckpt_write(plan: Optional[FaultPlan],
                             iteration: int) -> None:
    """Kill the checkpoint WRITER between the payload writes and the
    manifest write — the mid-write crash the manifest-last protocol and the
    .tmp staging directory must make harmless."""
    if plan is not None and plan.kill_in_ckpt_write == iteration:
        _hard_exit(f"inside checkpoint write for iteration {iteration}")


def maybe_nan_grad(plan: Optional[FaultPlan], iteration: int, g, h):
    """Overwrite the first ``nan_grad_count`` gradient entries with NaN at
    the armed iteration (returns possibly-modified (g, h))."""
    if plan is None or plan.nan_grad_at_iter != iteration:
        return g, h
    import jax.numpy as jnp
    n = min(plan.nan_grad_count, g.shape[0])
    flat = g.reshape(-1)
    flat = flat.at[:n].set(jnp.nan)
    return flat.reshape(g.shape), h


def corrupt_file(path: str, offset: Optional[int] = None,
                 nbytes: int = 16, truncate: bool = False) -> None:
    """Damage a file in place: XOR-flip ``nbytes`` at ``offset`` (middle of
    the file by default), or truncate it there. Shared by the
    corrupt-checkpoint injection point and the tests."""
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    offset = max(0, min(offset, max(size - 1, 0)))
    if truncate:
        with open(path, "r+b") as fh:
            fh.truncate(offset)
        return
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(nbytes)
        fh.seek(offset)
        fh.write(bytes(b ^ 0xA5 for b in chunk))


def maybe_corrupt_checkpoint(plan: Optional[FaultPlan], path: str) -> None:
    """Corruption injection point the checkpoint writer calls after a
    successful save (damages the payload but leaves the manifest intact,
    so only checksum validation can catch it)."""
    if plan is not None and plan.corrupt_checkpoint:
        corrupt_file(path)


def maybe_kill_in_shard_write(plan: Optional[FaultPlan],
                              iteration: int) -> None:
    """Kill rank r between writing its score-cache shard into the staging
    directory and the shard-metadata exchange — mid-protocol death of ONE
    participant in the sharded checkpoint write. The manifest never lands,
    so the stale ``ckpt_N.tmp`` must be ignored by readers and reclaimed
    by the next write."""
    if plan is None or plan.kill_in_shard_write is None:
        return
    if plan.kill_in_shard_write[1] == iteration \
            and plan.kill_in_shard_write[0] == _process_rank():
        _hard_exit(f"(rank {plan.kill_in_shard_write[0]}) inside sharded "
                   f"checkpoint write for iteration {iteration}")


def maybe_corrupt_shard(plan: Optional[FaultPlan], path: str,
                        rank: int) -> None:
    """Corrupt ONE rank's published shard file (manifest intact): only the
    per-shard sha256 in MANIFEST.json can catch it, and the checkpoint
    must then be treated as invalid by the prune/fallback logic."""
    if plan is not None and plan.corrupt_shard == rank:
        corrupt_file(path)


def maybe_flip_score(plan: Optional[FaultPlan], iteration: int, score):
    """Flip ONE bit (the lowest mantissa bit of element 0) of the armed
    rank's train-score cache after iteration ``iteration`` completes —
    the silent single-bit corruption the cross-rank divergence check must
    attribute to exactly this rank. Returns the corrupted score array, or
    None when the fault is not armed for (this rank, this iteration).
    Involutory: applying it twice restores the original bits (the tests
    use that to verify exactly one bit moved)."""
    if plan is None or plan.flip_score_rank is None:
        return None
    if plan.flip_score_rank[1] != iteration \
            or plan.flip_score_rank[0] != _process_rank():
        return None
    import jax.numpy as jnp
    import numpy as np
    arr = np.array(np.asarray(score, np.float32), copy=True)
    flat = arr.reshape(-1).view(np.uint32)
    flat[0] ^= np.uint32(1)
    sys.stderr.write(f"[faults] flipping one score-cache bit on rank "
                     f"{_process_rank()} after iteration {iteration}\n")
    sys.stderr.flush()
    return jnp.asarray(arr)


def nan_hist_iter(plan: Optional[FaultPlan]) -> int:
    """The iteration armed for the IN-PROGRAM NaN injection (-1 = off).
    The fused step closes over this as a STATIC so the disarmed program is
    byte-identical to a fault-free trace; the armed program compares the
    traced iteration operand against it (models/gbdt.py _fused_step_fn)."""
    return plan.nan_hist_at_iter if plan is not None else -1


def maybe_nan_hist(plan: Optional[FaultPlan], iteration: int, g, h):
    """Host-path twin of the in-program NaN injection: poison ONE gradient
    value at the armed iteration (the unfused spelling of what
    nan_hist_iter injects inside the fused program). Returns (g, h)."""
    if plan is None or plan.nan_hist_at_iter != iteration:
        return g, h
    import jax.numpy as jnp
    flat = g.reshape(-1).at[0].set(jnp.nan)
    return flat.reshape(g.shape), h


class SimulatedResourceExhausted(RuntimeError):
    """Stands in for the backend's RESOURCE_EXHAUSTED XlaRuntimeError so
    the OOM degradation ladder is exercisable on any host. The message
    carries the literal token ``is_resource_exhausted`` matches on."""


def maybe_oom(plan: Optional[FaultPlan], iteration: int) -> None:
    """Raise a simulated RESOURCE_EXHAUSTED from the boosting step at the
    armed iteration, ``oom_count`` consecutive times (the plan's counter
    decrements per raise) — each raise drives the degradation ladder
    down one rung before the step is retried."""
    if plan is None or plan.oom_at_iter != iteration or plan.oom_count <= 0:
        return
    plan.oom_count -= 1
    raise SimulatedResourceExhausted(
        f"RESOURCE_EXHAUSTED: simulated histogram allocation failure at "
        f"iteration {iteration} ({plan.oom_count} more armed)")


def is_resource_exhausted(exc: BaseException) -> bool:
    """Whether an exception is an out-of-device-memory failure: the
    backend's RESOURCE_EXHAUSTED XlaRuntimeError (compile-time VMEM/HBM
    exhaustion and runtime allocation failures both carry the token), or
    the fault harness's simulated stand-in. The classifier the OOM
    degradation ladder gates on — it must never match unrelated errors,
    so the match is on the specific allocator phrasings only."""
    if isinstance(exc, SimulatedResourceExhausted):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "Out of memory" in text
            or "Resource exhausted" in text)


# ------------------------------------------------------------ serve faults
# Serve-side injection points (see lightgbm_tpu/serving.py). Unlike the
# training faults these are re-read on EVERY predict dispatch (a fresh
# tiny plan per call, two env lookups + two attribute reads when
# disarmed), because serve tests arm/disarm them around individual
# requests without rebuilding the booster.

@dataclass
class ServeFaults:
    slow_predict_ms: float = 0.0   # sleep inside every predict dispatch
    oom_predicts: int = 0          # simulated OOMs to raise, process-wide


# predict-OOM raises fired so far in this process: the budget lives HERE
# (module state) rather than on the plan, because a fresh plan is built
# per predict call — a per-plan counter would re-arm on every ladder
# retry and loop the rescue forever. Check-and-increment runs under a
# lock: concurrent serve dispatches must not both pass the budget check
# and burn two ladder rungs for a budget of one.
_predict_oom_fired = 0
_predict_oom_lock = threading.Lock()


def serve_faults(config=None) -> Optional[ServeFaults]:
    """Build the active serve-side fault plan from config fields
    overridden by the LGBM_TPU_FAULT_* environment; None when nothing is
    armed (the common case — kept to two env reads)."""
    get = (lambda k, d: getattr(config, k, d)) if config is not None \
        else (lambda k, d: d)
    slow = _env_float("LGBM_TPU_FAULT_SLOW_PREDICT_MS",
                      float(get("fault_slow_predict_ms", 0.0)))
    ooms = _env_int("LGBM_TPU_FAULT_OOM_AT_PREDICT",
                    int(get("fault_oom_at_predict", 0)))
    if slow <= 0 and ooms <= 0:
        return None
    return ServeFaults(slow_predict_ms=slow, oom_predicts=ooms)


def maybe_slow_predict(sf: Optional[ServeFaults]) -> None:
    """Delay inside the predict dispatch path — forces requests past
    their deadlines and backs the queue up into admission control."""
    if sf is not None and sf.slow_predict_ms > 0:
        time.sleep(sf.slow_predict_ms / 1e3)


def maybe_oom_predict(sf: Optional[ServeFaults]) -> None:
    """Raise a simulated RESOURCE_EXHAUSTED from the predict dispatch
    while the armed budget has raises left (process-wide fired counter,
    see _predict_oom_fired) — each raise drives the predict-chunk
    degradation rung once before the call is retried."""
    global _predict_oom_fired
    if sf is None or sf.oom_predicts <= 0:
        return
    with _predict_oom_lock:
        if _predict_oom_fired >= sf.oom_predicts:
            return
        _predict_oom_fired += 1
        left = sf.oom_predicts - _predict_oom_fired
    raise SimulatedResourceExhausted(
        f"RESOURCE_EXHAUSTED: simulated predict allocation failure "
        f"({left} more armed)")


def reset_predict_oom() -> None:
    """Re-arm the predict-OOM budget (tests call this between scenarios)."""
    global _predict_oom_fired
    _predict_oom_fired = 0


def next_predict_chunk(exc: BaseException, cur: int,
                       hist_oom_fallback: bool = True) -> Optional[int]:
    """Predict-OOM ladder arithmetic, shared by GBDT and LoadedGBDT
    (`_maybe_degrade_predict_oom` in models/gbdt.py and io/model_text.py
    — ONE place owns the start/floor/halving so the two rungs cannot
    drift): the halved chunk to retry with, or None when the rung must
    not fire (gate off, not RESOURCE_EXHAUSTED, or the 16k-row floor is
    already reached — the caller then re-raises)."""
    if not hist_oom_fallback or not is_resource_exhausted(exc):
        return None
    cur = cur or (1 << 22)
    if cur <= (1 << 14):
        return None
    return max(1 << 14, cur // 2)


def maybe_fail_spawn(rank: int) -> None:
    """Spawn-failure injection point, called at the very top of spawned
    children (before jax/distributed bootstrap, so it is env-driven only):
    exits with SPAWN_FAIL_EXIT_CODE so the supervisor classifies the rank
    as permanently lost and shrinks the gang."""
    v = os.environ.get("LGBM_TPU_FAULT_SPAWN_FAIL_RANK", "")
    if not v:
        return
    try:
        target = int(v)
    except ValueError:
        sys.stderr.write(f"[faults] ignoring malformed "
                         f"LGBM_TPU_FAULT_SPAWN_FAIL_RANK={v!r}\n")
        return
    if target == rank:
        from .. import distributed
        sys.stderr.write(f"[faults] failing spawn of rank {rank}\n")
        sys.stderr.flush()
        os._exit(distributed.SPAWN_FAIL_EXIT_CODE)
