"""Utility layer (logging, timers)."""
