"""Exclusive Feature Bundling (EFB).

Host-side greedy bundling of mutually-exclusive sparse features into shared
device columns (reference: src/io/dataset.cpp:100-237 ``FindGroups`` /
``FastFeatureBundling``; NeurIPS'17 LightGBM paper §4). Without it,
wide-sparse data (Allstate 13.2M x 4228) cannot fit a dense ``[N, F]`` bin
matrix.

Semantics carried over:

- conflict budget: ``total_sample_cnt / 10000`` per bundle
  (dataset.cpp:108-109), a feature joins the first bundle where its
  conflicts fit the remaining budget and at most half its non-default rows
  (dataset.cpp:154-158);
- bundles capped at 256 total bins (dataset.cpp:107 max_bin_per_group) so a
  bundle column still fits uint8;
- two greedy passes — original feature order and by non-default count
  descending — keeping whichever yields fewer bundles (dataset.cpp:293-303);
- conflict marks are over rows where the feature is NOT at its
  most-frequent bin (dataset.cpp:76-97 FixSampleIndices).

Bundle column layout (the analog of FeatureGroup::bin_offsets,
feature_group.h): bundle bin 0 = every member at its most-frequent bin;
member ``f`` with ``nb`` bins occupies ``nb`` bins
``[offset_f, offset_f + nb)`` — one leading PHANTOM bin (never populated;
it hosts the threshold candidate whose left side is only the member's
most-frequent mass) followed by the ``nb - 1`` data bins in the member's
own bin order with the most-frequent bin elided. Rows in another member's
range (or bin 0) are ``f``-default — at split time their mass is
reconstructed from the leaf totals exactly like the reference's
``FixHistogram`` (dataset.cpp), and the per-bin scan-direction masks
(basic.py _build_feature_meta_bundled) restrict candidates so every
original-feature threshold is evaluated exactly once with exact sums,
reproducing the unbundled scan.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

MAX_BIN_PER_BUNDLE = 256          # dataset.cpp:107 max_bin_per_group
MAX_SEARCH_GROUP = 100            # dataset.cpp:106


class Bundle(NamedTuple):
    members: List[int]            # used-feature indices (inner, pre-bundle)
    offsets: List[int]            # bundle-bin offset per member
    num_bin: int                  # total bundle bins (incl. shared bin 0)


def _member_span(num_bin: int) -> int:
    """Bins a member occupies in the bundle: a leading phantom candidate bin
    + (num_bin - 1) data bins (most-frequent bin elided)."""
    return num_bin


def find_groups(nonzero_rows: List[Optional[np.ndarray]], num_bins: List[int],
                order: np.ndarray, total_cnt: int,
                max_conflict: int) -> List[List[int]]:
    """One greedy pass (reference: dataset.cpp:100-187 first round)."""
    groups: List[List[int]] = []
    marks: List[np.ndarray] = []
    group_total: List[int] = []
    group_used: List[int] = []
    group_bins: List[int] = []
    rng = np.random.RandomState(total_cnt)
    for fi in order:
        fi = int(fi)
        rows = nonzero_rows[fi]
        cnt = len(rows)
        span = _member_span(num_bins[fi])
        available = [g for g in range(len(groups))
                     if group_total[g] + cnt <= total_cnt + max_conflict
                     and group_bins[g] + span <= MAX_BIN_PER_BUNDLE]
        if len(available) > MAX_SEARCH_GROUP:
            # sample a search subset but always keep the most recent group
            picked = rng.choice(len(available) - 1, MAX_SEARCH_GROUP - 1,
                                replace=False)
            available = [available[-1]] + [available[i] for i in picked]
        best = -1
        for g in available:
            rest = max_conflict - group_total[g] + group_used[g]
            conflicts = int(marks[g][rows].sum())
            if conflicts <= rest and conflicts <= cnt // 2:
                best = g
                best_conflicts = conflicts
                break
        if best >= 0:
            groups[best].append(fi)
            marks[best][rows] = True
            group_total[best] += cnt
            group_used[best] += cnt - best_conflicts
            group_bins[best] += span
        else:
            groups.append([fi])
            m = np.zeros(total_cnt, dtype=bool)
            m[rows] = True
            marks.append(m)
            group_total.append(cnt)
            group_used.append(cnt)
            group_bins.append(1 + span)
    return groups


def fast_feature_bundling(nonzero_rows: List[Optional[np.ndarray]],
                          num_bins: List[int],
                          bundle_ok: np.ndarray,
                          total_cnt: int) -> List[Bundle]:
    """Greedy EFB over the bundle-eligible features.

    Args:
      nonzero_rows: per used-feature sampled row indices where the feature is
        NOT at its most-frequent bin (None for ineligible features).
      num_bins: per used-feature bin counts.
      bundle_ok: [F] bool eligibility (numerical, zero-default, no NaN bin,
        unconstrained).
      total_cnt: number of sampled rows the indices refer to.

    Returns one Bundle per output column (singles included), covering every
    input feature exactly once, in input feature order by first member.
    """
    f = len(num_bins)
    eligible = [i for i in range(f) if bundle_ok[i]]
    singles = [i for i in range(f) if not bundle_ok[i]]
    max_conflict = total_cnt // 10000           # dataset.cpp:108-109
    groups: List[List[int]] = []
    if eligible:
        counts = np.array([len(nonzero_rows[i]) for i in eligible])
        order_a = np.array(eligible)
        order_b = order_a[np.argsort(-counts, kind="stable")]
        ga = find_groups(nonzero_rows, num_bins, order_a, total_cnt,
                         max_conflict)
        gb = find_groups(nonzero_rows, num_bins, order_b, total_cnt,
                         max_conflict)
        groups = gb if len(gb) < len(ga) else ga
    groups = groups + [[i] for i in singles]
    groups.sort(key=lambda g: min(g))

    bundles = []
    for g in groups:
        g = sorted(g)
        if len(g) == 1:
            # single-member groups stay regular columns (no elision)
            bundles.append(Bundle(members=g, offsets=[0],
                                  num_bin=num_bins[g[0]]))
            continue
        offsets = []
        off = 1                                  # bin 0 = all-default
        for fi in g:
            offsets.append(off)
            off += _member_span(num_bins[fi])
        bundles.append(Bundle(members=g, offsets=offsets, num_bin=off))
    return bundles
