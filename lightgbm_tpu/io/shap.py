"""TreeSHAP feature contributions (pred_contrib).

Implements the polynomial-time TreeSHAP algorithm (Lundberg et al.) that the
reference exposes as ``Tree::PredictContrib`` / ``PredictContribByMap``
(reference: include/LightGBM/tree.h:139-141, src/io/tree.cpp TreeSHAP
implementation; surfaced via predict(..., pred_contrib=True),
c_api.h:802). Output layout matches the reference: per class, one column per
feature plus a final bias column holding the tree-ensemble expected value
(tests/python_package_test/test_engine.py:1011-1117 contract: contribs sum
to the raw prediction).

Two implementations:

- ``predict_contrib_trees`` (default): a BATCHED leaf-path decomposition.
  Each leaf's root path is reduced host-side to its unique features with
  merged zero-fractions (the on-the-fly merge the recursive algorithm does
  when it re-encounters a feature); rows then enter the computation only
  through binary one-fractions, so the extend/unwind DP runs as a jitted
  scan over stacked ``[trees, leaves, depth]`` arrays with the row axis
  vectorized — the TPU-repo analog of the reference's OMP-parallel
  ``PredictContrib`` loops.
- ``predict_contrib_trees_reference``: the original per-row explicit-stack
  walk, kept as the parity oracle (pinned against brute-force Shapley in
  tests) and as the fallback (``LIGHTGBM_TPU_SHAP=reference``).
"""

from __future__ import annotations

import os
from typing import List

import numpy as np


def _tree_decisions(tree, X: np.ndarray, out: np.ndarray) -> np.ndarray:
    """Fill ``out[node] = go_left`` for every internal node of one tree,
    vectorized over rows via the tree's own ``_go_left`` (the single
    source of numerical/categorical/missing decision semantics for both
    the oracle and the batched SHAP paths)."""
    nodes_arr = np.empty(X.shape[0], dtype=np.int64)
    for node in range(tree.num_leaves - 1):
        nodes_arr.fill(node)
        out[node] = tree._go_left(nodes_arr,
                                  X[:, int(tree.split_feature[node])])
    return out


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - (path[i].pweight * zero_fraction
                                      * (unique_depth - i) / (unique_depth + 1))
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * (
                (unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def tree_shap_values_batch(tree, X: np.ndarray,
                           num_features: int) -> np.ndarray:
    """TreeSHAP contributions of one tree for ALL rows: [N, num_features+1]
    (last column = expected value).

    Iterative (explicit stack, no Python recursion — a 255-leaf leaf-wise
    chain would otherwise flirt with the recursion limit) with the per-node
    routing decisions precomputed VECTORIZED across rows, so the per-row
    walk does no numpy work beyond float accumulation."""
    n = X.shape[0]
    out = np.zeros((n, num_features + 1), np.float64)
    out[:, -1] = tree_expected_value(tree)
    if tree.num_leaves <= 1 or n == 0:
        return out
    n_nodes = tree.num_leaves - 1
    # row-batched decisions: one vectorized _go_left per node
    dec = _tree_decisions(tree, X, np.zeros((n_nodes, n), bool))
    sf = [int(s) for s in tree.split_feature]
    lc = [int(c) for c in tree.left_child]
    rc = [int(c) for c in tree.right_child]
    icount = [float(c) for c in tree.internal_count]
    lcount = [float(c) for c in tree.leaf_count]
    lvalue = [float(v) for v in tree.leaf_value]

    for r in range(n):
        phi = out[r]
        stack = [(0, 0, [], 1.0, 1.0, -1)]
        while stack:
            node, ud, parent_path, pzf, pof, pfi = stack.pop()
            path = [p.copy() for p in parent_path[:ud]]
            path.extend(_PathElement() for _ in range(2))
            _extend_path(path, ud, pzf, pof, pfi)

            if node < 0:   # leaf
                lv = lvalue[~node]
                for i in range(1, ud + 1):
                    w = _unwound_path_sum(path, ud, i)
                    el = path[i]
                    phi[el.feature_index] += (
                        w * (el.one_fraction - el.zero_fraction) * lv)
                continue

            feat = sf[node]
            left, right = lc[node], rc[node]
            hot, cold = (left, right) if dec[node, r] else (right, left)
            node_count = icount[node]

            def child_count(c):
                return lcount[~c] if c < 0 else icount[c]

            hot_zero = child_count(hot) / node_count if node_count > 0 else 0.0
            cold_zero = child_count(cold) / node_count if node_count > 0 else 0.0
            izf = iof = 1.0

            # if this feature was seen before on the path, undo that split
            pi = 0
            while pi <= ud and path[pi].feature_index != feat:
                pi += 1
            if pi != ud + 1:
                izf = path[pi].zero_fraction
                iof = path[pi].one_fraction
                _unwind_path(path, ud, pi)
                ud -= 1

            stack.append((hot, ud + 1, path, hot_zero * izf, iof, feat))
            stack.append((cold, ud + 1, path, cold_zero * izf, 0.0, feat))
    return out


def tree_expected_value(tree) -> float:
    """Count-weighted mean leaf output (reference: Tree::ExpectedValue)."""
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    counts = np.asarray(tree.leaf_count[:tree.num_leaves], np.float64)
    total = counts.sum()
    if total <= 0:
        return 0.0
    return float((counts * np.asarray(
        tree.leaf_value[:tree.num_leaves], np.float64)).sum() / total)


def tree_shap_values(tree, x: np.ndarray, num_features: int) -> np.ndarray:
    """SHAP contributions of one tree for one row: [num_features + 1]
    (last = expected value)."""
    return tree_shap_values_batch(tree, x.reshape(1, -1), num_features)[0]


def predict_contrib_trees_reference(trees, X: np.ndarray, num_features: int,
                                    num_tree_per_iteration: int = 1,
                                    average: bool = False) -> np.ndarray:
    """SHAP contributions over an ensemble, per-row oracle path.

    Returns [N, (num_features + 1) * k] with per-class blocks
    (reference: gbdt.cpp PredictContrib layout)."""
    n = X.shape[0]
    k = max(num_tree_per_iteration, 1)
    width = num_features + 1
    out = np.zeros((n, width * k), np.float64)
    # row chunks bound the per-tree [n_nodes, rows] decision matrix
    # (255-leaf trees at 10M rows would otherwise allocate ~2.5 GB per tree)
    chunk = 65536
    for r0 in range(0, n, chunk):
        Xc = X[r0:r0 + chunk]
        for ti, tree in enumerate(trees):
            c = ti % k
            out[r0:r0 + chunk, c * width:(c + 1) * width] += \
                tree_shap_values_batch(tree, Xc, num_features)
    if average and trees:
        out /= (len(trees) // k)
    return out


# ---------------------------------------------------------------------------
# Batched leaf-path TreeSHAP
# ---------------------------------------------------------------------------
def _leaf_paths(tree):
    """Per-leaf unique-feature path elements of one ModelTree/HostTree.

    Walks every root->leaf path and merges repeated features exactly like
    the recursive algorithm's unwind-and-re-extend (tree.cpp TreeSHAP: a
    re-encountered feature multiplies its zero/one fractions instead of
    adding a path element). Returns, per leaf:
      feats:  unique feature ids in first-encounter order
      zs:     merged zero fractions (product of child_count/node_count)
      splits: per element, list of (node, went_left) whose conjunction is
              the element's binary one-fraction for a row
    """
    n_nodes = tree.num_leaves - 1
    icount = tree.internal_count
    lcount = tree.leaf_count
    sf = tree.split_feature
    out = [None] * tree.num_leaves
    if n_nodes == 0:
        out[0] = ([], [], [])
        return out
    # DFS with explicit stack: (node, path list of (node_idx, went_left))
    stack = [(0, [])]
    while stack:
        node, path = stack.pop()
        if node < 0:
            leaf = ~node
            feats, zs, splits = [], [], []
            pos = {}
            for nd, went_left in path:
                f = int(sf[nd])
                child = tree.left_child[nd] if went_left else tree.right_child[nd]
                ccount = (float(lcount[~child]) if child < 0
                          else float(icount[child]))
                ncount = float(icount[nd])
                zfrac = ccount / ncount if ncount > 0 else 0.0
                if f in pos:
                    p = pos[f]
                    zs[p] *= zfrac
                    splits[p].append((nd, went_left))
                else:
                    pos[f] = len(feats)
                    feats.append(f)
                    zs.append(zfrac)
                    splits.append([(nd, went_left)])
            out[leaf] = (feats, zs, splits)
            continue
        stack.append((int(tree.left_child[node]), path + [(node, True)]))
        stack.append((int(tree.right_child[node]), path + [(node, False)]))
    return out


class _DepthBucket:
    """One stacked leaf group: every (tree, leaf) pair of a class whose
    unique-path length fits ``Db``. Flat leaf axis P (padded to a multiple
    of 64) — no per-tree leaf padding, no shared Dmax, so each leaf only
    pays its own depth class in the O(P * Db^2 * rows) DP."""

    __slots__ = ("Db", "P", "z", "leafD", "leaf_value", "elem_feat",
                 "split_elem", "split_node", "split_dir", "rho")

    def __init__(self, entries, Db: int, num_features: int):
        # entries: list of (leaf_value, feats, zs, splits-with-global-nodes)
        self.Db = Db
        P = -(-len(entries) // 64) * 64
        self.P = P
        self.z = np.ones((P, Db), np.float64)
        self.leafD = np.zeros((P,), np.int32)
        self.leaf_value = np.zeros((P,), np.float64)
        # padded elements scatter into a dump column (index num_features)
        self.elem_feat = np.full((P, Db), num_features, np.int32)
        split_elem, split_node, split_dir = [], [], []
        for p, (lv, feats, zs, splits) in enumerate(entries):
            self.leafD[p] = len(feats)
            self.leaf_value[p] = lv
            for d, (f, zv, sp) in enumerate(zip(feats, zs, splits)):
                self.z[p, d] = zv
                self.elem_feat[p, d] = f
                for gnode, went_left in sp:
                    split_elem.append(p * Db + d)
                    split_node.append(gnode)
                    split_dir.append(went_left)
        order = np.argsort(np.asarray(split_elem, np.int64), kind="stable")
        self.split_elem = np.asarray(split_elem, np.int32)[order]
        self.split_node = np.asarray(split_node, np.int32)[order]
        self.split_dir = np.asarray(split_dir, bool)[order]
        self.rho = self._unwind_coefficients()

    def _unwind_coefficients(self) -> np.ndarray:
        """[P, Db+1, Db+1] row-independent unwind coefficients.

        The unwound path SUM is linear in the extend DP vector m:
        ``w_j = sum_k rho[p, j, k] * m[k]``. Row j < Db holds the
        one_fraction=1 coefficients of element j (the _unwound_path_sum
        recursion run on unit vectors, vectorized over leaves); row Db
        holds the one_fraction=0 sum ``S0 = sum_k m[k]*(D+1)/(D-k)``
        (whose 1/z_j factor cancels against the (0 - z_j) multiplier, so
        every unmatched element contributes exactly -leaf_value * S0).
        This turns the per-(row, element) unwind into one batched matmul.
        """
        P, Db = self.P, self.Db
        K = Db + 1
        D = self.leafD.astype(np.float64)[:, None]      # [P, 1]
        Dp1 = D + 1.0
        kidx = np.arange(K)[None, :]                    # [1, K]
        rho = np.zeros((P, K, K), np.float64)
        # the recursion applied to the identity (all basis vectors at once)
        for j in range(Db):
            zj = self.z[:, j][:, None]
            npo = (self.leafD[:, None] == kidx).astype(np.float64)
            total = np.zeros((P, K))
            for i in range(Db - 1, -1, -1):
                act = (i < self.leafD)[:, None]
                tmp = np.where(act, npo * Dp1 / (i + 1.0), 0.0)
                total += tmp
                mi = (kidx == i).astype(np.float64)
                npo = np.where(act, mi - tmp * zj * (D - i) / Dp1, npo)
            rho[:, j, :] = total
        rho[:, Db, :] = np.where(kidx < self.leafD[:, None],
                                 Dp1 / np.maximum(D - kidx, 1e-300), 0.0)
        return rho


# bucket ceilings: leaves grouped by the smallest ceiling >= their D
_DEPTH_BUCKETS = (2, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256)


def _bucket_ceiling(D: int) -> int:
    """Smallest bucket ceiling >= D (beyond the table: next multiple of
    64, so arbitrarily deep paths never crash the fast path)."""
    return next((b for b in _DEPTH_BUCKETS if b >= D), -(-D // 64) * 64)


class _ClassStack:
    """Host precompute for one class: global node table + depth buckets."""

    def __init__(self, trees, num_features: int):
        self.trees = trees
        self.num_features = num_features
        self.node_offset = np.zeros(len(trees) + 1, np.int64)
        for t, tree in enumerate(trees):
            self.node_offset[t + 1] = self.node_offset[t] + max(
                tree.num_leaves - 1, 0)
        self.total_nodes = int(self.node_offset[-1])
        by_depth: dict = {}
        for t, tree in enumerate(trees):
            off = int(self.node_offset[t])
            for leaf, (feats, zs, splits) in enumerate(_leaf_paths(tree)):
                D = len(feats)
                if D == 0:
                    continue
                Db = _bucket_ceiling(D)
                gsplits = [[(off + nd, wl) for nd, wl in sp]
                           for sp in splits]
                by_depth.setdefault(Db, []).append(
                    (float(tree.leaf_value[leaf]), feats, zs, gsplits))
        self.buckets = [
            _DepthBucket(entries, Db, num_features)
            for Db, entries in sorted(by_depth.items())]
        self.expected = sum(tree_expected_value(t) for t in trees)

    def decisions(self, X: np.ndarray) -> np.ndarray:
        """[total_nodes, N] uint8 go-left decisions via the trees' own
        _go_left (handles numerical/categorical/missing semantics),
        computed once over all rows."""
        dec = np.zeros((max(self.total_nodes, 1), X.shape[0]), np.uint8)
        for t, tree in enumerate(self.trees):
            off = int(self.node_offset[t])
            _tree_decisions(tree, X, dec[off:off + tree.num_leaves - 1])
        return dec


def _shap_bucket_fn(nf: int, Db: int):
    """Build the jitted DP for one depth bucket.

    Extend runs as an unrolled loop with a GROWING lane axis (after i
    pushes only lanes 0..i are nonzero — a fixed-width scan would double
    the work), and the whole per-element unwind is one batched matmul
    against the host-precomputed ``rho`` coefficients (see
    ``_DepthBucket._unwind_coefficients``). The only per-row tensors are
    multiplies/adds and the final scatter."""
    import jax
    import jax.numpy as jnp

    def fn(dec, z, leafD, leaf_value, elem_feat, split_elem, split_node,
           split_dir, rho):
        P = z.shape[0]
        C = dec.shape[1]
        f64 = z.dtype
        # binary one-fractions: AND of each element's split decisions
        match = (jnp.take(dec, split_node, axis=0)
                 == split_dir[:, None])
        o_flat = jax.ops.segment_min(match.astype(jnp.int32), split_elem,
                                     num_segments=P * Db,
                                     indices_are_sorted=True)
        o = (o_flat > 0).reshape(P, Db, C)

        # ---- extend: m[k] = pweights after pushing all D elements
        # (transcribes _extend_path with the root sentinel at lane 0)
        m = jnp.ones((P, 1, C), f64)
        for d in range(Db):
            i = d + 1
            lanes = jnp.arange(d + 2, dtype=f64)
            a = (i - lanes) / (i + 1.0)                 # [d+2]
            b = lanes / (i + 1.0)
            mpad = jnp.pad(m, ((0, 0), (0, 1), (0, 0)))
            shifted = jnp.pad(m, ((0, 0), (1, 0), (0, 0)))
            za = z[:, d][:, None] * a[None, :]          # [P, d+2] row-indep
            new = (za[:, :, None] * mpad
                   + o[:, d, :][:, None, :] * (b[None, :, None] * shifted))
            act = (d < leafD)[:, None, None]
            m = jnp.where(act, new, mpad)               # [P, d+2, C]

        # ---- unwind: one batched GEMM against the rho coefficients
        W = jnp.einsum("pjk,pkc->pjc", rho, m)          # [P, Db+1, C]
        W1 = W[:, :Db, :]
        S0 = W[:, Db, :]
        # matched elements: w_j*(1 - z_j)*v; unmatched: -v*S0 (z cancels)
        c1 = (1.0 - z) * leaf_value[:, None]            # [P, Db]
        contrib = jnp.where(o, c1[:, :, None] * W1,
                            (-leaf_value)[:, None, None] * S0[:, None, :])
        maskj = (jnp.arange(Db)[None, :] < leafD[:, None])[..., None]
        contrib = jnp.where(maskj, contrib, 0.0)
        phi = jnp.zeros((nf + 1, C), f64).at[elem_feat.reshape(-1)].add(
            contrib.reshape(-1, C))
        return phi[:nf].T                               # [C, nf]

    return fn


_shap_jit_cache: dict = {}
# byte budget for one [total_nodes, rows] uint8 decision block (the row
# block shrinks as the ensemble's node count grows)
_DEC_BLOCK_BYTES = 512 * 1024 * 1024
_DEC_ROW_BLOCK_MAX = 65536


def _dec_row_block(total_nodes: int) -> int:
    return max(1024, min(_DEC_ROW_BLOCK_MAX,
                         _DEC_BLOCK_BYTES // max(total_nodes, 1)))


def _class_stack_cached(cls_trees, num_features: int) -> "_ClassStack":
    """Cache the stack ON the first tree object so repeated pred_contrib
    calls with the same tree list skip the leaf-path walk and rho build,
    and the precompute's lifetime is tied to the trees (dropping the
    Booster frees it — no module-global pinning multi-GB rho arrays)."""
    tree0 = cls_trees[0]
    hit = getattr(tree0, "_shap_stack", None)
    if (hit is not None and hit.num_features == num_features
            and len(hit.trees) == len(cls_trees)
            and all(a is b for a, b in zip(hit.trees, cls_trees))):
        return hit
    stack = _ClassStack(cls_trees, num_features)
    try:
        tree0._shap_stack = stack
    except AttributeError:
        pass            # slotted/frozen tree types just skip the cache
    return stack


def _shap_bucket_jit(nf: int, Db: int):
    import jax
    key = (nf, Db)
    fn = _shap_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(_shap_bucket_fn(nf, Db))
        _shap_jit_cache[key] = fn
    return fn


def predict_contrib_trees_fast(trees, X: np.ndarray, num_features: int,
                               num_tree_per_iteration: int = 1,
                               average: bool = False) -> np.ndarray:
    """Batched TreeSHAP over the ensemble (see module docstring).

    Runs the DP in float64 on the CPU backend (jax.enable_x64 scope —
    TPUs have no native f64, and SHAP is a host-side analysis path in the
    reference too: OMP C++ in tree.cpp PredictContrib). The DP is
    memory-bandwidth-bound; ``LIGHTGBM_TPU_SHAP_DTYPE=float32`` halves the
    traffic (measured 2x on a single-core host) at ~1e-6 relative
    contribution error."""
    import jax
    if hasattr(jax, "enable_x64"):
        enable_x64 = jax.enable_x64
    else:      # pre-0.5 jax keeps the scope under jax.experimental
        from jax.experimental import enable_x64

    dt = (np.float32 if os.environ.get("LIGHTGBM_TPU_SHAP_DTYPE")
          == "float32" else np.float64)
    n = X.shape[0]
    k = max(num_tree_per_iteration, 1)
    width = num_features + 1
    out = np.zeros((n, width * k), np.float64)
    cpu = jax.devices("cpu")[0]
    budget = 256 * 1024 * 1024
    for c in range(k):
        cls_trees = [t for ti, t in enumerate(trees) if ti % k == c]
        if not cls_trees:
            continue
        stack = _class_stack_cached(cls_trees, num_features)
        out[:, c * width + num_features] = stack.expected
        if not stack.buckets:
            continue
        with enable_x64():
            bucket_state = []
            # device-resident constants cached per dtype on the stack, so
            # repeat calls skip the host->device copies of rho etc. too
            const_cache = getattr(stack, "_device_consts", None)
            if const_cache is None or const_cache[0] != dt:
                const_cache = (dt, [
                    [jax.device_put(v, cpu) for v in (
                        b.z.astype(dt), b.leafD, b.leaf_value.astype(dt),
                        b.elem_feat, b.split_elem, b.split_node,
                        b.split_dir, b.rho.astype(dt))]
                    for b in stack.buckets])
                stack._device_consts = const_cache
            for b, consts in zip(stack.buckets, const_cache[1]):
                # DP chunk: keep the [P, 3*Db, C] state within the
                # budget; power-of-two widths bound recompiles
                chunk = max(128, budget // (b.P * (3 * b.Db + 2)
                                            * np.dtype(dt).itemsize))
                chunk = 1 << (min(chunk, 16384, max(n, 128))
                              .bit_length() - 1)
                bucket_state.append(
                    (b, _shap_bucket_jit(num_features, b.Db), consts,
                     chunk))
            # outer row blocks bound the [total_nodes, rows] decision
            # matrix (a 500-tree 255-leaf model at 10M rows would
            # otherwise materialize ~TB of uint8)
            row_block = _dec_row_block(stack.total_nodes)
            for q0 in range(0, n, row_block):
                qn = min(row_block, n - q0)
                dec_all = stack.decisions(X[q0:q0 + qn])
                for b, fn, consts, chunk in bucket_state:
                    for r0 in range(0, qn, chunk):
                        rows = min(chunk, qn - r0)
                        dec = dec_all[:, r0:r0 + rows]
                        if rows < chunk:
                            # pad to the jitted width: at most one
                            # partial call per (bucket, block)
                            dec = np.concatenate(
                                [dec, np.zeros(
                                    (dec.shape[0], chunk - rows),
                                    np.uint8)], axis=1)
                        phi = np.asarray(
                            fn(jax.device_put(dec, cpu), *consts))
                        out[q0 + r0:q0 + r0 + rows,
                            c * width:c * width + num_features] += \
                            phi[:rows]
    if average and trees:
        out /= (len(trees) // k)
    return out


def predict_contrib_trees(trees, X: np.ndarray, num_features: int,
                          num_tree_per_iteration: int = 1,
                          average: bool = False) -> np.ndarray:
    """SHAP contributions over an ensemble: [N, (num_features + 1) * k]
    (reference: gbdt.cpp PredictContrib layout). Dispatches to the batched
    path unless ``LIGHTGBM_TPU_SHAP=reference``."""
    if os.environ.get("LIGHTGBM_TPU_SHAP") == "reference":
        return predict_contrib_trees_reference(
            trees, X, num_features, num_tree_per_iteration, average)
    return predict_contrib_trees_fast(
        trees, X, num_features, num_tree_per_iteration, average)
