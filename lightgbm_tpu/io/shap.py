"""TreeSHAP feature contributions (pred_contrib).

Implements the polynomial-time TreeSHAP algorithm (Lundberg et al.) that the
reference exposes as ``Tree::PredictContrib`` / ``PredictContribByMap``
(reference: include/LightGBM/tree.h:139-141, src/io/tree.cpp TreeSHAP
implementation; surfaced via predict(..., pred_contrib=True),
c_api.h:802). Output layout matches the reference: per class, one column per
feature plus a final bias column holding the tree-ensemble expected value
(tests/python_package_test/test_engine.py:1011-1117 contract: contribs sum
to the raw prediction).

This host-side implementation walks each ModelTree (real-threshold space)
per row. It is the reference-parity path; a batched device formulation is a
future optimization.
"""

from __future__ import annotations

from typing import List

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - (path[i].pweight * zero_fraction
                                      * (unique_depth - i) / (unique_depth + 1))
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * (
                (unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def _tree_shap_recurse(tree, x: np.ndarray, phi: np.ndarray, node: int,
                       unique_depth: int, parent_path: List[_PathElement],
                       parent_zero_fraction: float,
                       parent_one_fraction: float,
                       parent_feature_index: int) -> None:
    path = [p.copy() for p in parent_path[:unique_depth]]
    path.extend(_PathElement() for _ in range(2))
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:   # leaf
        li = ~node
        leaf_value = float(tree.leaf_value[li])
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += (w * (el.one_fraction - el.zero_fraction)
                                      * leaf_value)
        return

    feat = int(tree.split_feature[node])
    left, right = int(tree.left_child[node]), int(tree.right_child[node])
    go_left = bool(tree._go_left(np.array([node]), np.array([x[feat]]))[0])
    hot, cold = (left, right) if go_left else (right, left)

    node_count = float(tree.internal_count[node])

    def child_count(c):
        return float(tree.leaf_count[~c] if c < 0 else tree.internal_count[c])

    hot_zero_fraction = child_count(hot) / node_count if node_count > 0 else 0.0
    cold_zero_fraction = child_count(cold) / node_count if node_count > 0 else 0.0
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # if this feature was seen before on the path, undo that split
    path_index = 0
    while path_index <= unique_depth:
        if path[path_index].feature_index == feat:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap_recurse(tree, x, phi, hot, unique_depth + 1, path,
                       hot_zero_fraction * incoming_zero_fraction,
                       incoming_one_fraction, feat)
    _tree_shap_recurse(tree, x, phi, cold, unique_depth + 1, path,
                       cold_zero_fraction * incoming_zero_fraction,
                       0.0, feat)


def tree_expected_value(tree) -> float:
    """Count-weighted mean leaf output (reference: Tree::ExpectedValue)."""
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    counts = np.asarray(tree.leaf_count[:tree.num_leaves], np.float64)
    total = counts.sum()
    if total <= 0:
        return 0.0
    return float((counts * np.asarray(
        tree.leaf_value[:tree.num_leaves], np.float64)).sum() / total)


def tree_shap_values(tree, x: np.ndarray, num_features: int) -> np.ndarray:
    """SHAP contributions of one tree for one row: [num_features + 1]
    (last = expected value)."""
    phi = np.zeros(num_features + 1, np.float64)
    phi[-1] = tree_expected_value(tree)
    if tree.num_leaves > 1:
        _tree_shap_recurse(tree, x, phi, 0, 0, [], 1.0, 1.0, -1)
    return phi


def predict_contrib_trees(trees, X: np.ndarray, num_features: int,
                          num_tree_per_iteration: int = 1,
                          average: bool = False) -> np.ndarray:
    """SHAP contributions over an ensemble.

    Returns [N, (num_features + 1) * k] with per-class blocks
    (reference: gbdt.cpp PredictContrib layout)."""
    n = X.shape[0]
    k = max(num_tree_per_iteration, 1)
    width = num_features + 1
    out = np.zeros((n, width * k), np.float64)
    for ti, tree in enumerate(trees):
        c = ti % k
        for r in range(n):
            out[r, c * width:(c + 1) * width] += tree_shap_values(
                tree, X[r], num_features)
    if average and trees:
        out /= (len(trees) // k)
    return out
