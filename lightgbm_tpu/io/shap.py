"""TreeSHAP feature contributions (pred_contrib).

Implements the polynomial-time TreeSHAP algorithm (Lundberg et al.) that the
reference exposes as ``Tree::PredictContrib`` / ``PredictContribByMap``
(reference: include/LightGBM/tree.h:139-141, src/io/tree.cpp TreeSHAP
implementation; surfaced via predict(..., pred_contrib=True),
c_api.h:802). Output layout matches the reference: per class, one column per
feature plus a final bias column holding the tree-ensemble expected value
(tests/python_package_test/test_engine.py:1011-1117 contract: contribs sum
to the raw prediction).

This host-side implementation walks each ModelTree (real-threshold space)
per row. It is the reference-parity path; a batched device formulation is a
future optimization.
"""

from __future__ import annotations

from typing import List

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - (path[i].pweight * zero_fraction
                                      * (unique_depth - i) / (unique_depth + 1))
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * (
                (unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def tree_shap_values_batch(tree, X: np.ndarray,
                           num_features: int) -> np.ndarray:
    """TreeSHAP contributions of one tree for ALL rows: [N, num_features+1]
    (last column = expected value).

    Iterative (explicit stack, no Python recursion — a 255-leaf leaf-wise
    chain would otherwise flirt with the recursion limit) with the per-node
    routing decisions precomputed VECTORIZED across rows, so the per-row
    walk does no numpy work beyond float accumulation."""
    n = X.shape[0]
    out = np.zeros((n, num_features + 1), np.float64)
    out[:, -1] = tree_expected_value(tree)
    if tree.num_leaves <= 1 or n == 0:
        return out
    n_nodes = tree.num_leaves - 1
    # row-batched decisions: one vectorized _go_left per node
    dec = np.zeros((n_nodes, n), bool)
    nodes_arr = np.empty(n, dtype=np.int64)
    for node in range(n_nodes):
        nodes_arr.fill(node)
        dec[node] = tree._go_left(nodes_arr,
                                  X[:, int(tree.split_feature[node])])
    sf = [int(s) for s in tree.split_feature]
    lc = [int(c) for c in tree.left_child]
    rc = [int(c) for c in tree.right_child]
    icount = [float(c) for c in tree.internal_count]
    lcount = [float(c) for c in tree.leaf_count]
    lvalue = [float(v) for v in tree.leaf_value]

    for r in range(n):
        phi = out[r]
        stack = [(0, 0, [], 1.0, 1.0, -1)]
        while stack:
            node, ud, parent_path, pzf, pof, pfi = stack.pop()
            path = [p.copy() for p in parent_path[:ud]]
            path.extend(_PathElement() for _ in range(2))
            _extend_path(path, ud, pzf, pof, pfi)

            if node < 0:   # leaf
                lv = lvalue[~node]
                for i in range(1, ud + 1):
                    w = _unwound_path_sum(path, ud, i)
                    el = path[i]
                    phi[el.feature_index] += (
                        w * (el.one_fraction - el.zero_fraction) * lv)
                continue

            feat = sf[node]
            left, right = lc[node], rc[node]
            hot, cold = (left, right) if dec[node, r] else (right, left)
            node_count = icount[node]

            def child_count(c):
                return lcount[~c] if c < 0 else icount[c]

            hot_zero = child_count(hot) / node_count if node_count > 0 else 0.0
            cold_zero = child_count(cold) / node_count if node_count > 0 else 0.0
            izf = iof = 1.0

            # if this feature was seen before on the path, undo that split
            pi = 0
            while pi <= ud and path[pi].feature_index != feat:
                pi += 1
            if pi != ud + 1:
                izf = path[pi].zero_fraction
                iof = path[pi].one_fraction
                _unwind_path(path, ud, pi)
                ud -= 1

            stack.append((hot, ud + 1, path, hot_zero * izf, iof, feat))
            stack.append((cold, ud + 1, path, cold_zero * izf, 0.0, feat))
    return out


def tree_expected_value(tree) -> float:
    """Count-weighted mean leaf output (reference: Tree::ExpectedValue)."""
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    counts = np.asarray(tree.leaf_count[:tree.num_leaves], np.float64)
    total = counts.sum()
    if total <= 0:
        return 0.0
    return float((counts * np.asarray(
        tree.leaf_value[:tree.num_leaves], np.float64)).sum() / total)


def tree_shap_values(tree, x: np.ndarray, num_features: int) -> np.ndarray:
    """SHAP contributions of one tree for one row: [num_features + 1]
    (last = expected value)."""
    return tree_shap_values_batch(tree, x.reshape(1, -1), num_features)[0]


def predict_contrib_trees(trees, X: np.ndarray, num_features: int,
                          num_tree_per_iteration: int = 1,
                          average: bool = False) -> np.ndarray:
    """SHAP contributions over an ensemble.

    Returns [N, (num_features + 1) * k] with per-class blocks
    (reference: gbdt.cpp PredictContrib layout)."""
    n = X.shape[0]
    k = max(num_tree_per_iteration, 1)
    width = num_features + 1
    out = np.zeros((n, width * k), np.float64)
    # row chunks bound the per-tree [n_nodes, rows] decision matrix
    # (255-leaf trees at 10M rows would otherwise allocate ~2.5 GB per tree)
    chunk = 65536
    for r0 in range(0, n, chunk):
        Xc = X[r0:r0 + chunk]
        for ti, tree in enumerate(trees):
            c = ti % k
            out[r0:r0 + chunk, c * width:(c + 1) * width] += \
                tree_shap_values_batch(tree, Xc, num_features)
    if average and trees:
        out /= (len(trees) // k)
    return out
