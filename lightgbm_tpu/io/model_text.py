"""Model text serialization, v3-format compatible.

Re-implements the reference's model text format (reference:
src/boosting/gbdt_model_text.cpp:311-417 ``SaveModelToString`` /
``LoadModelFromString`` and src/io/tree.cpp:336-410 ``Tree::ToString`` /
tree.cpp:653+ parsing ctor) so models serialize to / load from the same
``version=v3`` text layout the reference uses: header key=values, per-tree
blocks with real-valued thresholds and packed ``decision_type`` bytes
(cat bit | default-left bit | missing-type<<2, reference tree.h:19-20,269),
feature_importances and an echoed parameters block.

Loaded models predict by traversing REAL thresholds over raw features
(reference: Tree::NumericalDecision / CategoricalDecision, tree.h:320-360) —
no bin mappers are required after loading, exactly like the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO, K_ZERO_THRESHOLD
from ..config import Config
from ..utils import log

K_MODEL_VERSION = "v3"   # reference: gbdt_model_text.cpp:19 kModelVersion

_CAT_MASK = 1            # reference: tree.h:19 kCategoricalMask
_DEFAULT_LEFT_MASK = 2   # reference: tree.h:20 kDefaultLeftMask


def _d2s(v: float) -> str:
    """Shortest round-trip decimal for a double (the analog of the
    reference's max_digits10 stream precision)."""
    return repr(float(v))


def _join(arr, fmt=str) -> str:
    return " ".join(fmt(x) for x in arr)


class ModelTree:
    """One tree in model-text (real-value) space: original feature indices,
    real thresholds, packed decision types. numpy arrays throughout."""

    def __init__(self):
        self.num_leaves = 1
        self.num_cat = 0
        self.split_feature = np.zeros(0, np.int32)
        self.split_gain = np.zeros(0, np.float64)
        self.threshold = np.zeros(0, np.float64)
        self.decision_type = np.zeros(0, np.int8)
        self.left_child = np.zeros(0, np.int32)
        self.right_child = np.zeros(0, np.int32)
        self.leaf_value = np.zeros(1, np.float64)
        self.leaf_weight = np.zeros(1, np.float64)
        self.leaf_count = np.zeros(1, np.int64)
        self.internal_value = np.zeros(0, np.float64)
        self.internal_weight = np.zeros(0, np.float64)
        self.internal_count = np.zeros(0, np.int64)
        self.cat_boundaries = np.zeros(1, np.int32)   # [num_cat+1]
        self.cat_threshold = np.zeros(0, np.uint32)
        self.shrinkage = 1.0
        self.is_linear = False
        self.leaf_const = np.zeros(0, np.float64)
        self.leaf_features: List[List[int]] = []
        self.leaf_coeff: List[List[float]] = []

    # ------------------------------------------------------------- build
    @classmethod
    def from_host(cls, ht, mappers) -> "ModelTree":
        """Convert a trained HostTree (bin space) to model space.

        ``mappers``: the dataset's BinMapper list indexed by ORIGINAL feature.
        Categorical bin-bitsets are re-encoded over raw category values
        (the reference's cat_threshold stores category-value bitsets,
        tree.h:349-360 CategoricalDecision on int(fval))."""
        t = cls()
        n = ht.num_leaves - 1
        t.num_leaves = ht.num_leaves
        t.split_feature = np.array(
            [int(ht.feature_indices[f]) for f in ht.split_feature], np.int32)
        t.split_gain = np.asarray(ht.split_gain, np.float64)
        t.decision_type = np.zeros(n, np.int8)
        t.threshold = np.asarray(ht.threshold, np.float64).copy()
        t.left_child = np.asarray(ht.left_child, np.int32)
        t.right_child = np.asarray(ht.right_child, np.int32)
        t.leaf_value = np.asarray(ht.leaf_value, np.float64)
        t.leaf_weight = np.asarray(ht.leaf_weight, np.float64)
        t.leaf_count = np.asarray(np.round(ht.leaf_count), np.int64)
        t.internal_value = np.asarray(ht.internal_value, np.float64)
        t.internal_weight = np.asarray(ht.internal_weight, np.float64)
        t.internal_count = np.asarray(np.round(ht.internal_count), np.int64)
        t.shrinkage = ht.shrinkage
        cat_boundaries = [0]
        cat_words: List[int] = []
        for i in range(n):
            dt = 0
            if bool(ht.is_cat[i]):
                dt |= _CAT_MASK
                mapper = mappers[t.split_feature[i]]
                cats = [mapper.bin_2_categorical[b]
                        for b in range(min(mapper.num_bin,
                                           ht.cat_bitset.shape[1] * 32))
                        if (int(ht.cat_bitset[i, b >> 5]) >> (b & 31)) & 1
                        and mapper.bin_2_categorical[b] >= 0]
                max_cat = max(cats) if cats else 0
                n_words = max_cat // 32 + 1
                words = [0] * n_words
                for cval in cats:
                    words[cval >> 5] |= 1 << (cval & 31)
                t.threshold[i] = t.num_cat          # cat index into boundaries
                t.num_cat += 1
                cat_words.extend(words)
                cat_boundaries.append(len(cat_words))
            if bool(ht.default_left[i]):
                dt |= _DEFAULT_LEFT_MASK
            dt |= int(ht.missing_type[i]) << 2
            t.decision_type[i] = dt
        t.cat_boundaries = np.asarray(cat_boundaries, np.int32)
        t.cat_threshold = np.asarray(cat_words, np.uint32)
        if getattr(ht, "is_linear", False):
            t.is_linear = True
            t.leaf_const = np.asarray(ht.leaf_const, np.float64)
            t.leaf_coeff = [list(map(float, c)) for c in ht.leaf_coeff]
            t.leaf_features = [list(map(int, fs)) for fs in ht.leaf_features_raw]
        return t

    # -------------------------------------------------------------- text
    def to_string(self) -> str:
        """Tree block body (reference: tree.cpp:336-410 Tree::ToString)."""
        n = self.num_leaves - 1
        lines = [
            f"num_leaves={self.num_leaves}",
            f"num_cat={self.num_cat}",
            "split_feature=" + _join(self.split_feature),
            "split_gain=" + _join(self.split_gain, _d2s),
            "threshold=" + _join(self.threshold, _d2s),
            "decision_type=" + _join(self.decision_type),
            "left_child=" + _join(self.left_child),
            "right_child=" + _join(self.right_child),
            "leaf_value=" + _join(self.leaf_value[:self.num_leaves], _d2s),
            "leaf_weight=" + _join(self.leaf_weight[:self.num_leaves], _d2s),
            "leaf_count=" + _join(self.leaf_count[:self.num_leaves]),
            "internal_value=" + _join(self.internal_value[:n], _d2s),
            "internal_weight=" + _join(self.internal_weight[:n], _d2s),
            "internal_count=" + _join(self.internal_count[:n]),
        ]
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + _join(self.cat_boundaries))
            lines.append("cat_threshold=" + _join(self.cat_threshold))
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            lines.append("leaf_const=" + _join(self.leaf_const, _d2s))
            lines.append("num_features=" + _join(
                [len(f) for f in self.leaf_features]))
            lines.append("leaf_features=" + " ".join(
                (_join(f) + " ") if f else "" for f in self.leaf_features).rstrip() + " ")
            lines.append("leaf_coeff=" + " ".join(
                (_join(c, _d2s) + " ") if c else "" for c in self.leaf_coeff).rstrip() + " ")
        lines.append(f"shrinkage={_d2s(self.shrinkage)}")
        return "\n".join(lines) + "\n\n"

    @classmethod
    def from_kv(cls, kv: Dict[str, str]) -> "ModelTree":
        """Parse one tree block (reference: tree.cpp:653+ Tree(const char*)).
        Every section is validated for presence/length/parseability so a
        truncated block raises a descriptive ValueError naming the section
        instead of a bare KeyError/IndexError deep in numpy."""
        t = cls()
        if "num_leaves" not in kv:
            raise ValueError("missing 'num_leaves' section")
        t.num_leaves = int(kv["num_leaves"])
        if t.num_leaves < 1:
            raise ValueError(f"invalid num_leaves={t.num_leaves}")
        t.num_cat = int(kv.get("num_cat", "0"))
        n = t.num_leaves - 1

        def arr(key, dtype, count, default=None):
            s = kv.get(key, "")
            if not s.strip():
                if default is not None:
                    return np.full(count, default, dtype)
                return np.zeros(count, dtype)
            try:
                out = np.asarray(s.split(), dtype=dtype)
            except (ValueError, OverflowError) as e:
                raise ValueError(f"unparseable '{key}' section: {e}")
            if len(out) != count:
                raise ValueError(f"'{key}' section has {len(out)} values, "
                                 f"expected {count}")
            return out

        t.split_feature = arr("split_feature", np.int32, n)
        t.split_gain = arr("split_gain", np.float64, n)
        t.threshold = arr("threshold", np.float64, n)
        t.decision_type = arr("decision_type", np.int8, n)
        t.left_child = arr("left_child", np.int32, n)
        t.right_child = arr("right_child", np.int32, n)
        t.leaf_value = arr("leaf_value", np.float64, t.num_leaves)
        t.leaf_weight = arr("leaf_weight", np.float64, t.num_leaves)
        t.leaf_count = arr("leaf_count", np.int64, t.num_leaves)
        t.internal_value = arr("internal_value", np.float64, n)
        t.internal_weight = arr("internal_weight", np.float64, n)
        t.internal_count = arr("internal_count", np.int64, n)
        if t.num_cat > 0:
            t.cat_boundaries = arr("cat_boundaries", np.int32, t.num_cat + 1)
            if "cat_threshold" not in kv:
                raise ValueError("missing 'cat_threshold' section")
            t.cat_threshold = np.asarray(kv["cat_threshold"].split(),
                                         dtype=np.uint64).astype(np.uint32)
        t.is_linear = bool(int(kv.get("is_linear", "0")))
        if t.is_linear:
            t.leaf_const = arr("leaf_const", np.float64, t.num_leaves)
            nf = arr("num_features", np.int32, t.num_leaves)
            feats = kv.get("leaf_features", "").split()
            coefs = kv.get("leaf_coeff", "").split()
            total = int(np.sum(nf))
            if len(feats) < total or len(coefs) < total:
                raise ValueError(
                    f"'leaf_features'/'leaf_coeff' sections hold "
                    f"{len(feats)}/{len(coefs)} values, expected {total}")
            pos = 0
            for c in nf:
                t.leaf_features.append([int(x) for x in feats[pos:pos + c]])
                t.leaf_coeff.append([float(x) for x in coefs[pos:pos + c]])
                pos += c
        t.shrinkage = float(kv.get("shrinkage", "1"))
        return t

    # --------------------------------------------------------- traversal
    def _go_left(self, nd: np.ndarray, fval: np.ndarray) -> np.ndarray:
        """Vectorized split decision for node indices ``nd`` and raw feature
        values ``fval`` (reference: tree.h:320-360 Numerical/CategoricalDecision)."""
        dt = self.decision_type[nd]
        missing_type = (dt.astype(np.int32) >> 2) & 3
        default_left = (dt & _DEFAULT_LEFT_MASK) > 0
        is_cat = (dt & _CAT_MASK) > 0

        # NaN with non-NaN missing handling is treated as 0.0 (tree.h:330)
        fv = np.where(np.isnan(fval) & (missing_type != MISSING_NAN), 0.0, fval)
        is_missing = (((missing_type == MISSING_ZERO)
                       & (np.abs(fv) <= K_ZERO_THRESHOLD))
                      | ((missing_type == MISSING_NAN) & np.isnan(fv)))
        with np.errstate(invalid="ignore"):
            num_left = np.where(is_missing, default_left,
                                fv <= self.threshold[nd])
        if not is_cat.any():
            return num_left
        # categorical: membership of int(fval) in the node's value bitset
        cat_left = np.zeros(len(nd), dtype=bool)
        sel = np.nonzero(is_cat)[0]
        for i in sel:
            ci = int(self.threshold[nd[i]])
            lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
            v = fval[i]
            if np.isnan(v) or v < 0:
                cat_left[i] = False
                continue
            iv = int(v)
            w = iv >> 5
            if w < hi - lo:
                cat_left[i] = bool((int(self.cat_threshold[lo + w]) >> (iv & 31)) & 1)
        return np.where(is_cat, cat_left, num_left)

    def leaf_index(self, X: np.ndarray) -> np.ndarray:
        """Per-row leaf index over raw features [N, F]."""
        n = X.shape[0]
        out = np.zeros(n, np.int32)
        if self.num_leaves <= 1:
            return out
        cur = np.zeros(n, np.int32)
        active = np.ones(n, dtype=bool)
        while active.any():
            idx = np.nonzero(active)[0]
            nd = cur[idx]
            fval = X[idx, self.split_feature[nd]]
            left = self._go_left(nd, fval)
            nxt = np.where(left, self.left_child[nd], self.right_child[nd])
            cur[idx] = nxt
            done = nxt < 0
            out[idx[done]] = ~nxt[done]
            active[idx[done]] = False
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaf = self.leaf_index(X)
        out = self.leaf_value[leaf]
        if self.is_linear:
            # linear leaves: const + sum(coeff * feature), NaN features
            # fall back to the plain leaf value (linear_tree_learner.cpp:19-41)
            lin = np.asarray(self.leaf_const)[leaf].copy()
            ok = np.ones(len(leaf), dtype=bool)
            for li in range(self.num_leaves):
                rows = leaf == li
                if not rows.any() or not self.leaf_features[li]:
                    continue
                feats = np.asarray(self.leaf_features[li], np.int64)
                coefs = np.asarray(self.leaf_coeff[li], np.float64)
                vals = X[np.ix_(rows, feats)]
                bad = np.isnan(vals).any(axis=1) | np.isinf(vals).any(axis=1)
                contrib = vals @ coefs
                lin[rows] += np.where(bad, 0.0, contrib)
                ok_rows = ok[rows]
                ok_rows &= ~bad
                ok[rows] = ok_rows
            out = np.where(ok, lin, out)
        return out

    def depth_of(self) -> np.ndarray:
        """Leaf depths (for plotting/JSON)."""
        depth = np.zeros(self.num_leaves, np.int32)
        ndepth = np.zeros(max(self.num_leaves - 1, 1), np.int32)
        for i in range(self.num_leaves - 1):
            for child in (self.left_child[i], self.right_child[i]):
                if child >= 0:
                    ndepth[child] = ndepth[i] + 1
                else:
                    depth[~child] = ndepth[i] + 1
        return depth

    def to_json_node(self, index: int = 0) -> dict:
        """Nested node dict (reference: tree.cpp:412-520 Tree::ToJSON)."""
        if self.num_leaves == 1:
            return {"leaf_value": float(self.leaf_value[0])}
        if index >= 0:
            dt = int(self.decision_type[index])
            is_cat = bool(dt & _CAT_MASK)
            mt = (dt >> 2) & 3
            node = {
                "split_index": int(index),
                "split_feature": int(self.split_feature[index]),
                "split_gain": float(self.split_gain[index]),
                "threshold": (self._cat_json_threshold(index) if is_cat
                              else float(self.threshold[index])),
                "decision_type": "==" if is_cat else "<=",
                "default_left": bool(dt & _DEFAULT_LEFT_MASK),
                "missing_type": {MISSING_NONE: "None", MISSING_ZERO: "Zero",
                                 MISSING_NAN: "NaN"}[mt],
                "internal_value": float(self.internal_value[index]),
                "internal_weight": float(self.internal_weight[index]),
                "internal_count": int(self.internal_count[index]),
                "left_child": self.to_json_node(int(self.left_child[index])),
                "right_child": self.to_json_node(int(self.right_child[index])),
            }
            return node
        li = ~index
        return {
            "leaf_index": int(li),
            "leaf_value": float(self.leaf_value[li]),
            "leaf_weight": float(self.leaf_weight[li]),
            "leaf_count": int(self.leaf_count[li]),
        }

    def _cat_json_threshold(self, index: int) -> str:
        ci = int(self.threshold[index])
        lo, hi = int(self.cat_boundaries[ci]), int(self.cat_boundaries[ci + 1])
        cats = []
        for w in range(lo, hi):
            bits = int(self.cat_threshold[w])
            for b in range(32):
                if (bits >> b) & 1:
                    cats.append((w - lo) * 32 + b)
        return "||".join(str(c) for c in cats)


# ===================================================================== dump
def _objective_string(config: Config) -> Optional[str]:
    obj = config.objective
    if obj in ("none", "", None):
        return None
    if obj == "binary":
        return f"binary sigmoid:{config.sigmoid:g}"
    if obj == "multiclass":
        return f"multiclass num_class:{config.num_class}"
    if obj == "multiclassova":
        return (f"multiclassova num_class:{config.num_class} "
                f"sigmoid:{config.sigmoid:g}")
    if obj == "quantile":
        return f"quantile alpha:{config.alpha:g}"
    if obj == "huber":
        return f"huber alpha:{config.alpha:g}"
    if obj == "fair":
        return f"fair c:{config.fair_c:g}"
    if obj == "tweedie":
        return f"tweedie tweedie_variance_power:{config.tweedie_variance_power:g}"
    if obj == "lambdarank":
        return "lambdarank"
    if obj == "cross_entropy":
        return "cross_entropy"
    if obj == "cross_entropy_lambda":
        return "cross_entropy_lambda"
    return obj


def _feature_infos(mappers) -> List[str]:
    """Per-feature info strings (reference: bin.h:190-199 bin_info_string)."""
    from .. import binning
    infos = []
    for m in mappers:
        if m.is_trivial:
            infos.append("none")
        elif m.bin_type == binning.BIN_TYPE_CATEGORICAL:
            infos.append(":".join(str(c) for c in m.bin_2_categorical if c >= 0))
        else:
            infos.append(f"[{m.min_val:.17g}:{m.max_val:.17g}]")
    return infos


def _collect_model_trees(boosting, num_iteration: int = -1,
                         start_iteration: int = 0
                         ) -> Tuple[dict, List[ModelTree]]:
    """Header metadata + ModelTree list for either a trained GBDT or a
    LoadedGBDT, honoring start/num iteration windows
    (reference: gbdt_model_text.cpp:343-356)."""
    if isinstance(boosting, LoadedGBDT):
        meta = dict(boosting.meta)
        all_trees = list(boosting.trees)
        k = boosting.num_tree_per_iteration
    else:
        cfg = boosting.config
        ds = boosting.train_set
        k = boosting.num_tree_per_iteration
        meta = {
            "num_class": boosting.num_class,
            "num_tree_per_iteration": k,
            "label_index": 0,
            "max_feature_idx": ds.num_total_features - 1,
            "objective": _objective_string(cfg),
            "average_output": boosting.average_output,
            "feature_names": ds.get_feature_names(),
            "monotone_constraints": list(cfg.monotone_constraints),
            "feature_infos": _feature_infos(ds.mappers),
            "parameters": cfg.to_params(),
            "pandas_categorical": {int(k): list(v) for k, v in
                                   ds.pandas_categorical.items()},
        }
        all_trees = []
        if boosting.loaded is not None:
            all_trees.extend(boosting.loaded.trees)
        for ht in boosting.host_trees:
            all_trees.append(ModelTree.from_host(ht, ds.mappers))
    total_iteration = len(all_trees) // max(k, 1)
    start_iteration = min(max(start_iteration, 0), total_iteration)
    if num_iteration is not None and num_iteration > 0:
        end_iteration = min(start_iteration + num_iteration, total_iteration)
    else:
        end_iteration = total_iteration
    trees = all_trees[start_iteration * k:end_iteration * k]
    return meta, trees


def dump_model_text(boosting, num_iteration: int = -1,
                    start_iteration: int = 0) -> str:
    """Serialize to the v3 text format
    (reference: gbdt_model_text.cpp:311-403 SaveModelToString)."""
    meta, trees = _collect_model_trees(boosting, num_iteration, start_iteration)
    out = ["tree", f"version={K_MODEL_VERSION}",
           f"num_class={meta['num_class']}",
           f"num_tree_per_iteration={meta['num_tree_per_iteration']}",
           f"label_index={meta['label_index']}",
           f"max_feature_idx={meta['max_feature_idx']}"]
    if meta.get("objective"):
        out.append(f"objective={meta['objective']}")
    if meta.get("average_output"):
        out.append("average_output")
    out.append("feature_names=" + " ".join(meta["feature_names"]))
    if meta.get("monotone_constraints"):
        out.append("monotone_constraints=" +
                   " ".join(str(m) for m in meta["monotone_constraints"]))
    out.append("feature_infos=" + " ".join(meta["feature_infos"]))

    tree_strs = [f"Tree={i}\n" + t.to_string() + "\n"
                 for i, t in enumerate(trees)]
    out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
    out.append("")
    body = "\n".join(out) + "\n"
    body += "".join(tree_strs)
    body += "end of trees\n"

    # feature importances, sorted descending (gbdt_model_text.cpp:370-392)
    imp = np.zeros(meta["max_feature_idx"] + 1, np.float64)
    for t in trees:
        for f in t.split_feature:
            imp[f] += 1
    pairs = [(int(imp[i]), meta["feature_names"][i])
             for i in range(len(imp)) if imp[i] > 0]
    pairs.sort(key=lambda p: -p[0])
    body += "\nfeature_importances:\n"
    for cnt, name in pairs:
        body += f"{name}={cnt}\n"

    params = meta.get("parameters")
    if params:
        body += "\nparameters:\n"
        for key, val in params.items():
            if isinstance(val, (list, tuple)):
                val = ",".join(str(v) for v in val)
            body += f"[{key}: {val}]\n"
        body += "end of parameters\n"
    # pandas category lists so DataFrame prediction maps values the same way
    # after loading (reference: basic.py save_model appends
    # 'pandas_categorical:' JSON as the final line)
    pc = meta.get("pandas_categorical")
    if pc:
        import json as _json
        body += "\npandas_categorical:" + _json.dumps(
            {str(k): v for k, v in pc.items()}) + "\n"
    return body


def dump_model_json(boosting, num_iteration: int = -1,
                    start_iteration: int = 0) -> dict:
    """JSON model dump (reference: gbdt_model_text.cpp:26-116 DumpModel)."""
    meta, trees = _collect_model_trees(boosting, num_iteration, start_iteration)
    tree_info = []
    for i, t in enumerate(trees):
        tree_info.append({
            "tree_index": i,
            "num_leaves": t.num_leaves,
            "num_cat": t.num_cat,
            "shrinkage": t.shrinkage,
            "tree_structure": t.to_json_node(0),
        })
    return {
        "name": "tree",
        "version": K_MODEL_VERSION,
        "num_class": meta["num_class"],
        "num_tree_per_iteration": meta["num_tree_per_iteration"],
        "label_index": meta["label_index"],
        "max_feature_idx": meta["max_feature_idx"],
        "objective": meta.get("objective") or "",
        "average_output": bool(meta.get("average_output")),
        "feature_names": meta["feature_names"],
        "monotone_constraints": meta.get("monotone_constraints", []),
        "feature_infos": {
            name: info for name, info in zip(meta["feature_names"],
                                             meta["feature_infos"])},
        "tree_info": tree_info,
    }


# ===================================================================== load
def _parse_objective(obj_str: str, config: Config) -> None:
    """Apply an 'objective=' model line to the config
    (inverse of _objective_string)."""
    from ..config import _OBJECTIVE_ALIASES
    toks = obj_str.split()
    if not toks:
        return
    config.objective = _OBJECTIVE_ALIASES.get(toks[0], toks[0])
    for tok in toks[1:]:
        if ":" not in tok:
            continue
        key, val = tok.split(":", 1)
        if key == "num_class":
            config.num_class = int(val)
        elif key == "sigmoid":
            config.sigmoid = float(val)
        elif key in ("alpha", "fair_c", "tweedie_variance_power"):
            setattr(config, {"alpha": "alpha", "fair_c": "fair_c",
                             "tweedie_variance_power": "tweedie_variance_power"}[key],
                    float(val))


class LoadedGBDT:
    """A model restored from text: predicts over raw features via real
    thresholds; supports re-serialization and serving as an init model
    for continued training (reference: GBDT::LoadModelFromString,
    gbdt_model_text.cpp:417-520)."""

    def __init__(self, meta: dict, trees: List[ModelTree], config: Config):
        self.meta = meta
        self.trees = trees
        self.config = config
        self.num_class = meta["num_class"]
        self.num_tree_per_iteration = meta["num_tree_per_iteration"]
        self.average_output = bool(meta.get("average_output"))
        self.feature_names = meta["feature_names"]
        self.max_feature_idx = meta["max_feature_idx"]
        from ..objectives import create_objective
        try:
            self.objective = create_objective(config)
        except Exception:
            self.objective = None
        self.best_iteration = -1

    # ------------------------------------------------------------ basics
    @property
    def num_iteration(self) -> int:
        return len(self.trees) // max(self.num_tree_per_iteration, 1)

    @property
    def num_trees(self) -> int:
        return len(self.trees)

    def current_iteration(self) -> int:
        return self.num_iteration

    def _check_features(self, X) -> np.ndarray:
        pc = self.meta.get("pandas_categorical") or {}
        if hasattr(X, "dtypes") and pc:
            import pandas as pd
            X = X.copy()
            for ci, col in enumerate(X.columns):
                cats = pc.get(ci, pc.get(str(ci)))
                if cats is not None and str(X[col].dtype) == "category":
                    codes = np.asarray(
                        pd.Categorical(X[col], categories=cats).codes)
                    X[col] = np.where(codes >= 0,
                                      codes.astype(np.float64), np.nan)
        if hasattr(X, "values"):
            X = X.values
        if hasattr(X, "toarray"):
            X = X.toarray()
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[1] != self.max_feature_idx + 1:
            log.fatal(f"The number of features in data ({X.shape[1]}) is not "
                      f"the same as it was in training data "
                      f"({self.max_feature_idx + 1}).")
        return X

    # ----------------------------------------------------------- predict
    _oom_predict_chunk = 0       # predict-chunk degradation rung (serve OOM)

    def predict_raw(self, X, num_iteration: Optional[int] = None,
                    start_iteration: int = 0,
                    pred_early_stop: bool = False,
                    pred_early_stop_freq: int = 10,
                    pred_early_stop_margin: float = 10.0) -> np.ndarray:
        X = self._check_features(X)
        kwargs = dict(num_iteration=num_iteration,
                      start_iteration=start_iteration,
                      pred_early_stop=pred_early_stop,
                      pred_early_stop_freq=pred_early_stop_freq,
                      pred_early_stop_margin=pred_early_stop_margin)
        # same predict-chunk degradation rung as GBDT.predict_raw, so a
        # hot-swapped file-loaded model honors the serving layer's
        # OOM-rides-the-ladder contract: a RESOURCE_EXHAUSTED shrinks the
        # chunk and the request is retried and ANSWERED (chunking the
        # host loop is numerics-exact — rows never interact)
        while True:
            try:
                chunk = self._oom_predict_chunk
                if chunk and X.shape[0] > chunk:
                    return np.concatenate(
                        [self._predict_raw_chunk(X[a:a + chunk], **kwargs)
                         for a in range(0, X.shape[0], chunk)], axis=0)
                return self._predict_raw_chunk(X, **kwargs)
            except BaseException as e:    # noqa: BLE001 — reclassified
                if not self._maybe_degrade_predict_oom(e):
                    raise

    def _maybe_degrade_predict_oom(self, exc: BaseException) -> bool:
        """The GBDT predict-OOM rung for file-loaded models: halve the
        effective predict chunk (floor 16k rows), record the event, retry.
        Bounded — once the floor is reached the error re-raises."""
        from .. import distributed
        from ..utils import faults, profiling
        nxt = faults.next_predict_chunk(
            exc, self._oom_predict_chunk,
            getattr(self.config, "hist_oom_fallback", True))
        if nxt is None:
            return False
        self._oom_predict_chunk = nxt
        action = f"predict_chunk_rows -> {self._oom_predict_chunk}"
        distributed.record_degradation({
            "kind": "oom_predict", "iteration": -1, "level": 0,
            "action": action, "error": str(exc)[:200],
            # allocator/host snapshot at failure (no traffic-model
            # prediction here: a file-loaded model has no training shape)
            "memory": profiling.sample_memory()})
        profiling.set_gauge("predict_oom_chunk_rows",
                            float(self._oom_predict_chunk))
        log.warning(f"RESOURCE_EXHAUSTED in loaded-model predict: "
                    f"degrading ({action}) and retrying")
        return True

    def _predict_raw_chunk(self, X, num_iteration=None, start_iteration=0,
                           pred_early_stop=False, pred_early_stop_freq=10,
                           pred_early_stop_margin=10.0) -> np.ndarray:
        from ..utils import faults
        sf = faults.serve_faults(self.config)
        if sf is not None:
            # same serve-side injection points as GBDT._predict_raw_impl,
            # so file-loaded models behave identically under the serving
            # layer's fault drills (serve_smoke hot-swaps to one)
            faults.maybe_slow_predict(sf)
            faults.maybe_oom_predict(sf)
        k = self.num_tree_per_iteration
        total = self.num_iteration
        if num_iteration is None or num_iteration <= 0:
            end = total
        else:
            end = min(start_iteration + num_iteration, total)
        out = np.zeros((X.shape[0], k), np.float64)
        active = np.ones(X.shape[0], dtype=bool)
        use_es = pred_early_stop and not self.average_output
        from ..models.gbdt import _accumulate_active, _early_stop_mask
        for it in range(start_iteration, end):
            for c in range(k):
                delta = self.trees[it * k + c].predict(X)
                _accumulate_active(out, c, delta, active, use_es)
            if use_es and (it - start_iteration + 1) % pred_early_stop_freq == 0:
                active &= ~_early_stop_mask(out, k, pred_early_stop_margin)
                if not active.any():
                    break
        if self.average_output:
            out /= max(end - start_iteration, 1)
        return out if k > 1 else out[:, 0]

    def predict(self, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                start_iteration: int = 0, **kwargs) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration, start_iteration, **kwargs)
        if raw_score or self.objective is None:
            return raw
        import jax.numpy as jnp
        return np.asarray(self.objective.convert_output(jnp.asarray(raw)))

    def predict_leaf(self, X, num_iteration: Optional[int] = None,
                     start_iteration: int = 0) -> np.ndarray:
        X = self._check_features(X)
        k = self.num_tree_per_iteration
        total = self.num_iteration
        if num_iteration is None or num_iteration <= 0:
            end = total
        else:
            end = min(start_iteration + num_iteration, total)
        cols = [self.trees[it * k + c].leaf_index(X)
                for it in range(start_iteration, end) for c in range(k)]
        return np.stack(cols, axis=1) if cols else np.zeros((X.shape[0], 0), np.int32)

    def predict_contrib(self, X, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> np.ndarray:
        from .shap import predict_contrib_trees
        X = self._check_features(X)
        k = self.num_tree_per_iteration
        total = self.num_iteration
        if num_iteration is None or num_iteration <= 0:
            end = total
        else:
            end = min(start_iteration + num_iteration, total)
        trees = [self.trees[it * k + c]
                 for it in range(start_iteration, end) for c in range(k)]
        return predict_contrib_trees(trees, X, self.max_feature_idx + 1, k,
                                     average=self.average_output)

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        imp = np.zeros(self.max_feature_idx + 1, np.float64)
        for t in self.trees:
            for i in range(t.num_leaves - 1):
                if importance_type == "split":
                    imp[t.split_feature[i]] += 1.0
                else:
                    imp[t.split_feature[i]] += max(float(t.split_gain[i]), 0.0)
        return imp

    # ----------------------------------------------- Booster API adapters
    def eval_set(self, feval=None):
        log.fatal("Booster loaded from a model file has no attached data to evaluate")

    def train_one_iter(self, grad=None, hess=None):
        log.fatal("Cannot continue training a loaded Booster directly; pass it "
                  "as init_model to train()")


def load_model(model_str: str, config: Optional[Config] = None) -> LoadedGBDT:
    """Parse a v3 model text (reference: gbdt_model_text.cpp:417-520).

    Truncated or garbage input fails with a descriptive
    "corrupt or truncated model file" error naming the tree block /
    section / line — never a bare KeyError/IndexError that lets a
    half-written file parse into a silently shorter model."""
    config = config or Config()
    lines = model_str.split("\n")
    kv: Dict[str, str] = {}
    i = 0
    # header: key=value until the first Tree= block
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree=") or line == "end of trees":
            break
        if line and "=" in line:
            key, val = line.split("=", 1)
            kv[key] = val
        elif line == "average_output":
            kv["average_output"] = "1"
        i += 1

    trees: List[ModelTree] = []
    saw_end_of_trees = False
    while i < len(lines):
        line = lines[i].strip()
        if line == "end of trees":
            saw_end_of_trees = True
            break
        if line.startswith("Tree="):
            tree_line = i + 1          # 1-based line of the Tree= marker
            tkv: Dict[str, str] = {}
            i += 1
            while i < len(lines):
                tl = lines[i].strip()
                if not tl or tl.startswith("Tree=") or tl == "end of trees":
                    break
                if "=" in tl:
                    key, val = tl.split("=", 1)
                    tkv[key] = val
                i += 1
            try:
                trees.append(ModelTree.from_kv(tkv))
            except (KeyError, IndexError, ValueError, OverflowError) as e:
                msg = f"missing {e} section" if isinstance(e, KeyError) \
                    else str(e)
                log.fatal(f"corrupt or truncated model file: tree block "
                          f"{len(trees)} (line {tree_line}): {msg}")
        else:
            i += 1
    if not saw_end_of_trees:
        log.fatal(f"corrupt or truncated model file: missing the "
                  f"'end of trees' sentinel (input ends at line "
                  f"{len(lines)} after {len(trees)} complete tree blocks "
                  f"— a partial write?)")

    # parameters block (gbdt_model_text.cpp:507-516 loaded_parameter_)
    params: Dict[str, str] = {}
    pandas_categorical: Dict[int, list] = {}
    in_params = False
    for line in lines[i:]:
        line = line.strip()
        if line == "parameters:":
            in_params = True
        elif line == "end of parameters":
            in_params = False
        elif in_params and line.startswith("[") and ":" in line:
            key, val = line[1:-1].split(":", 1)
            params[key.strip()] = val.strip()
        elif line.startswith("pandas_categorical:"):
            import json as _json
            try:
                parsed = _json.loads(line[len("pandas_categorical:"):])
                if isinstance(parsed, dict):
                    pandas_categorical = {int(k): v for k, v in parsed.items()}
            except (ValueError, TypeError):
                pass

    try:
        if "objective" in kv:
            _parse_objective(kv["objective"], config)
        if "num_class" in kv:
            config.num_class = int(kv["num_class"])

        meta = {
            "num_class": int(kv.get("num_class", "1")),
            "num_tree_per_iteration": int(kv.get("num_tree_per_iteration", "1")),
            "label_index": int(kv.get("label_index", "0")),
            "max_feature_idx": int(kv.get("max_feature_idx", "0")),
            "objective": kv.get("objective"),
            "average_output": "average_output" in kv,
            "feature_names": kv.get("feature_names", "").split(),
            "monotone_constraints": [int(x) for x in
                                     kv.get("monotone_constraints", "").split()],
            "feature_infos": kv.get("feature_infos", "").split(),
            "parameters": params,
            "pandas_categorical": pandas_categorical,
        }
    except (ValueError, OverflowError) as e:
        log.fatal(f"corrupt or truncated model file: header: {e}")
    return LoadedGBDT(meta, trees, config)
