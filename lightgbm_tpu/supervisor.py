"""Gang supervisor: launch a multi-process training gang, watch it, and
relaunch it from the latest valid checkpoint when a rank dies or hangs.

The restart half of the training-supervision layer (the detection half —
heartbeat + collective watchdog — lives in ``distributed.py``). The
reference's answer to a mid-boost worker failure is operational: sockets
time out (linkers_socket.cpp TimeOut), the job dies, an external scheduler
restarts it and ``snapshot_freq`` models limit the loss. Here the whole
loop is a library primitive, and PR 2's checkpoint subsystem makes the
restart BIT-IDENTICAL: kill a rank at iteration k, the supervisor tears
down the survivors, relaunches the gang, the gang resumes from the newest
valid checkpoint, and the final model text equals the uninterrupted run's
byte for byte (tests/test_supervisor.py proves it for kill, hang and
kill-during-checkpoint-write).

Usage — ``fn`` is a picklable ``fn(rank, *args)`` exactly as in
``distributed.spawn``; it should train with a checkpoint callback AND
``resume_from`` pointing at the same directory, so a relaunched
incarnation continues instead of restarting. Every worker must hold the
FULL dataset (replicated — the reference's ``pre_partition=false`` mode):
that is what makes each rank's trainer state identical, so rank 0's
checkpoint restores the whole gang bit-identically. Multi-process
pre-partitioned datasets keep process-local score caches and are
REJECTED by ``train(resume_from=...)``::

    def work(rank, ckdir):
        ds = lgb.Dataset(X_full, label=y_full)     # replicated per rank
        booster = lgb.train(params, ds, rounds,
                            callbacks=[lgb.checkpoint_callback(ckdir)],
                            resume_from=ckdir)
        return booster.model_to_string()

    report = lgb.supervisor.run_supervised(work, nproc=2, args=(ckdir,),
                                           checkpoint_dir=ckdir)
    report.result      # rank 0's return value
    report.restarts    # how many gang relaunches it took

Children run with ``LGBM_TPU_SUPERVISED=1``: a rank whose collective
watchdog fires exits with ``WATCHDOG_EXIT_CODE`` (writing a JSON diagnosis
the supervisor folds into its report — the diagnosis references the
rank's flushed flight-recorder JSONL, see ``telemetry.py`` and
``GangFailure.flight_recorders``, so every failure leaves a
per-iteration post-mortem next to the stall verdict) instead of raising,
since a rank stuck inside a native collective cannot be unstuck from
Python. A rank
the cross-rank integrity check (``integrity_check_period``) identifies as
holding silently-diverged state exits with ``DIVERGENCE_EXIT_CODE`` the
same way — the supervisor charges ITS restart budget (the divergence vote
is hard evidence against that rank, unlike a watchdog exit) and restores
it from the last valid checkpoint, shrinking it away once the budget is
spent. One-shot
``LGBM_TPU_FAULT_*`` injections are stripped from relaunched incarnations
(a kill-at-iteration-k fault would otherwise re-fire forever at the exact
iteration the checkpoint resumes from); ``LGBM_TPU_RESTART_COUNT`` tells
children (and their telemetry) which incarnation they are.

ELASTIC gangs: a rank whose spawn itself fails (exit
``SPAWN_FAIL_EXIT_CODE``), or that keeps failing past the per-rank
``rank_restart_budget`` at one world size, is classified PERMANENTLY lost
— the supervisor then relaunches the gang at world size n-1 (down to
``min_world_size``) instead of giving up, recording a ``GangShrink`` in
the report and the ``supervisor_world_size`` gauge. Ranks renumber to
``0..n-2``, so ``fn`` should derive its data slice from
``jax.process_index()/process_count()`` AFTER distributed init;
pre-partitioned runs resume across the shrink because sharded checkpoints
re-partition their per-rank score-cache shards onto the new world size on
load (see lightgbm_tpu/checkpoint.py).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from . import distributed
from .utils import log
from .utils import profiling

# env vars whose faults are one-shot: armed for the FIRST incarnation only
_FAULT_ENV_PREFIX = "LGBM_TPU_FAULT_"


@dataclass
class GangFailure:
    """One failed gang incarnation: which rank(s) went down, how, and what
    the watchdog diagnosis (if any) said."""
    incarnation: int
    failed_ranks: List[int]
    exit_codes: dict
    reason: str
    watchdog: List[dict] = field(default_factory=list)
    world_size: int = 0               # nproc of this incarnation

    @property
    def watchdog_fired(self) -> bool:
        return bool(self.watchdog) or any(
            c == distributed.WATCHDOG_EXIT_CODE
            for c in self.exit_codes.values())

    @property
    def spawn_failed_ranks(self) -> List[int]:
        """Ranks whose process never came up (exit SPAWN_FAIL_EXIT_CODE):
        classified permanently lost without burning the per-rank budget."""
        return sorted(r for r, c in self.exit_codes.items()
                      if c == distributed.SPAWN_FAIL_EXIT_CODE)

    @property
    def flight_recorders(self) -> List[str]:
        """Per-rank flight-recorder JSONL paths referenced by this
        incarnation's watchdog/divergence diagnoses (telemetry.py): the
        per-iteration post-mortems of the failed gang. Ranks that died
        by harness kill flush too, but reference themselves only from
        the JSONL — find those as flight_rank*.jsonl in the diag dir."""
        return sorted({d["flight_recorder"] for d in self.watchdog
                       if d.get("flight_recorder")})


@dataclass
class GangShrink:
    """One gang-shrink event: the supervisor classified rank(s) as
    permanently lost and relaunched the gang at a smaller world size (the
    surviving data/ranks renumber to 0..to_nproc-1; a sharded checkpoint
    re-partitions on load, see checkpoint.py)."""
    incarnation: int                  # the incarnation that FAILED
    from_nproc: int
    to_nproc: int
    lost_ranks: List[int]             # ranks (old numbering) given up on
    reason: str


@dataclass
class SupervisorReport:
    """Outcome of a supervised gang run."""
    result: Any
    restarts: int
    failures: List[GangFailure]
    wall_time: float
    world_size: int = 0               # nproc the gang FINISHED at
    shrinks: List[GangShrink] = field(default_factory=list)
    # path of the auto-generated post-mortem report (postmortem.py)
    # when any incarnation failed; None on a clean first-try run
    postmortem: Optional[str] = None


class GangFailedError(RuntimeError):
    """The gang kept failing past ``max_restarts``; carries the failure
    history for diagnosis (and the path of the auto-generated
    post-mortem report classifying it, when analysis succeeded)."""

    def __init__(self, msg: str, failures: List[GangFailure],
                 postmortem: Optional[str] = None):
        super().__init__(msg)
        self.failures = failures
        self.postmortem = postmortem


def _run_postmortem(diag_dir: str, failures: List[GangFailure],
                    checkpoint_dir: Optional[str]) -> Optional[str]:
    """Analyze the failed gang's breadcrumbs (flight JSONLs in the diag
    dir + the consumed watchdog/divergence diags riding the GangFailure
    history + checkpoint manifests) and write the classified report next
    to them. Best-effort by contract: a failing analyzer must never
    replace the real failure path — it warns and returns None."""
    try:
        from . import postmortem
        pm = postmortem.analyze(diag_dir, checkpoint_dir=checkpoint_dir,
                                failures=failures)
        path = postmortem.write_report(pm, diag_dir)
        log.warning(f"supervisor: post-mortem verdict "
                    f"{pm.verdict.upper()}"
                    + (f" (rank {pm.rank})" if pm.rank is not None else "")
                    + f" — report at {path}")
        return path
    except Exception as e:           # noqa: BLE001 — see docstring
        log.warning(f"supervisor: post-mortem analysis failed: {e}")
        return None


def _read_diags(diag_dir: str) -> List[dict]:
    import json
    out = []
    try:
        names = sorted(os.listdir(diag_dir))
    except OSError:
        return out
    for name in names:
        # watchdog_rank*.json: collective-stall diagnoses;
        # divergence_rank*.json: cross-rank integrity verdicts (the
        # corrupt rank names itself + the fingerprint table before
        # exiting with DIVERGENCE_EXIT_CODE)
        if not name.startswith(("watchdog_rank", "divergence_rank")):
            continue
        try:
            with open(os.path.join(diag_dir, name)) as fh:
                out.append(json.load(fh))
        except (OSError, ValueError):
            pass
        try:                              # consumed: one diag per failure
            os.unlink(os.path.join(diag_dir, name))
        except OSError:
            pass
    return out


class _Incarnation:
    """One launched gang: processes + result queue + env bookkeeping."""

    def __init__(self, fn, nproc, args, per_rank_args, devices_per_proc,
                 incarnation, heartbeat_port, diag_dir):
        import multiprocessing as mp
        self.nproc = nproc
        port = distributed.free_port()
        machines = ",".join(f"127.0.0.1:{port}" for _ in range(nproc))
        ctx = mp.get_context("spawn")
        self.q = ctx.Queue()
        # children inherit os.environ at start(): install the supervision
        # env, strip one-shot faults on relaunches, then restore
        override = {
            distributed._SUPERVISED_ENV: "1",
            distributed._HEARTBEAT_ADDR_ENV: f"127.0.0.1:{heartbeat_port}",
            distributed._DIAG_DIR_ENV: diag_dir,
            distributed._RESTART_COUNT_ENV: str(incarnation),
        }
        removed = {}
        if incarnation > 0:
            for k in list(os.environ):
                if k.startswith(_FAULT_ENV_PREFIX):
                    removed[k] = os.environ.pop(k)
        saved = {k: os.environ.get(k) for k in override}
        os.environ.update(override)
        try:
            self.procs = [ctx.Process(
                target=distributed._spawn_child,
                args=(self.q, fn, r, nproc, machines, devices_per_proc,
                      args if per_rank_args is None
                      else (per_rank_args[r],) + tuple(args)))
                for r in range(nproc)]
            for p in self.procs:
                p.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            os.environ.update(removed)

    def teardown(self) -> None:
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + 10
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
        for p in self.procs:
            if p.is_alive():              # SIGTERM swallowed in native code
                p.kill()
                p.join(timeout=10)
        self.q.close()
        self.q.cancel_join_thread()


def run_supervised(fn: Callable, nproc: int = 2, args: tuple = (),
                   per_rank_args: Optional[list] = None,
                   devices_per_proc: Optional[int] = None,
                   checkpoint_dir: Optional[str] = None,
                   max_restarts: int = 2,
                   timeout: Optional[float] = 600.0,
                   diag_dir: Optional[str] = None,
                   rank_restart_budget: int = 1,
                   min_world_size: int = 1) -> SupervisorReport:
    """Run ``fn(rank, *args)`` as a supervised, ELASTIC ``nproc``-process
    gang.

    Like ``distributed.spawn`` but fault-tolerant: when any rank exits
    nonzero (killed, crashed, or watchdog-tripped) the surviving ranks are
    torn down and the WHOLE gang relaunches — ranks share compiled SPMD
    programs, so a partial gang cannot continue — up to ``max_restarts``
    times. ``fn`` is responsible for resuming from ``checkpoint_dir`` (via
    ``train(resume_from=...)``); the supervisor guarantees relaunch, fault
    disarming, the heartbeat side-channel, and failure diagnosis.

    The gang SHRINKS instead of giving up when a rank is classified
    permanently lost: its spawn itself failed (exit
    ``SPAWN_FAIL_EXIT_CODE``), or the same rank has now failed more than
    ``rank_restart_budget`` times at the current world size. The next
    incarnation launches with one fewer process (ranks renumber to
    ``0..n-2``; ``fn`` should derive its data slice from
    ``jax.process_index()/process_count()`` after init) and resumes from
    the newest valid checkpoint — sharded checkpoints re-partition their
    score-cache shards onto the new world size on load (checkpoint.py).
    Shrinks consume the same ``max_restarts`` budget as same-size
    relaunches and are recorded in ``SupervisorReport.shrinks`` and the
    ``supervisor_world_size`` health gauge. Shrinking requires
    ``per_rank_args is None`` (a static per-rank payload pins the world
    size) and stops at ``min_world_size``.

    Args:
      fn, nproc, args, per_rank_args, devices_per_proc: as in
        ``distributed.spawn``.
      checkpoint_dir: advisory — recorded in errors so an operator knows
        where the resumable state lives.
      max_restarts: gang relaunch budget (per run, not per rank).
      timeout: per-incarnation deadline; a gang that neither finishes nor
        fails within it counts as a failure (and is relaunched).
      diag_dir: where ranks' watchdog diagnoses land (default: a
        ``supervisor_diag`` dir inside checkpoint_dir, or a temp dir).
      rank_restart_budget: same-rank failures tolerated at one world size
        before the rank is declared permanently lost and the gang shrinks.
      min_world_size: floor the gang may shrink to.

    Returns a SupervisorReport with rank 0's result and the restart
    history; raises GangFailedError after the budget is exhausted.
    """
    import queue as _queue
    if per_rank_args is not None and len(per_rank_args) != nproc:
        raise ValueError(f"per_rank_args has {len(per_rank_args)} entries "
                         f"for {nproc} ranks")
    if diag_dir is None:
        if checkpoint_dir:
            diag_dir = os.path.join(checkpoint_dir, "supervisor_diag")
        else:
            # no durable home for diagnoses: use a temp dir rather than
            # littering the caller's cwd
            import tempfile
            diag_dir = tempfile.mkdtemp(prefix="lgbm_supervisor_diag_")
    os.makedirs(diag_dir, exist_ok=True)
    failures: List[GangFailure] = []
    shrinks: List[GangShrink] = []
    world = int(nproc)
    rank_failures: dict = {}          # rank -> failures at CURRENT world
    t0 = time.monotonic()
    profiling.set_gauge("supervisor_world_size", world)
    for incarnation in range(max_restarts + 1):
        hb_port = distributed.free_port()
        gang = _Incarnation(fn, world, args, per_rank_args,
                            devices_per_proc, incarnation, hb_port,
                            diag_dir)
        profiling.set_gauge("supervisor_incarnation", incarnation)
        results = {}
        failure = None
        dead_codes = {}
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while len(results) < world and failure is None:
                try:
                    rank, ok, payload = gang.q.get(timeout=0.5)
                    if not ok:
                        failure = (f"rank {rank} raised:\n"
                                   f"{str(payload)[-2000:]}")
                        dead_codes = {rank: None}
                        break
                    results[rank] = payload
                    continue
                except _queue.Empty:
                    pass
                # exit codes captured at DETECTION time: after teardown
                # the healthy survivors we SIGTERM would also read as
                # "died", obscuring which rank actually failed
                dead_codes = {r: p.exitcode for r, p in enumerate(gang.procs)
                              if r not in results and not p.is_alive()
                              and p.exitcode not in (0, None)}
                if dead_codes:
                    kinds = ", ".join(
                        f"rank {r} exit {c}"
                        + (" (watchdog)" if c ==
                           distributed.WATCHDOG_EXIT_CODE else
                           (" (spawn failed)" if c ==
                            distributed.SPAWN_FAIL_EXIT_CODE else
                            (" (diverged)" if c ==
                             distributed.DIVERGENCE_EXIT_CODE else "")))
                        for r, c in sorted(dead_codes.items()))
                    failure = f"gang member(s) died: {kinds}"
                    break
                if deadline is not None and time.monotonic() > deadline:
                    missing = [r for r in range(world) if r not in results]
                    failure = (f"incarnation timed out after {timeout}s "
                               f"waiting for ranks {missing}")
                    break
        finally:
            gang.teardown()
        if failure is None:
            profiling.set_gauge("supervisor_restarts", incarnation)
            return SupervisorReport(result=results.get(0),
                                    restarts=incarnation,
                                    failures=failures,
                                    wall_time=time.monotonic() - t0,
                                    world_size=world, shrinks=shrinks,
                                    postmortem=(_run_postmortem(
                                        diag_dir, failures,
                                        checkpoint_dir)
                                        if failures else None))
        diags = _read_diags(diag_dir)
        rec = GangFailure(
            incarnation=incarnation,
            failed_ranks=sorted(dead_codes) or
            [r for r in range(world) if r not in results],
            exit_codes=dead_codes, reason=failure, watchdog=diags,
            world_size=world)
        failures.append(rec)
        sus = {s for d in diags for s in (d.get("suspects") or [])}
        # ---- permanent-loss classification -> gang shrink
        # a DIVERGENCE exit is hard evidence against the exiting rank (it
        # held minority state by the gang's own vote), so like a kill/OOM
        # it charges that rank's budget and shields collateral exits
        hard = {r for r, c in rec.exit_codes.items()
                if c in (137, distributed.SPAWN_FAIL_EXIT_CODE,
                         distributed.DIVERGENCE_EXIT_CODE)}
        for r in rec.failed_ranks:
            if r not in rec.exit_codes:
                # incarnation timeout: ranks merely missing from results
                # carry no evidence of THEIR failure (a slow-but-healthy
                # rank must not be classified permanently lost)
                continue
            if rec.exit_codes.get(r) == distributed.WATCHDOG_EXIT_CODE:
                # a watchdog exit is the SYMPTOM of a stalled gang (this
                # rank declared a peer dead/hung), not evidence the rank
                # itself is bad — it must not burn its restart budget
                continue
            if hard and r not in hard:
                # when some rank died HARD (kill/OOM 137, spawn failure)
                # in the same incarnation, generic nonzero exits alongside
                # it are likely collateral (e.g. coordination-service
                # calls failing once the peer is gone) — charging them
                # would mis-target the shrink at healthy ranks
                continue
            rank_failures[r] = rank_failures.get(r, 0) + 1
        lost = sorted(set(rec.spawn_failed_ranks)
                      | {r for r in rec.failed_ranks
                         if rank_failures.get(r, 0)
                         > max(0, int(rank_restart_budget))})
        shrink = None
        if lost and per_rank_args is None \
                and world - len(lost) >= max(1, int(min_world_size)):
            why = ", ".join(
                f"rank {r} " + ("spawn failed"
                                if r in rec.spawn_failed_ranks else
                                f"failed {rank_failures[r]}x (budget "
                                f"{rank_restart_budget})")
                for r in lost)
            shrink = GangShrink(incarnation=incarnation, from_nproc=world,
                                to_nproc=world - len(lost),
                                lost_ranks=lost, reason=why)
            shrinks.append(shrink)
            world -= len(lost)
            rank_failures = {}        # new gang numbering: counts reset
            profiling.set_gauge("supervisor_world_size", world)
            profiling.set_gauge("supervisor_shrinks", len(shrinks))
        log.warning(
            f"supervisor: incarnation {incarnation} failed ({failure})"
            + (f"; watchdog suspects rank(s) "
               f"{sorted(sus)} at iteration "
               f"{max((d.get('iteration', -1) for d in diags), default=-1)}"
               if diags else "")
            + (f"; rank(s) {shrink.lost_ranks} permanently lost "
               f"({shrink.reason}) — SHRINKING gang "
               f"{shrink.from_nproc} -> {shrink.to_nproc}" if shrink else "")
            + (f"; relaunching from {checkpoint_dir}"
               if incarnation < max_restarts and checkpoint_dir else
               ("; relaunching" if incarnation < max_restarts else "")))
    profiling.set_gauge("supervisor_restarts", max_restarts + 1)
    last = failures[-1]
    pm_path = _run_postmortem(diag_dir, failures, checkpoint_dir)
    raise GangFailedError(
        f"gang failed {len(failures)} time(s), exceeding max_restarts="
        f"{max_restarts}. Last failure: {last.reason}"
        + (f" (watchdog diagnosis: "
           f"{distributed.format_timeout_message(last.watchdog[0].get('rank'), last.watchdog[0].get('iteration'), last.watchdog[0].get('suspects'), last.watchdog[0].get('phase'), last.watchdog[0].get('deadline'))})"
           if last.watchdog else "")
        + (f". Post-mortem report: {pm_path}" if pm_path else "")
        + (f". Resumable checkpoints: {checkpoint_dir}"
           if checkpoint_dir else ""),
        failures, postmortem=pm_path)


def train_supervised(params: dict, data, label=None,
                     num_boost_round: int = 100, nproc: int = 2,
                     checkpoint_dir: str = "", checkpoint_period: int = 1,
                     devices_per_proc: Optional[int] = None,
                     timeout: Optional[float] = 900.0,
                     **train_kwargs):
    """Fault-tolerant distributed training: an ``nproc``-process gang over
    REPLICATED data (every worker holds the full dataset and takes its
    device shards through the data/voting/feature-parallel learners — the
    reference's ``pre_partition=false`` mode), checkpointing every
    ``checkpoint_period`` iterations and resuming BIT-IDENTICALLY across
    gang restarts.

    Relaunch cost: pass ``compile_cache_dir`` in ``params`` (a shared
    persistent XLA compile cache path) and every relaunched incarnation
    starts HOT — the resume path AOT-warms the training programs
    (``GBDT.warm_start``) against the disk cache, so a gang restart pays
    zero fused-step XLA recompiles instead of the full first-iteration
    compile wall (see README "Compile wall").

    Replication is what makes the restart exact: with every rank's trainer
    state identical (SPMD over replicated rows), rank 0's checkpoint
    restores the whole gang. Pre-partitioned datasets keep process-LOCAL
    score caches that a rank-0 checkpoint cannot restore on other ranks —
    engine.train rejects that resume combination (see ``resume_from``).

    Returns (Booster, SupervisorReport)."""
    if not checkpoint_dir:
        raise ValueError("train_supervised needs a checkpoint_dir")
    params = dict(params or {})
    params.setdefault("tree_learner", "data")
    cfg_restarts = int(params.get("max_restarts", 2))
    report = run_supervised(
        _supervised_train_fn,
        nproc=nproc,
        args=(data, label, params, num_boost_round, checkpoint_dir,
              checkpoint_period, dict(train_kwargs)),
        devices_per_proc=devices_per_proc, checkpoint_dir=checkpoint_dir,
        max_restarts=cfg_restarts, timeout=timeout,
        rank_restart_budget=int(params.get("rank_restart_budget", 1)),
        min_world_size=int(params.get("min_world_size", 1)))
    from .booster import Booster
    return Booster(params=params, model_str=report.result), report


def _supervised_train_fn(rank, data, label, params, num_boost_round,
                         checkpoint_dir, checkpoint_period, train_kwargs):
    """Per-worker body of train_supervised (module-level so spawn can
    pickle it): full replicated Dataset + checkpointed, resumable train —
    every incarnation after the first resumes from the newest valid
    checkpoint."""
    from . import callback as callback_mod
    from .basic import Dataset
    from .engine import train as _train
    ds = Dataset(data, label=label, params=dict(params),
                 free_raw_data=False)
    cbs = list(train_kwargs.pop("callbacks", []) or [])
    cbs.append(callback_mod.checkpoint(checkpoint_dir,
                                       period=checkpoint_period))
    booster = _train(params, ds, num_boost_round, callbacks=cbs,
                     resume_from=checkpoint_dir, **train_kwargs)
    return booster.model_to_string()
