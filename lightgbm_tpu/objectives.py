"""Objective functions: per-row gradients/hessians on device.

TPU-native analog of the reference objective layer
(reference: src/objective/*.hpp, abstract interface
include/LightGBM/objective_function.h: GetGradients(:37), BoostFromScore(:51),
ConvertOutput(:67), NumModelPerIteration(:57), RenewTreeOutput(:46)).
The reference's per-row OpenMP loops become vectorized jnp expressions;
weights are folded into grad/hess exactly as the reference does.

Formulas are carried over 1:1 with file:line citations on each class.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .utils import log

K_EPSILON = 1e-15


def _percentile(data: np.ndarray, alpha: float) -> float:
    """reference: regression_objective.hpp:17-47 PercentileFun (unweighted)."""
    cnt = len(data)
    if cnt <= 1:
        return float(data[0]) if cnt else 0.0
    d = np.sort(data)[::-1]  # descending; pos counts from the top
    float_pos = (1.0 - alpha) * cnt
    pos = int(float_pos)
    if pos < 1:
        return float(d[0])
    if pos >= cnt:
        return float(d[-1])
    bias = float_pos - pos
    v1, v2 = float(d[pos - 1]), float(d[pos])
    return v1 - (v1 - v2) * bias


def _weighted_percentile(data: np.ndarray, weight: np.ndarray, alpha: float) -> float:
    """reference: regression_objective.hpp:49-87 WeightedPercentileFun."""
    cnt = len(data)
    if cnt <= 1:
        return float(data[0]) if cnt else 0.0
    order = np.argsort(data, kind="stable")
    d = data[order]
    cdf = np.cumsum(weight[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, cnt - 1)
    if pos == 0 or pos == cnt - 1:
        return float(d[pos])
    v1, v2 = float(d[pos - 1]), float(d[pos])
    if pos + 1 < cnt and cdf[pos + 1] - cdf[pos] >= 1.0:
        return (threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1
    return v2


class ObjectiveFunction:
    """Base objective (reference: include/LightGBM/objective_function.h)."""

    name = "base"
    num_model_per_iteration = 1
    is_constant_hessian = False
    need_renew_tree_output = False
    # False when get_grad_hess has host-side state (e.g. a numpy RNG draw)
    # that would freeze at trace time inside a jitted training step — such
    # objectives must run the phase-by-phase path (gbdt._fused_ok)
    jit_safe_gradients = True

    def __init__(self, config: Config):
        self.config = config

    def init(self, label: np.ndarray, weight: Optional[np.ndarray],
             groups: Optional[np.ndarray] = None) -> None:
        self.label_np = np.asarray(label, dtype=np.float64)
        self.weight_np = (np.asarray(weight, dtype=np.float64)
                         if weight is not None else None)
        self.num_data = len(self.label_np)
        self.label = jnp.asarray(self.label_np, dtype=jnp.float32)
        self.weight = (jnp.asarray(self.weight_np, dtype=jnp.float32)
                       if weight is not None else None)

    def _apply_weight(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def get_grad_hess(self, score: jax.Array):
        raise NotImplementedError

    # ------------------------------------------------- traced-program use
    def device_consts(self) -> dict:
        """Every device-resident array this objective closes over in
        ``get_grad_hess`` (label, weight, and subclass derivatives such as
        the binary label_sign/label_weight or the multiclass onehot).

        A jitted training step that calls ``get_grad_hess`` directly
        embeds these O(N) arrays as CONSTANTS of the compiled program —
        and every label-derived subexpression (``label_sign * sigmoid``,
        the softmax onehot subtraction setup, ...) becomes dataset-
        constant compute XLA constant-folds AT COMPILE TIME, taking
        multi-second alarms per instruction at 10M-row scale
        (BENCH_r04). The fused step instead fetches this dict once,
        passes it as program OPERANDS, and traces ``get_grad_hess``
        under :meth:`bound` so the arrays enter the program as
        parameters that cannot be folded."""
        return {k: v for k, v in vars(self).items()
                if isinstance(v, jax.Array)}

    def bound(self, consts: dict):
        """Context manager substituting ``device_consts``-shaped values
        (typically tracers, inside a jit trace) for the objective's
        device arrays, restoring the originals on exit."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            old = {k: getattr(self, k) for k in consts}
            try:
                for k, v in consts.items():
                    setattr(self, k, v)
                yield self
            finally:
                for k, v in old.items():
                    setattr(self, k, v)
        return _ctx()

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, raw: jax.Array) -> jax.Array:
        return raw

    def renew_tree_output(self, pred_leaf: np.ndarray, score: np.ndarray,
                          num_leaves: int) -> Optional[np.ndarray]:
        """Per-leaf output refresh for L1-family objectives
        (reference: objective_function.h:46 RenewTreeOutput;
        regression_objective.hpp:253-263, 537-548, 640-652). Returns new leaf
        values [num_leaves] or None."""
        return None


# ------------------------------------------------------------- regression
class RegressionL2(ObjectiveFunction):
    """reference: regression_objective.hpp:93-201 (RegressionL2loss)."""
    name = "regression"
    is_constant_hessian = True

    def init(self, label, weight, groups=None):
        if self.config.reg_sqrt:
            label = np.sign(label) * np.sqrt(np.abs(label))
        super().init(label, weight, groups)

    def get_grad_hess(self, score):
        return self._apply_weight(score - self.label, jnp.ones_like(score))

    def boost_from_score(self, class_id: int = 0) -> float:
        # reference: regression_objective.hpp:173-198 (weighted mean label)
        if self.weight_np is not None:
            return float(np.sum(self.label_np * self.weight_np) / np.sum(self.weight_np))
        return float(np.mean(self.label_np))

    def convert_output(self, raw):
        if self.config.reg_sqrt:
            return jnp.sign(raw) * raw * raw
        return raw


class RegressionL1(RegressionL2):
    """reference: regression_objective.hpp:207-290 (RegressionL1loss)."""
    name = "regression_l1"
    need_renew_tree_output = True

    def get_grad_hess(self, score):
        diff = score - self.label
        if self.weight is not None:
            return jnp.sign(diff) * self.weight, self.weight
        return jnp.sign(diff), jnp.ones_like(score)

    def boost_from_score(self, class_id: int = 0) -> float:
        if self.weight_np is not None:
            return _weighted_percentile(self.label_np, self.weight_np, 0.5)
        return _percentile(self.label_np, 0.5)

    def _renew_alpha(self) -> float:
        return 0.5

    def renew_tree_output(self, pred_leaf, score, num_leaves):
        # reference: regression_objective.hpp:253-263 — leaf value := percentile
        # of (label - score) over the leaf's rows
        residual = self.label_np - score
        alpha = self._renew_alpha()
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            mask = pred_leaf == leaf
            if not mask.any():
                continue
            r = residual[mask]
            if self.weight_np is not None:
                out[leaf] = _weighted_percentile(r, self.weight_np[mask], alpha)
            else:
                out[leaf] = _percentile(r, alpha)
        return out


class RegressionHuber(RegressionL2):
    """reference: regression_objective.hpp:293-348 (RegressionHuberLoss)."""
    name = "huber"

    def get_grad_hess(self, score):
        diff = score - self.label
        alpha = self.config.alpha
        g = jnp.where(jnp.abs(diff) <= alpha, diff, jnp.sign(diff) * alpha)
        return self._apply_weight(g, jnp.ones_like(score))


class RegressionFair(RegressionL2):
    """reference: regression_objective.hpp:351-395 (RegressionFairLoss)."""
    name = "fair"

    def get_grad_hess(self, score):
        c = self.config.fair_c
        x = score - self.label
        g = c * x / (jnp.abs(x) + c)
        h = c * c / ((jnp.abs(x) + c) ** 2)
        return self._apply_weight(g, h)


class RegressionPoisson(RegressionL2):
    """reference: regression_objective.hpp:398-477 (RegressionPoissonLoss).
    Score is log-mean: grad = exp(s) - y, hess = exp(s + poisson_max_delta_step)."""
    name = "poisson"

    def init(self, label, weight, groups=None):
        if np.any(np.asarray(label) < 0):
            log.fatal("[poisson]: at least one target label is negative")
        super().init(label, weight, groups)

    def get_grad_hess(self, score):
        g = jnp.exp(score) - self.label
        h = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id: int = 0) -> float:
        mean = RegressionL2.boost_from_score(self, class_id)
        return float(np.log(max(mean, 1e-300)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantile(RegressionL2):
    """reference: regression_objective.hpp:478-573 (RegressionQuantileloss)."""
    name = "quantile"
    is_constant_hessian = True
    need_renew_tree_output = True

    def get_grad_hess(self, score):
        alpha = self.config.alpha
        delta = score - self.label
        g = jnp.where(delta >= 0, 1.0 - alpha, -alpha)
        return self._apply_weight(g, jnp.ones_like(score))

    def boost_from_score(self, class_id: int = 0) -> float:
        if self.weight_np is not None:
            return _weighted_percentile(self.label_np, self.weight_np, self.config.alpha)
        return _percentile(self.label_np, self.config.alpha)

    def _renew_alpha(self) -> float:
        return self.config.alpha

    renew_tree_output = RegressionL1.renew_tree_output


class RegressionMAPE(RegressionL1):
    """reference: regression_objective.hpp:576-672 (RegressionMAPELOSS)."""
    name = "mape"

    def init(self, label, weight, groups=None):
        super().init(label, weight, groups)
        lw = 1.0 / np.maximum(1.0, np.abs(self.label_np))
        if self.weight_np is not None:
            lw = lw * self.weight_np
        self.label_weight_np = lw
        self.label_weight = jnp.asarray(lw, dtype=jnp.float32)

    def get_grad_hess(self, score):
        diff = score - self.label
        g = jnp.sign(diff) * self.label_weight
        h = self.weight if self.weight is not None else jnp.ones_like(score)
        return g, h

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self.label_np, self.label_weight_np, 0.5)

    def renew_tree_output(self, pred_leaf, score, num_leaves):
        # reference: regression_objective.hpp:640-652 — weighted median of
        # residual with label_weight_
        residual = self.label_np - score
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            mask = pred_leaf == leaf
            if mask.any():
                out[leaf] = _weighted_percentile(residual[mask],
                                                 self.label_weight_np[mask], 0.5)
        return out


class RegressionGamma(RegressionPoisson):
    """reference: regression_objective.hpp:677-707 (RegressionGammaLoss)."""
    name = "gamma"

    def get_grad_hess(self, score):
        g = 1.0 - self.label * jnp.exp(-score)
        h = self.label * jnp.exp(-score)
        return self._apply_weight(g, h)


class RegressionTweedie(RegressionPoisson):
    """reference: regression_objective.hpp:712-751 (RegressionTweedieLoss)."""
    name = "tweedie"

    def get_grad_hess(self, score):
        rho = self.config.tweedie_variance_power
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        g = -self.label * e1 + e2
        h = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._apply_weight(g, h)


# ----------------------------------------------------------------- binary
class BinaryLogloss(ObjectiveFunction):
    """reference: src/objective/binary_objective.hpp:21-199."""
    name = "binary"

    def __init__(self, config: Config, is_pos=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal(f"Sigmoid parameter {self.sigmoid} should be greater than zero")
        self._is_pos = is_pos if is_pos is not None else (lambda y: y > 0)

    def init(self, label, weight, groups=None):
        super().init(label, weight, groups)
        is_pos = self._is_pos(self.label_np)
        cnt_pos = int(np.sum(is_pos))
        cnt_neg = self.num_data - cnt_pos
        self.need_train = not (cnt_pos == 0 or cnt_neg == 0)
        if not self.need_train:
            log.warning("Contains only one class")
        # label weights (binary_objective.hpp:88-102)
        w_pos, w_neg = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.config.scale_pos_weight
        self._is_pos_np = is_pos
        self.label_sign = jnp.asarray(np.where(is_pos, 1.0, -1.0), dtype=jnp.float32)
        self.label_weight = jnp.asarray(np.where(is_pos, w_pos, w_neg), dtype=jnp.float32)
        log.info(f"Number of positive: {cnt_pos}, number of negative: {cnt_neg}")

    def get_grad_hess(self, score):
        # reference: binary_objective.hpp:110-136
        if not self.need_train:
            return jnp.zeros_like(score), jnp.zeros_like(score)
        response = -self.label_sign * self.sigmoid / (
            1.0 + jnp.exp(self.label_sign * self.sigmoid * score))
        abs_response = jnp.abs(response)
        g = response * self.label_weight
        h = abs_response * (self.sigmoid - abs_response) * self.label_weight
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id: int = 0) -> float:
        # reference: binary_objective.hpp:139-161
        if self.weight_np is not None:
            pavg = float(np.sum(self._is_pos_np * self.weight_np) / np.sum(self.weight_np))
        else:
            pavg = float(np.mean(self._is_pos_np))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        initscore = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        log.info(f"[binary:BoostFromScore]: pavg={pavg:.6f} -> initscore={initscore:.6f}")
        return initscore

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))


# -------------------------------------------------------------- multiclass
class MulticlassSoftmax(ObjectiveFunction):
    """reference: src/objective/multiclass_objective.hpp:20-180."""
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = self.num_class
        self.factor = self.num_class / (self.num_class - 1.0)

    def init(self, label, weight, groups=None):
        super().init(label, weight, groups)
        li = self.label_np.astype(np.int32)
        if np.any((li < 0) | (li >= self.num_class)):
            log.fatal("Label must be in [0, num_class)")
        self.label_int = jnp.asarray(li)
        self.onehot = jax.nn.one_hot(self.label_int, self.num_class, dtype=jnp.float32)
        # class_init_probs_: weighted class frequencies
        w = self.weight_np if self.weight_np is not None else np.ones(self.num_data)
        probs = np.zeros(self.num_class)
        for k in range(self.num_class):
            probs[k] = np.sum(w * (li == k)) / np.sum(w)
        self.class_init_probs = probs

    def get_grad_hess(self, score):
        # score: [N, K]; reference: multiclass_objective.hpp:90-127
        p = jax.nn.softmax(score, axis=1)
        g = p - self.onehot
        h = self.factor * p * (1.0 - p)
        if self.weight is not None:
            g = g * self.weight[:, None]
            h = h * self.weight[:, None]
        return g, h

    def boost_from_score(self, class_id: int = 0) -> float:
        # reference: multiclass_objective.hpp:154-156
        return float(np.log(max(K_EPSILON, self.class_init_probs[class_id])))

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=-1)


class MulticlassOVA(ObjectiveFunction):
    """reference: multiclass_objective.hpp:184-280 (one-vs-all binary)."""
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = self.num_class
        self.binaries = [BinaryLogloss(config, is_pos=(lambda y, k=k: y.astype(np.int32) == k))
                         for k in range(self.num_class)]

    def init(self, label, weight, groups=None):
        super().init(label, weight, groups)
        for b in self.binaries:
            b.init(label, weight, groups)

    def get_grad_hess(self, score):
        gs, hs = [], []
        for k, b in enumerate(self.binaries):
            g, h = b.get_grad_hess(score[:, k])
            gs.append(g)
            hs.append(h)
        return jnp.stack(gs, axis=1), jnp.stack(hs, axis=1)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self.binaries[class_id].boost_from_score(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * raw))


# ------------------------------------------------------------ cross-entropy
class CrossEntropy(ObjectiveFunction):
    """reference: src/objective/xentropy_objective.hpp:44-147 (labels in [0,1])."""
    name = "cross_entropy"

    def init(self, label, weight, groups=None):
        if np.any((np.asarray(label) < 0) | (np.asarray(label) > 1)):
            log.fatal("[cross_entropy]: labels must be in [0, 1]")
        super().init(label, weight, groups)

    def get_grad_hess(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        g = z - self.label
        h = z * (1.0 - z)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self.weight_np if self.weight_np is not None else np.ones(self.num_data)
        pavg = float(np.sum(self.label_np * w) / np.sum(w))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-raw))


class CrossEntropyLambda(CrossEntropy):
    """reference: xentropy_objective.hpp:152-260 (weighted 'lambda' variant).
    Unweighted it reduces to plain cross-entropy (:195-197); the weighted form
    uses z = 1 - exp(-w*log1p(exp(s)))."""
    name = "cross_entropy_lambda"

    def get_grad_hess(self, score):
        if self.weight is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - self.label, z * (1.0 - z)
        w = self.weight
        y = self.label
        enf = jnp.exp(-score)
        hhat = jnp.log1p(jnp.exp(score))
        z = 1.0 - jnp.exp(-w * hhat)
        g = (1.0 - y / jnp.maximum(z, K_EPSILON)) * w / (1.0 + enf)
        c = 1.0 / (1.0 - jnp.maximum(z, K_EPSILON))
        d = 1.0 + jnp.exp(score)
        a = w * jnp.exp(score) / (d * d)
        b = (c - 1.0) * w / d - c + 1.0
        h = a * (1.0 + y * b)
        return g, h

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self.weight_np if self.weight_np is not None else np.ones(self.num_data)
        havg = float(np.sum(self.label_np * w) / np.sum(w))
        havg = max(havg, K_EPSILON)
        return float(np.log(np.expm1(havg))) if havg > K_EPSILON else float(np.log(K_EPSILON))

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))


_REGISTRY = {}
for _cls in [RegressionL2, RegressionL1, RegressionHuber, RegressionFair,
             RegressionPoisson, RegressionQuantile, RegressionMAPE,
             RegressionGamma, RegressionTweedie, BinaryLogloss,
             MulticlassSoftmax, MulticlassOVA, CrossEntropy, CrossEntropyLambda]:
    _REGISTRY[_cls.name] = _cls


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """reference: src/objective/objective_function.cpp CreateObjectiveFunction."""
    name = config.objective
    if name in ("none", "null", "custom", "na"):
        return None
    if name in ("lambdarank", "rank_xendcg"):
        from .ranking import create_ranking_objective
        return create_ranking_objective(config)
    if name not in _REGISTRY:
        log.fatal(f"Unknown objective: {name}")
    return _REGISTRY[name](config)
