"""Persistent XLA compilation cache + AOT program warmup.

The compile wall: at 10.5M rows the FIRST boosting iteration costs 232 s
of XLA compile against 7.2 s steady state (BENCH_r03-r05) — and every
short job, every supervisor gang relaunch and every hot-swap candidate
validation pays it again, because compiled executables die with the
process. This module makes compiles pay ONCE PER SHAPE, EVER:

- :func:`configure` points jax's persistent compilation cache at a
  directory (``compile_cache_dir`` param or the standard
  ``JAX_COMPILATION_CACHE_DIR`` env var): every compiled program is
  keyed by (HLO, backend, compile flags) and serialized to disk, so a
  SECOND process with the same shapes deserializes instead of
  compiling. Works on every backend this container has (CPU included —
  the CI smoke proves the cold -> warm transition there).

- :func:`aot_compile` is the explicit ``jit(...).lower(...).compile()``
  warmup used by ``GBDT.warm_start`` (fused step/block + score add) and
  ``PredictEngine.warm_aot``: on jax 0.4.x an AOT compile does NOT
  populate the jit call cache, so its value is (a) moving the compile
  out of the measured first step and (b) FILLING/HITTING the persistent
  disk cache — after which the first real call's compile is a disk
  read.

- :func:`install_compile_hook` wraps jax's persistent-cache hit/miss
  logging funnels (which receive the MODULE NAME, e.g.
  ``jit__fused_block``) plus the raw ``backend_compile`` entry point, so
  tests and bench.py can assert per-program cache behavior: the
  supervisor warm-restart regression pins "a relaunched incarnation
  performs ZERO fused-step XLA recompiles" on exactly these counters.
"""

from __future__ import annotations

import os
import threading
from collections import defaultdict
from typing import Dict, Optional

from .utils import log

_lock = threading.RLock()   # configure() calls install_compile_hook()
_configured_dir: Optional[str] = None
_hook_installed = False
# module_name -> count; "hits"/"misses" are persistent-cache outcomes,
# "compiles" counts actual backend_compile invocations (every XLA build,
# cached or not — a hit never reaches backend_compile)
_stats: Dict[str, Dict[str, int]] = {
    "hits": defaultdict(int),
    "misses": defaultdict(int),
    "compiles": defaultdict(int),
}


def configure(config=None, cache_dir: Optional[str] = None) -> Optional[str]:
    """Enable jax's persistent compilation cache for this process.

    ``cache_dir`` (or ``config.compile_cache_dir``) wins; otherwise an
    already-set ``JAX_COMPILATION_CACHE_DIR`` env var / jax config value
    is respected as-is. Idempotent — the first configured directory
    sticks for the process (jax initializes the cache once). Returns the
    active directory or None when caching stays disabled.

    When this module configures the dir it also drops jax's minimum
    entry-size/compile-time thresholds so EVERY program is cached — the
    fused step at CPU test scale compiles in milliseconds but must still
    produce the warm-start disk hit the tests and the gang-restart path
    rely on."""
    global _configured_dir
    d = cache_dir if cache_dir is not None else \
        (getattr(config, "compile_cache_dir", "") or "")
    with _lock:
        if _configured_dir is not None:
            if d and d != _configured_dir:
                log.warning(
                    f"compile_cache_dir={d!r} ignored: the persistent "
                    f"compilation cache is already configured at "
                    f"{_configured_dir!r} for this process")
            return _configured_dir
        import jax
        if not d:
            # respect an externally-configured cache (env var or direct
            # jax config) — just record and hook it
            d = (jax.config.jax_compilation_cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR") or "")
            if not d:
                return None
            _configured_dir = d
            install_compile_hook()
            return d
        try:
            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
            # cache everything: the thresholds exist to bound disk churn
            # on giant fleets; here a skipped small entry is a compile
            # the next incarnation pays again
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            # jax initializes the cache object ONCE, on the first compile
            # — which may already have happened (dir-less) before this
            # call; reset so the next compile re-initializes against the
            # directory just configured
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
            _configured_dir = d
            install_compile_hook()
            log.info(f"persistent XLA compilation cache at {d}")
        except Exception as e:   # pragma: no cover - jax version drift
            log.warning(f"could not configure the persistent compilation "
                        f"cache at {d!r}: {e}")
            return None
        return _configured_dir


def configured_dir() -> Optional[str]:
    return _configured_dir


def install_compile_hook() -> bool:
    """Count persistent-cache hits/misses per HLO module name (and raw
    backend compiles) by wrapping jax's own logging funnels. Idempotent;
    returns whether the counters are live. The wrappers only increment
    dicts — they never change compile behavior, so the hook stays
    installed for the process lifetime."""
    global _hook_installed
    with _lock:
        if _hook_installed:
            return True
        try:
            from jax._src import compiler as _compiler

            orig_hit = _compiler.log_persistent_cache_hit
            orig_miss = _compiler.log_persistent_cache_miss
            orig_bc = _compiler.backend_compile

            def _hit(module_name, *a, **kw):
                _stats["hits"][str(module_name)] += 1
                return orig_hit(module_name, *a, **kw)

            def _miss(module_name, *a, **kw):
                _stats["misses"][str(module_name)] += 1
                return orig_miss(module_name, *a, **kw)

            def _bc(backend, module, *a, **kw):
                name = "<unknown>"
                try:
                    from jax._src.interpreters import mlir as _mlir  # noqa
                    import jax._src.lib.mlir.ir as ir
                    sym = module.operation.attributes["sym_name"]
                    name = ir.StringAttr(sym).value
                except Exception:
                    pass
                _stats["compiles"][name] += 1
                return orig_bc(backend, module, *a, **kw)

            _compiler.log_persistent_cache_hit = _hit
            _compiler.log_persistent_cache_miss = _miss
            _compiler.backend_compile = _bc
            _hook_installed = True
        except Exception as e:   # pragma: no cover - jax version drift
            log.warning(f"compile-cache counters unavailable on this jax: "
                        f"{e}")
            return False
        return True


def compile_stats() -> Dict[str, Dict[str, int]]:
    """Snapshot of the per-module counters: ``{"hits": {module: n},
    "misses": {...}, "compiles": {...}}`` (empty until
    :func:`install_compile_hook` succeeds). Monotonic — diff two
    snapshots to scope a measurement."""
    return {k: dict(v) for k, v in _stats.items()}


def totals() -> Dict[str, int]:
    """Aggregate hit/miss/compile counts across modules."""
    return {k: sum(v.values()) for k, v in _stats.items()}


def module_count(kind: str, prefix: str) -> int:
    """Sum a counter over module names starting with ``prefix`` (module
    names follow jit function names: the fused per-iteration step is
    ``jit__fused_step``, the K-block ``jit__fused_block``)."""
    return sum(n for name, n in _stats[kind].items()
               if name.startswith(prefix))


def aot_compile(jitted, args, label: str = "program",
                static_kwargs: Optional[dict] = None) -> bool:
    """AOT-compile a jitted callable for the given argument pytree (any
    mix of concrete arrays/scalars and ``jax.ShapeDtypeStruct``s — the
    concrete leaves are abstracted in place, so callers can hand over
    live trainer state without uploading or mutating anything).
    ``static_kwargs`` are passed through to ``lower`` for jits with
    static keyword parameters. Failures are logged and swallowed:
    warmup is an optimization, never a correctness dependency."""
    import jax
    import jax.numpy as jnp

    def _abstract(x):
        if x is None or isinstance(x, jax.ShapeDtypeStruct):
            return x
        return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))

    try:
        sds = jax.tree.map(_abstract, args)
        jitted.lower(*sds, **(static_kwargs or {})).compile()
        return True
    except Exception as e:
        log.warning(f"AOT warmup of {label} failed (will compile lazily "
                    f"on first call): {e}")
        return False
