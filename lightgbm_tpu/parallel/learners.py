"""tree_learner dispatch: serial / data / feature / voting over a device mesh.

The analog of the reference's TreeLearner factory
(reference: include/LightGBM/tree_learner.h:104 ``CreateTreeLearner``:
(serial|feature|data|voting) x device). Here every distributed mode is the
SAME jitted grower (models/grower.py) under a ``shard_map`` with a
mode-specific sharding layout and collective pattern:

- ``data``: rows sharded; histogram tiles ``psum_scatter``'d over feature
  ownership, owner search, best-split allreduce-argmax (reference:
  data_parallel_tree_learner.cpp:184-186 ReduceScatter + HistogramSumReducer,
  parallel_tree_learner.h:191 SyncUpGlobalBestSplit).
- ``feature``: rows replicated, features sliced; no histogram communication,
  only the best-split sync (reference:
  feature_parallel_tree_learner.cpp:59-78).
- ``voting``: rows sharded; local top-k vote elects 2k features per leaf and
  only those columns are summed (reference:
  voting_parallel_tree_learner.cpp:151-182 GlobalVoting).

The mesh is a 1-D enumeration of the visible devices (multi-host: initialize
``jax.distributed`` before constructing the Booster and every process sees
the global mesh).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.split import FeatureMeta
from ..models.grower import GrowAux, grow_tree
from .data_parallel import make_mesh

PARALLEL_MODES = ("data", "feature", "voting")


def _pad_rows(n_pad, *arrays):
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
        elif a.ndim == 1:
            out.append(jnp.pad(a, (0, n_pad)))
        else:
            out.append(jnp.pad(a, ((0, n_pad), (0, 0))))
    return out


def _pad_features(meta: FeatureMeta, f_pad: int) -> FeatureMeta:
    """Pad per-feature metadata with inert features (2 bins, no missing,
    numerical, unconstrained) — they are masked off via feature_mask."""
    return FeatureMeta(
        num_bins=jnp.pad(meta.num_bins, (0, f_pad), constant_values=2),
        missing_type=jnp.pad(meta.missing_type, (0, f_pad)),
        default_bin=jnp.pad(meta.default_bin, (0, f_pad)),
        is_categorical=jnp.pad(meta.is_categorical, (0, f_pad)),
        monotone=jnp.pad(meta.monotone, (0, f_pad)),
        penalty=jnp.pad(meta.penalty, (0, f_pad), constant_values=1.0),
    )


class ParallelGrower:
    """Caches one shard_map'd grower per static configuration so repeated
    boosting iterations reuse the compiled program (the reference constructs
    its tree learner once in GBDT::Init, gbdt.cpp:49-138)."""

    def __init__(self, mode: str, mesh: Optional[Mesh] = None,
                 axis: str = "shard"):
        assert mode in PARALLEL_MODES, mode
        self.mode = mode
        self.axis = axis
        self.mesh = mesh if mesh is not None else make_mesh(axis=axis)
        self.ndev = self.mesh.shape[axis]
        self._cache = {}

    def _build(self, has_binsT: bool, grow_kwargs: tuple):
        axis = self.axis
        kw = dict(grow_kwargs)
        if self.mode == "data":
            kw.update(axis_name=axis, feature_axis_name=axis,
                      feature_shards=self.ndev)
        elif self.mode == "feature":
            kw.update(feature_axis_name=axis, feature_shards=self.ndev)
        else:  # voting
            kw.update(axis_name=axis, voting=True)

        rows_sharded = self.mode in ("data", "voting")
        row = P(axis) if rows_sharded else P()
        row2 = P(axis, None) if rows_sharded else P()
        colT = P(None, axis) if rows_sharded else P()

        if has_binsT:
            def fn(bins, grad, hess, mask, meta, params, fmask, missing_bin,
                   binsT, rng_key):
                return grow_tree(bins, grad, hess, mask, meta, params, fmask,
                                 missing_bin, binsT=binsT, rng_key=rng_key,
                                 **kw)
            in_specs = (row2, row, row, row, P(), P(), P(), P(), colT, P())
        else:
            def fn(bins, grad, hess, mask, meta, params, fmask, missing_bin,
                   rng_key):
                return grow_tree(bins, grad, hess, mask, meta, params, fmask,
                                 missing_bin, rng_key=rng_key, **kw)
            in_specs = (row2, row, row, row, P(), P(), P(), P(), P())
        out_specs = (P(), row, GrowAux(P(), P()))
        return jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

    def __call__(self, bins, grad, hess, sample_mask, meta, params,
                 feature_mask, missing_bin, *, binsT=None, rng_key=None,
                 **grow_kwargs):
        n, f = bins.shape
        d = self.ndev
        # pad rows (data/voting shard rows) and features (data/feature
        # shard feature ownership) to multiples of the mesh size
        n_pad = (-n) % d if self.mode in ("data", "voting") else 0
        f_pad = (-f) % d if self.mode in ("data", "feature") else 0
        if n_pad:
            bins, grad, hess, sample_mask = _pad_rows(
                n_pad, bins, grad, hess, sample_mask)
            if binsT is not None:
                binsT = jnp.pad(binsT, ((0, 0), (0, n_pad)))
        if f_pad:
            bins = jnp.pad(bins, ((0, 0), (0, f_pad)))
            meta = _pad_features(meta, f_pad)
            feature_mask = jnp.pad(feature_mask, (0, f_pad))
            missing_bin = jnp.pad(missing_bin, (0, f_pad),
                                  constant_values=-1)
            if binsT is not None:
                binsT = jnp.pad(binsT, ((0, f_pad), (0, 0)))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)

        key = (binsT is not None, tuple(sorted(grow_kwargs.items())))
        shard = self._cache.get(key)
        if shard is None:
            shard = self._build(binsT is not None,
                                tuple(sorted(grow_kwargs.items())))
            self._cache[key] = shard
        args = (bins, grad, hess, sample_mask, meta, params, feature_mask,
                missing_bin)
        if binsT is not None:
            args += (binsT,)
        tree, leaf_id, aux = shard(*args, rng_key)
        return tree, leaf_id[:n], aux
