"""tree_learner dispatch: serial / data / feature / voting over a device mesh.

The analog of the reference's TreeLearner factory
(reference: include/LightGBM/tree_learner.h:104 ``CreateTreeLearner``:
(serial|feature|data|voting) x device). Here every distributed mode is the
SAME jitted grower (models/grower.py) under a ``shard_map`` with a
mode-specific sharding layout and collective pattern:

- ``data``: rows sharded; histogram tiles ``psum_scatter``'d over feature
  ownership, owner search, best-split allreduce-argmax (reference:
  data_parallel_tree_learner.cpp:184-186 ReduceScatter + HistogramSumReducer,
  parallel_tree_learner.h:191 SyncUpGlobalBestSplit).
- ``feature``: rows replicated, features sliced; no histogram communication,
  only the best-split sync (reference:
  feature_parallel_tree_learner.cpp:59-78).
- ``voting``: rows sharded; local top-k vote elects 2k features per leaf and
  only those columns are summed (reference:
  voting_parallel_tree_learner.cpp:151-182 GlobalVoting).

The mesh is a 1-D enumeration of the visible devices (multi-host: initialize
``jax.distributed`` before constructing the Booster and every process sees
the global mesh).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.split import FeatureMeta
from ..models.grower import GrowAux, grow_tree
from .data_parallel import make_mesh

PARALLEL_MODES = ("data", "feature", "voting")


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """jax.shard_map became top-level API after 0.4.x (with check_rep
    renamed to check_vma); fall back to the experimental location so the
    parallel learners import on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _pad_cols(b, *, f_pad):
    return jnp.pad(b, ((0, 0), (0, f_pad)))


def _pad_rows(n_pad, *arrays):
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
        elif a.ndim == 1:
            out.append(jnp.pad(a, (0, n_pad)))
        else:
            out.append(jnp.pad(a, ((0, n_pad), (0, 0))))
    return out


def pad_bundle_meta(bundle_meta, f_pad: int):
    """Pad EFB bundle metadata with inert (non-bundle) columns whose single
    segment spans the full bin range — the grower slices bundle rows by the
    PADDED feature offset, so misaligned rows would corrupt real columns."""
    b = bundle_meta.seg_lo.shape[1]
    return type(bundle_meta)(
        seg_lo=jnp.pad(bundle_meta.seg_lo, ((0, f_pad), (0, 0))),
        seg_hi=jnp.pad(bundle_meta.seg_hi, ((0, f_pad), (0, 0)),
                       constant_values=b - 1),
        is_bundle=jnp.pad(bundle_meta.is_bundle, (0, f_pad)),
        fwd_ok=jnp.pad(bundle_meta.fwd_ok, ((0, f_pad), (0, 0))),
        rev_ok=jnp.pad(bundle_meta.rev_ok, ((0, f_pad), (0, 0))),
        # padded columns never produce valid candidates; preference 0
        # keeps them below every real candidate
        pref_fwd=jnp.pad(bundle_meta.pref_fwd, ((0, f_pad), (0, 0))),
        pref_rev=jnp.pad(bundle_meta.pref_rev, ((0, f_pad), (0, 0))))


def _pad_features(meta: FeatureMeta, f_pad: int) -> FeatureMeta:
    """Pad per-feature metadata with inert features (2 bins, no missing,
    numerical, unconstrained) — they are masked off via feature_mask."""
    return FeatureMeta(
        num_bins=jnp.pad(meta.num_bins, (0, f_pad), constant_values=2),
        missing_type=jnp.pad(meta.missing_type, (0, f_pad)),
        default_bin=jnp.pad(meta.default_bin, (0, f_pad)),
        is_categorical=jnp.pad(meta.is_categorical, (0, f_pad)),
        monotone=jnp.pad(meta.monotone, (0, f_pad)),
        penalty=jnp.pad(meta.penalty, (0, f_pad), constant_values=1.0),
    )


class ParallelGrower:
    """Caches one shard_map'd grower per static configuration so repeated
    boosting iterations reuse the compiled program (the reference constructs
    its tree learner once in GBDT::Init, gbdt.cpp:49-138)."""

    def __init__(self, mode: str, mesh: Optional[Mesh] = None,
                 axis: str = "shard"):
        assert mode in PARALLEL_MODES, mode
        self.mode = mode
        self.axis = axis
        self.mesh = mesh if mesh is not None else make_mesh(axis=axis)
        self.ndev = self.mesh.shape[axis]
        self._cache = {}
        self._global_arrays = {}   # id(host arr) -> (host arr, global arr)

    def _build(self, extras_spec: dict, grow_kwargs: tuple,
               pre_part: bool = False):
        axis = self.axis
        kw = dict(grow_kwargs)
        if self.mode == "data":
            kw.update(axis_name=axis, feature_axis_name=axis,
                      feature_shards=self.ndev)
        elif self.mode == "feature":
            kw.update(feature_axis_name=axis, feature_shards=self.ndev)
        else:  # voting
            kw.update(axis_name=axis, voting=True)

        rows_sharded = self.mode in ("data", "voting")
        row = P(axis) if rows_sharded else P()
        row2 = P(axis, None) if rows_sharded else P()
        # replicated-data multi-controller (every process constructed the
        # full Dataset): replicate the leaf ids with an in-program
        # all_gather so every process can address the full vector for its
        # full-length score update. Pre-partitioned mode keeps leaf_id
        # ROW-SHARDED end to end — the score update consumes only the
        # process-local shard (the reference's per-machine score partition,
        # score_updater.hpp), so no O(N_global) array ever lands on a host
        multiproc = jax.process_count() > 1
        gather_leaf = multiproc and rows_sharded and not pre_part

        def fn(bins, grad, hess, mask, meta, params, fmask, missing_bin,
               extras, rng_key):
            tree, leaf_id, aux = grow_tree(
                bins, grad, hess, mask, meta, params, fmask, missing_bin,
                binsT=extras.get("binsT"),
                bundle_meta=extras.get("bundle"),
                forced_splits=extras.get("forced"),
                rng_key=rng_key, **kw)
            if gather_leaf:
                leaf_id = jax.lax.all_gather(leaf_id, axis, tiled=True)
            return tree, leaf_id, aux

        leaf_spec = P() if gather_leaf else row
        in_specs = (row2, row, row, row, P(), P(), P(), P(), extras_spec,
                    P())
        out_specs = (P(), leaf_spec, GrowAux(P(), P(), P(), P(), P()))
        # jit the shard_map: a BARE shard_map re-traces and re-compiles on
        # every invocation, which made each unfused parallel-learner
        # iteration (the only path pre-partitioned runs have) pay a full
        # grower compile (~60 XLA compiles/iter measured on CPU). The
        # fused path embeds this same fn inside its own jit, where the
        # extra jit wrapper simply inlines.
        return jax.jit(_shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                  out_specs=out_specs))

    def pad_replicated_inputs(self, bins, binsT, meta, missing_bin,
                              bundle_meta):
        """Pad the dataset-constant arrays of the replicated (single-
        controller) path to mesh-divisible shapes — the ONE definition of
        the row/feature padding rules, shared by the per-call unfused
        ``__call__`` below and the fused step's build-once bindings
        (models/gbdt.py _fused_parallel_bindings), so the two paths
        cannot drift. Returns ``(bins, binsT, meta, missing_bin,
        bundle_meta, n_pad, f_pad)``."""
        n, f = bins.shape
        d = self.ndev
        n_pad = (-n) % d if self.mode in ("data", "voting") else 0
        f_pad = (-f) % d if self.mode in ("data", "feature") else 0
        if n_pad:
            bins = jnp.pad(bins, ((0, n_pad), (0, 0)))
            if binsT is not None:
                binsT = jnp.pad(binsT, ((0, 0), (0, n_pad)))
        if f_pad:
            bins = jnp.pad(bins, ((0, 0), (0, f_pad)))
            meta = _pad_features(meta, f_pad)
            missing_bin = jnp.pad(missing_bin, (0, f_pad),
                                  constant_values=-1)
            if binsT is not None:
                binsT = jnp.pad(binsT, ((0, f_pad), (0, 0)))
            if bundle_meta is not None:
                bundle_meta = pad_bundle_meta(bundle_meta, f_pad)
        return bins, binsT, meta, missing_bin, bundle_meta, n_pad, f_pad

    def build_extras(self, binsT, bundle_meta, forced_splits):
        """Assemble the optional-operand dict + its PartitionSpecs for
        the shard fn (the single definition of the binsT/bundle/forced
        wiring both call paths share)."""
        extras, extras_spec = {}, {}
        rows_sharded = self.mode in ("data", "voting")
        if binsT is not None:
            extras["binsT"] = binsT
            extras_spec["binsT"] = (P(None, self.axis) if rows_sharded
                                    else P())
        if bundle_meta is not None:
            extras["bundle"] = bundle_meta
            extras_spec["bundle"] = type(bundle_meta)(
                *(P() for _ in bundle_meta))
        if forced_splits is not None:
            extras["forced"] = forced_splits
            extras_spec["forced"] = tuple(P() for _ in forced_splits)
        return extras, extras_spec

    def get_shard_fn(self, extras_spec: dict, grow_kwargs: tuple,
                     pre_part: bool = False):
        """The cached shard_map'd grower for a static configuration — the
        single compile cache BOTH call paths share: the unfused per-phase
        ``__call__`` below and the fused one-dispatch iteration
        (models/gbdt.py ``_fused_step_fn``) embed the same program, so a
        config admitted to the fused path never compiles the grower
        twice."""
        key = (("prepart",) if pre_part else ()) + (
            frozenset(extras_spec), grow_kwargs)
        shard = self._cache.get(key)
        if shard is None:
            shard = self._build(extras_spec, grow_kwargs,
                                pre_part=pre_part)
            self._cache[key] = shard
        return shard

    def _to_global(self, arr, spec, key=None):
        """Multi-controller: build a GLOBAL array from this process's full
        host copy (every process constructed the same Dataset — the
        reference's machine-list flow where each machine loads data and the
        learner operates on its row shard). Each process materializes only
        its addressable shards. ``key`` (the pre-padding original of a
        dataset-constant input) caches the globalization so bins/meta/masks
        globalize once, not once per tree."""
        if arr is None or jax.process_count() == 1:
            return arr

        def build():
            sharding = jax.sharding.NamedSharding(self.mesh, spec)
            try:
                # device_put reshards without a host round trip when the
                # input is already device-resident (the grad/hess path)
                return jax.device_put(arr, sharding)
            except Exception:
                host = np.asarray(arr)
                return jax.make_array_from_callback(host.shape, sharding,
                                                    lambda idx: host[idx])

        return build() if key is None else self._cached_global(key, build)

    def _cached_global(self, key, build):
        """id()-keyed LRU over dataset-constant globalized arrays (the
        source object is retained so its id stays unique; bounded so a
        long-lived process over many Datasets doesn't pin old copies)."""
        hit = self._global_arrays.get(id(key))
        if hit is not None and hit[0] is key:
            self._global_arrays.pop(id(key))
            self._global_arrays[id(key)] = hit
            return hit[1]
        out = build()
        if len(self._global_arrays) >= 64:
            self._global_arrays.pop(next(iter(self._global_arrays)))
        self._global_arrays[id(key)] = (key, out)
        return out

    def __call__(self, bins, grad, hess, sample_mask, meta, params,
                 feature_mask, missing_bin, *, binsT=None, rng_key=None,
                 bundle_meta=None, forced_splits=None, pre_part=None,
                 **grow_kwargs):
        n, f = bins.shape
        d = self.ndev
        # pre-partitioned mode (distributed.load_partitioned): bins is
        # already a GLOBAL row-sharded array and grad/hess/mask arrive as
        # this process's LOCAL row slice. Callers holding the Dataset pass
        # the flag explicitly; the addressability probe covers direct
        # multi-process grower-level use (a 1-process pre-partitioned
        # array IS fully addressable, so the flag matters there)
        if pre_part is None:
            pre_part = (isinstance(bins, jax.Array)
                        and not bins.is_fully_addressable)
        if pre_part:
            assert self.mode in ("data", "voting"), (
                "pre-partitioned datasets shard rows; use data/voting")
            assert n % d == 0, (n, d)   # load_partitioned pads rows
            # grad/hess/mask arrive as this process's TRUE local rows; pad
            # to the per-process shard size with zero mass
            loc_target = n // max(jax.process_count(), 1)
            row = P(self.axis)
            sharding = jax.sharding.NamedSharding(self.mesh, row)
            rep = jax.sharding.NamedSharding(self.mesh, P())

            def glob(a, fill=0.0):
                a = np.asarray(a)
                if a.shape[0] < loc_target:
                    a = np.pad(a, (0, loc_target - a.shape[0]),
                               constant_values=fill)
                return jax.make_array_from_process_local_data(sharding, a)

            def glob_rep(a, key=None):
                """Replicate a (process-identical) host array globally."""
                build = lambda: jax.device_put(np.asarray(a), rep)
                return build() if key is None \
                    else self._cached_global(key, build)

            grad = glob(grad)
            hess = glob(hess)
            sample_mask = glob(sample_mask)
            f_pad = (-f) % d if self.mode == "data" else 0
            colT = P(None, self.axis)

            def pad_global(arr, spec, fn):
                """Cached jitted pad of a dataset-constant global array."""
                out_sh = jax.sharding.NamedSharding(self.mesh, spec)
                return self._cached_global(
                    arr, lambda: jax.jit(fn, out_shardings=out_sh)(arr))

            if f_pad:
                meta = _pad_features(meta, f_pad)
                feature_mask = jnp.pad(feature_mask, (0, f_pad))
                missing_bin = jnp.pad(missing_bin, (0, f_pad),
                                      constant_values=-1)
                bins = pad_global(bins, P(self.axis, None),
                                  functools.partial(_pad_cols, f_pad=f_pad))
                if binsT is not None:
                    binsT = pad_global(
                        binsT, colT,
                        lambda b: jnp.pad(b, ((0, f_pad), (0, 0))))
                if bundle_meta is not None:
                    bundle_meta = pad_bundle_meta(bundle_meta, f_pad)
            extras = {}
            extras_spec = {}
            if binsT is not None:
                # already a GLOBAL feature-major array from load_partitioned
                extras["binsT"] = binsT
                extras_spec["binsT"] = colT
            if bundle_meta is not None:
                extras["bundle"] = type(bundle_meta)(
                    *(glob_rep(a, key=ka)
                      for a, ka in zip(bundle_meta, bundle_meta)))
                extras_spec["bundle"] = type(bundle_meta)(
                    *(P() for _ in bundle_meta))
            if forced_splits is not None:
                extras["forced"] = tuple(
                    glob_rep(a, key=ka)
                    for a, ka in zip(forced_splits, forced_splits))
                extras_spec["forced"] = tuple(P() for _ in forced_splits)
            if rng_key is None:
                rng_key = jax.random.PRNGKey(0)
            shard = self.get_shard_fn(extras_spec,
                                      tuple(sorted(grow_kwargs.items())),
                                      pre_part=True)
            tree, leaf_id, aux = shard(bins, grad, hess, sample_mask, meta,
                                       params, feature_mask, missing_bin,
                                       extras, rng_key)
            return tree, leaf_id, aux
        # pre-padding originals key the multi-process globalization cache
        # (padding allocates fresh arrays every call)
        orig_bins, orig_binsT = bins, binsT
        orig_meta, orig_missing_bin = meta, missing_bin
        orig_bundle, orig_forced = bundle_meta, forced_splits
        (bins, binsT, meta, missing_bin, bundle_meta,
         n_pad, f_pad) = self.pad_replicated_inputs(
            bins, binsT, meta, missing_bin, bundle_meta)
        if n_pad:
            _, grad, hess, sample_mask = _pad_rows(n_pad, None, grad, hess,
                                                   sample_mask)
        if f_pad:
            feature_mask = jnp.pad(feature_mask, (0, f_pad))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        if jax.process_count() > 1:
            axis = self.axis
            rows_sharded = self.mode in ("data", "voting")
            row = P(axis) if rows_sharded else P()
            row2 = P(axis, None) if rows_sharded else P()
            bins = self._to_global(bins, row2, key=orig_bins)
            grad = self._to_global(grad, row)
            hess = self._to_global(hess, row)
            sample_mask = self._to_global(sample_mask, row)
            meta = type(meta)(*(self._to_global(a, P(), key=ka)
                                for a, ka in zip(meta, orig_meta)))
            feature_mask = self._to_global(feature_mask, P())
            missing_bin = self._to_global(missing_bin, P(),
                                          key=orig_missing_bin)

        extras, extras_spec = self.build_extras(binsT, bundle_meta,
                                                forced_splits)
        multiproc = jax.process_count() > 1
        if multiproc:
            if "binsT" in extras:
                extras["binsT"] = self._to_global(
                    extras["binsT"], extras_spec["binsT"], key=orig_binsT)
            if "bundle" in extras:
                extras["bundle"] = type(bundle_meta)(
                    *(self._to_global(a, P(), key=ka)
                      for a, ka in zip(extras["bundle"], orig_bundle)))
            if "forced" in extras:
                extras["forced"] = tuple(
                    self._to_global(a, P(), key=ka)
                    for a, ka in zip(extras["forced"], orig_forced))

        shard = self.get_shard_fn(extras_spec,
                                  tuple(sorted(grow_kwargs.items())))
        tree, leaf_id, aux = shard(bins, grad, hess, sample_mask, meta,
                                   params, feature_mask, missing_bin,
                                   extras, rng_key)
        return tree, leaf_id[:n], aux
