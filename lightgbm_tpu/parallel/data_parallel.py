"""Distributed data-parallel tree growth over a device mesh.

TPU-native re-design of the reference's distributed tree learners
(reference: src/treelearner/data_parallel_tree_learner.cpp and the socket/MPI
collective layer src/network/ it rides on — SURVEY.md §2.6). The reference
shards ROWS across machines, reduces per-leaf histograms with
``Network::ReduceScatter`` + ``HistogramSumReducer``
(data_parallel_tree_learner.cpp:184-186, bin.h:44-57), allreduces the root
sums (:125-152) and syncs the best split with an allreduce-max
(parallel_tree_learner.h:191-214).

Here the whole scheme collapses into one SPMD program under ``shard_map``:

- rows (bins/grad/hess/sample-mask) are sharded over the ``data`` mesh axis;
- local histograms are summed with ``jax.lax.psum`` over ICI — the analog of
  the ReduceScatter+owner-search+SyncUpGlobalBestSplit dance. After the psum
  every device holds identical global histograms, so split FINDING needs no
  further communication at all: each device computes the same argmax
  deterministically (no SplitInfo serialization, no allreduce-max);
- the per-row partition update stays local to each shard.

``grow_tree_dp`` is the shard_map-wrapped grower; the tree it returns is
replicated (identical on every device), the leaf ids stay row-sharded.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
from jax.sharding import Mesh

from ..ops.split import FeatureMeta, SplitParams
from ..models.tree import TreeArrays

_dp_growers = {}   # (mesh, axis) -> ParallelGrower (compile-cache reuse)


def make_mesh(num_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    """Build a 1-D device mesh over the first ``num_devices`` devices
    (the analog of the reference's machine-list bootstrap,
    linkers_socket.cpp:24-63 — here just jax device enumeration)."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(devs, (axis,))


def grow_tree_dp(mesh: Mesh, bins: jax.Array, grad: jax.Array, hess: jax.Array,
                 sample_mask: jax.Array, meta: FeatureMeta, params: SplitParams,
                 feature_mask: jax.Array, missing_bin: jax.Array, *,
                 max_leaves: int, num_bins: int, max_depth: int = -1,
                 hist_method: str = "auto",
                 deterministic: bool = False,
                 exact: bool = False,
                 with_categorical: bool = False,
                 axis: str = "data") -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree with rows sharded over ``mesh`` axis ``axis``.

    Thin mesh-explicit alias over the PRODUCTION data-parallel learner
    (learners.ParallelGrower mode="data": histogram psum_scatter over
    feature ownership + owner search + best-split sync — the reference's
    ReduceScatter pattern, data_parallel_tree_learner.cpp:184-186). Kept so
    callers holding an explicit Mesh (the driver dry run, unit tests) hit
    the same program the ``tree_learner="data"`` public API runs.
    """
    from ..ops.histogram import resolve_method
    from .learners import ParallelGrower
    pg = _dp_growers.get((mesh, axis))
    if pg is None:
        if len(_dp_growers) >= 4:     # bounded: drop the oldest grower
            _dp_growers.pop(next(iter(_dp_growers)))
        pg = ParallelGrower("data", mesh=mesh, axis=axis)
        _dp_growers[(mesh, axis)] = pg
    tree, leaf_id, _aux = pg(
        bins, grad, hess, sample_mask, meta, params, feature_mask,
        missing_bin, max_leaves=max_leaves, num_bins=num_bins,
        max_depth=max_depth,
        hist_method=resolve_method(hist_method, deterministic=deterministic),
        exact=exact, with_categorical=with_categorical)
    return tree, leaf_id
