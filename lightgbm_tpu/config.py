"""Configuration / parameter system.

TPU-native re-design of the reference's config layer
(reference: include/LightGBM/config.h:34, src/io/config.cpp, src/io/config_auto.cpp).
A single dataclass holds every supported parameter with its reference default;
``Config.from_params`` resolves aliases centrally the way ``ParameterAlias::
KeyAliasTransform`` does (reference: src/io/config.cpp, config_auto.cpp:12-168) and
the Python-side ``_ConfigAliases`` table (reference: python-package/lightgbm/basic.py:273).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from .utils import log

# Alias -> canonical name (reference: src/io/config_auto.cpp:12-168).
PARAM_ALIASES: Dict[str, str] = {
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective", "app": "objective", "application": "objective",
    "boosting_type": "boosting", "boost": "boosting",
    "linear_trees": "linear_tree",
    "train": "data", "train_data": "data", "train_data_file": "data", "data_filename": "data",
    "test": "valid", "valid_data": "valid", "valid_data_file": "valid",
    "test_data": "valid", "test_data_file": "valid", "valid_filenames": "valid",
    "num_iteration": "num_iterations", "n_iter": "num_iterations",
    "num_tree": "num_iterations", "num_trees": "num_iterations",
    "num_round": "num_iterations", "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations", "n_estimators": "num_iterations",
    "shrinkage_rate": "learning_rate", "eta": "learning_rate",
    "num_leaf": "num_leaves", "max_leaves": "num_leaves", "max_leaf": "num_leaves",
    "tree": "tree_learner", "tree_type": "tree_learner", "tree_learner_type": "tree_learner",
    "num_thread": "num_threads", "nthread": "num_threads", "nthreads": "num_threads",
    "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed", "random_state": "seed",
    "hist_pool_size": "histogram_pool_size",
    "min_data_per_leaf": "min_data_in_leaf", "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf", "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction", "subsample": "bagging_fraction", "bagging": "bagging_fraction",
    "pos_sub_row": "pos_bagging_fraction", "pos_subsample": "pos_bagging_fraction",
    "pos_bagging": "pos_bagging_fraction",
    "neg_sub_row": "neg_bagging_fraction", "neg_subsample": "neg_bagging_fraction",
    "neg_bagging": "neg_bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction", "colsample_bytree": "feature_fraction",
    "sub_feature_bynode": "feature_fraction_bynode", "colsample_bynode": "feature_fraction_bynode",
    "extra_tree": "extra_trees",
    "early_stopping_rounds": "early_stopping_round", "early_stopping": "early_stopping_round",
    "n_iter_no_change": "early_stopping_round",
    "max_tree_output": "max_delta_step", "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2", "lambda": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints", "monotone_constraint": "monotone_constraints",
    "monotone_constraining_method": "monotone_constraints_method",
    "mc_method": "monotone_constraints_method",
    "monotone_splits_penalty": "monotone_penalty", "ms_penalty": "monotone_penalty",
    "mc_penalty": "monotone_penalty",
    "feature_contrib": "feature_contri", "fc": "feature_contri", "fp": "feature_contri",
    "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename", "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename", "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    "model_input": "input_model", "model_in": "input_model",
    "model_output": "output_model", "model_out": "output_model",
    "save_period": "snapshot_freq",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "data_seed": "data_random_seed",
    "is_sparse": "is_enable_sparse", "enable_sparse": "is_enable_sparse", "sparse": "is_enable_sparse",
    "is_enable_bundle": "enable_bundle", "bundle": "enable_bundle",
    "is_pre_partition": "pre_partition",
    "two_round_loading": "two_round", "use_two_round_loading": "two_round",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column", "group_id": "group_column", "query_column": "group_column",
    "query": "group_column", "query_id": "group_column",
    "ignore_feature": "ignore_column", "blacklist": "ignore_column",
    "cat_feature": "categorical_feature", "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "is_save_binary": "save_binary", "is_save_binary_file": "save_binary",
    "is_predict_raw_score": "predict_raw_score", "predict_rawscore": "predict_raw_score",
    "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index", "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib", "contrib": "predict_contrib",
    "predict_result": "output_result", "prediction_result": "output_result",
    "predict_name": "output_result", "prediction_name": "output_result",
    "pred_name": "output_result", "name_pred": "output_result",
    "convert_model_file": "convert_model",
    "num_classes": "num_class",
    "unbalance": "is_unbalance", "unbalanced_sets": "is_unbalance",
    "metrics": "metric", "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at", "ndcg_at": "eval_at", "map_eval_at": "eval_at", "map_at": "eval_at",
    "num_machine": "num_machines",
    "local_port": "local_listen_port", "port": "local_listen_port",
    "machine_list_file": "machine_list_filename", "machine_list": "machine_list_filename",
    "mlist": "machine_list_filename",
    "workers": "machines", "nodes": "machines",
    "checkpoint_dir": "checkpoint_path", "ckpt_dir": "checkpoint_path",
}

# Objective aliases (reference: src/objective/objective_function.cpp + config.cpp ParseObjectiveAlias)
_OBJECTIVE_ALIASES = {
    "regression_l2": "regression", "mean_squared_error": "regression", "mse": "regression",
    "l2": "regression", "l2_root": "regression", "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1", "mean_absolute_error": "regression_l1",
    "mae": "regression_l1", "l1": "regression_l1",
    "mean_absolute_percentage_error": "mape",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda",
    "mean_squared_logarithmic_error": "regression",
}

_METRIC_ALIASES = {
    "l2_root": "rmse", "root_mean_squared_error": "rmse",
    "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2", "regression": "l2",
    "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "mean_absolute_percentage_error": "mape",
    "binary_logloss": "binary_logloss",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg", "xendcg": "ndcg",
    "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda",
    "kldiv": "kullback_leibler",
}


@dataclass
class Config:
    """All supported parameters, defaults matching the reference (config.h:34-1197)."""

    # Core (config.h:97-233)
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"   # reference default "cpu" (config.h:225); TPU-native here
    seed: Optional[int] = None
    deterministic: bool = False

    # Learning control (config.h:237-600)
    force_col_wise: bool = False
    force_row_wise: bool = False
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    early_stopping_round: int = 0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    linear_lambda: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1          # DART
    max_drop: int = 50              # DART
    skip_drop: float = 0.5          # DART
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2           # GOSS
    other_rate: float = 0.1         # GOSS
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20                 # voting parallel
    monotone_constraints: List[int] = field(default_factory=list)
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    path_smooth: float = 0.0
    interaction_constraints: List[List[int]] = field(default_factory=list)
    verbosity: int = 1
    snapshot_freq: int = -1
    linear_tree: bool = False
    # fail fast on NaN/Inf gradients/hessians/leaf outputs/score deltas,
    # naming the iteration and source before they poison the histograms.
    # On the fused one-dispatch path the checks run IN-PROGRAM as numerics
    # sentinels: a packed flag word (NaN/Inf bits per source) computed
    # inside the compiled step and judged lazily via non-blocking ready
    # checks (so the fetch never stalls the dispatch pipeline;
    # state-capture paths flush it first, so poisoned state is never
    # written) — the guard works WITH fused_iteration and quantized-grad
    # training (it no longer gates them off; the unfused path keeps the
    # host-side counting checks)
    check_numerics: bool = False

    # Checkpointing
    # directory for atomic training checkpoints ("" = <output_model>.ckpt
    # when snapshot_freq > 0 in the CLI); see lightgbm_tpu/checkpoint.py
    checkpoint_path: str = ""
    # how many recent checkpoints to retain (>= 2 keeps a fallback when the
    # newest is truncated/corrupt)
    checkpoint_keep: int = 2
    # sharded checkpoint layout for pre-partitioned datasets: every rank
    # writes its process-local score-cache shard (shard_rank{r}.pkl) plus a
    # rank-0 PARTITION.json row-partition manifest, enabling resume at a
    # DIFFERENT world size (re-partition-on-load) and supervisor gang
    # shrink; off falls back to the replicated rank-0-only layout (which
    # pre-partitioned multi-process runs cannot resume from)
    checkpoint_shards: bool = True

    # Distributed training supervision (see lightgbm_tpu/supervisor.py)
    # seconds between liveness heartbeats each rank sends to rank 0 over
    # the supervisor's TCP side-channel (<= 0 disables; only active in
    # multi-process runs with a heartbeat address configured)
    heartbeat_interval: float = 5.0
    # seconds one boosting step (or cross-process barrier) may take before
    # the watchdog declares the collective stalled and raises a
    # DistributedTimeoutError naming the suspect rank(s) and the last
    # completed iteration (0 disables the watchdog)
    collective_deadline: float = 0.0
    # how many times the gang supervisor relaunches a failed gang from the
    # latest valid checkpoint before giving up
    max_restarts: int = 2
    # per-rank restart budget: once the SAME rank has failed more than this
    # many times at the current world size (or its spawn itself fails), the
    # supervisor classifies it permanently lost and relaunches the gang at
    # world size n-1 (a gang SHRINK) instead of burning same-size restarts
    rank_restart_budget: int = 1
    # the smallest world size the supervisor may shrink a gang to; a loss
    # that would go below it exhausts the restart budget instead
    min_world_size: int = 1

    # Training integrity (see README "Training integrity")
    # every this many iterations, ranks exchange a cheap fingerprint of
    # the global model state (tree-structure hash + a score-cache checksum
    # over the rank's row range) over the coordination service and
    # majority-vote any mismatch: a minority rank whose state silently
    # diverged from the gang is named in a RankDivergenceError — or, under
    # supervision, exits with DIVERGENCE_EXIT_CODE so the supervisor
    # restarts it from the last valid checkpoint (and shrinks it away
    # after rank_restart_budget). 0 disables; no-op single-process
    integrity_check_period: int = 0
    # catch RESOURCE_EXHAUSTED during histogram compile/execute and step
    # down the documented degradation ladder (smaller histogram block ->
    # hist_method -> XLA scatter -> chunked predict buckets) instead of
    # killing the job; every degradation event lands in health_snapshot(),
    # the gauges and the checkpoint manifest's health section so an
    # operator can see the job is running degraded
    hist_oom_fallback: bool = True
    # flip ONE bit of rank r's train-score cache after 0-based iteration k
    # ("r:k"; config twin of LGBM_TPU_FAULT_FLIP_SCORE_RANK) — the silent
    # corruption the divergence check must attribute to exactly that rank
    fault_flip_score_rank: str = ""
    # poison one gradient value with NaN INSIDE the compiled program at
    # this 0-based iteration (the fused path's sentinels must catch it;
    # unlike fault_nan_grad_at_iter it does not unfuse the iteration)
    fault_nan_hist_at_iter: int = -1
    # raise a simulated RESOURCE_EXHAUSTED from the boosting step at this
    # 0-based iteration, fault_oom_count consecutive times — drives the
    # OOM degradation ladder one rung per raise
    fault_oom_at_iter: int = -1
    fault_oom_count: int = 1

    # Fault injection (testing)
    # hard-exit (like SIGKILL) at the start of this 0-based iteration;
    # see lightgbm_tpu/utils/faults.py
    fault_kill_at_iter: int = -1
    # sleep forever (interruptibly) at the start of this 0-based iteration
    # — the hung-rank shape the collective_deadline watchdog must catch
    fault_hang_at_iter: int = -1
    # hard-exit ONLY process rank r at 0-based iteration k ("r:k"; the
    # config twin of LGBM_TPU_FAULT_KILL_RANK_AT_ITER — unlike the env
    # form, the supervisor's one-shot fault stripping cannot disarm it)
    fault_kill_rank_at_iter: str = ""
    # hang ONLY process rank r at 0-based iteration k ("r:k")
    fault_hang_rank_at_iter: str = ""
    # hard-exit in the middle of the checkpoint write for this 0-based
    # iteration (after the payload files, before the manifest)
    fault_kill_in_ckpt_write: int = -1
    # hard-exit rank r mid-way through the SHARDED checkpoint write for
    # 0-based iteration k ("r:k": after its shard file, before the
    # shard-metadata exchange)
    fault_kill_in_shard_write: str = ""
    # flip bytes in rank r's shard file of every sharded checkpoint right
    # after publication (manifest intact: only checksums catch it)
    fault_corrupt_shard: int = -1
    # overwrite leading gradient values with NaN at this 0-based iteration
    fault_nan_grad_at_iter: int = -1
    # flip bytes in each checkpoint's model text right after it is written
    fault_corrupt_checkpoint: bool = False
    # sleep this many milliseconds inside EVERY predict dispatch (config
    # twin of LGBM_TPU_FAULT_SLOW_PREDICT_MS) — the slow-dispatch shape
    # the serving layer's deadlines and admission control must catch
    fault_slow_predict_ms: float = 0.0
    # raise a simulated RESOURCE_EXHAUSTED from the next N predict
    # dispatches, process-wide (twin of LGBM_TPU_FAULT_OOM_AT_PREDICT) —
    # drives the serve-side predict-chunk degradation rung
    fault_oom_at_predict: int = 0

    # IO / dataset (config.h:604-800)
    max_bin: int = 255
    max_bin_by_feature: List[int] = field(default_factory=list)
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    # streaming chunked construction (binning.py FeatureSketch +
    # StreamingBinWriter, basic.py Dataset.from_chunks): rows per chunk
    # when slicing monolithic array input (0 = auto, ~1M-row chunks);
    # chunk sources keep their own chunk sizes
    construct_chunk_rows: int = 0
    # route Dataset.construct through the two-pass streaming path (sketch
    # pass -> device bin pass, host memory O(chunk)) even for monolithic
    # array input; chunk-source datasets always stream
    construct_streaming: bool = False
    # per-feature distinct-value budget of the mergeable construct sketch;
    # 0 = exact (unbounded). Past it the sketch compacts to equal-mass
    # representatives (rank error ~compactions/sketch_max_size)
    sketch_max_size: int = 65536
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    pre_partition: bool = False
    two_round: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Union[str, List[int]] = ""
    forcedbins_filename: str = ""
    save_binary: bool = False
    precise_float_parser: bool = False

    # Predict (config.h:804-900)
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    output_result: str = "LightGBM_predict_result.txt"

    # Convert / model files
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"

    # Objective (config.h:904-970)
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9              # Huber / Quantile
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: List[float] = field(default_factory=list)

    # Metric (config.h:1000-1060)
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = field(default_factory=list)

    # Network / distributed (config.h:974-995). On TPU these select the device
    # mesh rather than a socket/MPI rank list (SURVEY.md §2.6 TPU-native note).
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # GPU analog: TPU controls
    gpu_use_dp: bool = False        # if True use float64-grade (compensated) histograms
    num_gpu: int = 1

    # TPU-specific (new; no reference analog)
    mesh_shape: Optional[Dict[str, int]] = None     # e.g. {"data": 8}
    # "batched": all available splits per histogram round (fast, see
    # models/grower.py docstring); "exact": strict best-first like the
    # reference's leaf-wise order (one histogram round per split).
    tree_growth_mode: str = "batched"
    histogram_method: str = "auto"                  # auto|scatter|binloop|onehot|onehot_hilo|onehot_q8|pallas|pallas_hilo|pallas_q8
    # quantized-gradient training (the XGBoost-GPU recipe, arXiv:1706.08359
    # §5; LightGBM 4.x quantized training re-designed for the MXU):
    # grad/hess quantize to int8 with stochastic rounding, histograms
    # accumulate EXACTLY in int32 on the int8 MXU path (~2x the bf16 rate),
    # and rescale to f32 once per tile at split-gain time. Maps
    # histogram_method onto its q8 twin (pallas_q8 on TPU, onehot_q8
    # elsewhere); excluded with gpu_use_dp
    quantized_grad: bool = False
    tile_leaves: int = 0                            # hist tile width (0 = auto: 42)
    hist_block: int = 0                             # hist row-block size (0 = auto per method)
    # measured Pallas kernel tuning on TPU (ops/pallas_hist.py
    # autotune_hist): times the candidate row-block sizes once per shape
    # bucket (keyed like the predict engine's compile cache) and picks the
    # leaf batch structurally (the widest tile in the 128-lane group);
    # explicit tile_leaves/hist_block values always win. Serial learner
    # only — the parallel learners keep the static defaults (a measured
    # winner is wall-clock-dependent and the method/block are static SPMD
    # program parameters that must match across shards)
    hist_autotune: bool = True
    # fused split-finding epilogue + level-batched frontier growth
    # (ops/pallas_hist.py epilogue kernels, models/grower.py
    # tile_pass_fused): the split-gain scan + per-feature argmax run in
    # the histogram pass itself — in kernel on the Pallas methods — and
    # sibling pairs share one frontier launch with the larger child's
    # plane derived in-pass (parent - smaller), so the split phase
    # consumes a tiny [L, F] candidate table instead of re-reading the
    # [L, F, B, 3] planes. "auto" (default) enables it whenever the
    # numerical non-bundled search is the whole story — serial learner,
    # no categorical features, no EFB bundles, no forced splits, no CEGB,
    # no extra_trees/bynode sampling, basic-or-off monotone constraints,
    # f32 histograms — and falls back to the classic split phase
    # otherwise (those semantics stay in ops/split.py find_best_splits).
    # "on" asserts instead of falling back; "off" forces the classic
    # phase (the reference side of the fusion bit-parity suite). Model
    # text is bit-identical to the classic path on representable sums
    # (tier-1-asserted), structure-identical within documented f32
    # bounds otherwise.
    split_fusion: str = "auto"
    # run the Pallas histogram kernels through the Pallas INTERPRETER on
    # non-TPU backends (tests/CI): the production TPU pipeline — fused
    # leaf channels, in-kernel row gather, q8 — becomes CPU-testable;
    # never set in production (the interpreter is orders of magnitude
    # slower than the XLA fallbacks)
    hist_pallas_interpret: bool = False
    # histogram subtraction trick (serial_tree_learner.cpp:311-320): build
    # only the smaller sibling and derive the larger as parent - smaller
    hist_subtraction: bool = True
    # leaf-partitioned row compaction (the DataPartition analog,
    # data_partition.hpp:21-60): gather only the pending leaves' rows into
    # a padded buffer before each histogram tile pass, sized by the first
    # ladder rung that fits (fractions of the histogram row count; the
    # full-size pass remains the fallback). Serial learner only.
    hist_compaction: bool = True
    hist_compaction_ladder: List[float] = field(
        default_factory=lambda: [0.5, 0.125])
    # run gradients -> tree growth -> score update as ONE jitted program
    # per boosting iteration whenever the configuration allows it (see
    # models/gbdt.py _fused_ok for the gate and its remaining exclusions).
    # false forces the phase-by-phase path — a debugging escape hatch and
    # the reference side of the fused-vs-unfused bit-parity test suite.
    fused_iteration: bool = True
    # grow this many boosting iterations per compiled-program dispatch: a
    # lax.scan over iterations INSIDE the fused program (the scan body is
    # the fused step re-keyed by the scanned iteration index), emitting K
    # stacked iterations' trees per dispatch and carrying the score cache
    # in-program — bit-identical to K separate fused iterations (the
    # carry add uses the pre-shrunk-tree gather form so nothing can
    # FMA-contract). Amortizes both the per-iteration dispatch round trip
    # and — the big one — the first-iteration XLA compile wall across K
    # trees. Only engine.train drives block consumption (manual
    # Booster.update loops keep one-iteration semantics); evaluation,
    # callbacks and early stopping run at block boundaries, and a
    # checkpoint callback period must be a multiple of K (rejected
    # otherwise). Configurations the fused gate excludes fall back to 1.
    boost_rounds_per_dispatch: int = 1
    # persistent XLA compilation cache directory ("" = disabled unless
    # JAX_COMPILATION_CACHE_DIR is already set): compiled programs are
    # keyed by (HLO, backend, flags) and written to disk, so a restarted
    # supervisor incarnation, a resumed elastic gang, or a second
    # same-shape process pays each compile ONCE EVER instead of once per
    # process — the 232s first-iteration wall at 10.5M rows becomes a
    # cache deserialization on every later start
    compile_cache_dir: str = ""
    # AOT-warm the training programs (fused step + score add) at
    # checkpoint-restore time via jit(...).lower().compile(): with the
    # persistent cache above, a warm restart reaches its first iteration
    # with zero XLA recompiles; without it, the compile simply moves from
    # the first boosting step to restore time
    compile_warmup: bool = True

    # Inference engine (models/predict_engine.py; no reference analog)
    # row-padding floor of the predict compile cache: batch rows pad up to
    # power-of-two buckets >= this, so varying serving batch sizes reuse a
    # handful of compiled programs instead of recompiling per distinct N
    predict_bucket_min_rows: int = 1024
    # chunked streaming predict: inputs larger than this many rows run in
    # row chunks so the device never holds more than one chunk of the
    # feature matrix (0 = auto, ~4M-row chunks)
    predict_chunk_rows: int = 0
    # row-shard full-ensemble prediction over all visible devices via
    # shard_map (trees replicated, rows split; per-row accumulation order
    # is unchanged so results are bit-identical to single-device)
    predict_sharded: bool = False
    # ensemble accumulation precision: auto|float64|compensated|float32.
    # auto/float64 sums tree outputs in float64 on device IN TREE ORDER —
    # bit-identical to the host-f64 reference accumulation; compensated =
    # two-float (Kahan) f32 for backends without usable f64; float32 =
    # fastest, least precise
    predict_accum: str = "auto"

    # Serving front end (lightgbm_tpu/serving.py ServeFrontend)
    # how long the micro-batching dispatcher waits after the FIRST queued
    # request before flushing the coalesced batch (the latency the
    # batching may add to a lone request; a full batch flushes early)
    serve_flush_ms: float = 2.0
    # coalesced-batch row cap: a flush takes queued same-model requests in
    # arrival order up to this many rows (one oversized request still
    # dispatches alone — the engine chunks it internally)
    serve_max_batch_rows: int = 8192
    # admission-control cap on queued + in-flight rows: a request that
    # would push past it is SHED with a retriable ServeOverloadError
    # instead of growing the queue without bound (recorded in
    # health_snapshot() / the serve_shed_count gauge); one request larger
    # than the cap still admits on an idle frontend — it dispatches alone
    # and the engine chunks it internally
    serve_max_queue_rows: int = 65536
    # default per-request deadline in milliseconds (0 = none): a request
    # not answered in time raises a ServeTimeoutError naming the phase it
    # died in (queue-wait vs dispatch); per-request deadline_ms overrides
    serve_deadline_ms: float = 0.0
    # expose a Prometheus-style text metrics endpoint on the
    # ServeFrontend (GET /metrics renders telemetry.prometheus_text():
    # lightgbm_tpu_serve_p99_ms and friends from the latency ring, plus
    # the scopes/counters/dispatch/health planes) — started when the
    # first model registers
    serve_metrics: bool = False
    # TCP port for the /metrics endpoint (0 = an ephemeral port; read the
    # bound address from ServeFrontend.metrics_addr)
    serve_metrics_port: int = 0
    # bind host for the /metrics endpoint. Loopback by default — the
    # exposition has no auth, so exposing it is an explicit decision:
    # set "0.0.0.0" (or a specific interface) for the standard off-host
    # Prometheus scrape deployment
    serve_metrics_host: str = "127.0.0.1"

    # Telemetry (lightgbm_tpu/telemetry.py)
    # per-iteration flight recorder: a bounded in-memory ring of
    # structured records (phase wall-time deltas, dispatch/transfer
    # deltas, sentinel verdicts, OOM rungs, heartbeat ages) flushed to
    # JSONL atomically on watchdog fire / divergence verdict /
    # OOM-ladder exhaustion / training error / fault-harness kill — any
    # dead gang or failed TPU round leaves a self-describing
    # post-mortem. Reads only already-fetched host values (never forces
    # a device sync): recorder-on training keeps the fused path at 2
    # dispatches/iteration and within the <=2% overhead budget
    telemetry_flight_recorder: bool = True
    # how many per-iteration records the flight-recorder ring retains
    telemetry_ring_size: int = 256
    # sample device + host memory into every flight record (and the
    # hbm_bytes_in_use / hbm_peak_bytes / host_rss_bytes gauges): one
    # allocator query + one /proc read per iteration, zero dispatches.
    # Backends without Device.memory_stats() (CPU) record the HBM fields
    # as null — never an error
    telemetry_memory: bool = True
    # where flight-recorder JSONLs flush ("" = the supervisor's diag dir
    # when supervised, else <checkpoint_path>/telemetry, else a temp dir
    # created only when an event flush actually fires)
    telemetry_dir: str = ""
    # with a durable telemetry directory configured, also flush the ring
    # every this many iterations (a REAL SIGKILL cannot flush, so the
    # periodic flush bounds the post-mortem loss to one period; 0 = only
    # event-driven flushes)
    telemetry_flush_period: int = 64

    def __post_init__(self):
        if self.seed is not None:
            # seed derives the sub-seeds exactly like config.cpp:150-161
            self.data_random_seed = self.seed + 1
            self.bagging_seed = self.seed + 3
            self.drop_seed = self.seed + 4
            self.feature_fraction_seed = self.seed + 2
            self.extra_seed = self.seed + 6

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]] = None, **kwargs) -> "Config":
        params = dict(params or {})
        params.update(kwargs)
        resolved: Dict[str, Any] = {}
        fields = {f.name for f in dataclasses.fields(cls)}
        for key, value in params.items():
            canonical = PARAM_ALIASES.get(key, key)
            if canonical in resolved and key != canonical:
                continue  # explicit canonical name wins over alias (config.cpp KV2Map)
            if canonical not in fields:
                log.warning(f"Unknown parameter: {key}")
                continue
            resolved[canonical] = value
        cfg = cls()
        for key, value in resolved.items():
            setattr(cfg, key, _coerce(cfg, key, value))
        cfg.objective = _OBJECTIVE_ALIASES.get(cfg.objective, cfg.objective)
        cfg.metric = [_METRIC_ALIASES.get(m, m) for m in cfg.metric]
        cfg._check()
        return cfg

    def _check(self) -> None:
        # bounds checks mirroring config.h CHECK_ constraints
        if self.num_leaves < 2:
            log.fatal(f"num_leaves must be >= 2, got {self.num_leaves}")
        if not (1 < self.max_bin <= 65535):
            log.fatal(f"max_bin must be in (1, 65535], got {self.max_bin}")
        if not (0.0 < self.bagging_fraction <= 1.0):
            log.fatal("bagging_fraction should be in (0.0, 1.0]")
        if not (0.0 < self.feature_fraction <= 1.0):
            log.fatal("feature_fraction should be in (0.0, 1.0]")
        if self.objective in ("multiclass", "multiclassova") and self.num_class < 2:
            log.fatal("num_class must be >= 2 for multiclass objectives")
        if self.split_fusion not in ("auto", "on", "off"):
            log.fatal(f"split_fusion must be auto/on/off, "
                      f"got {self.split_fusion!r}")
        log.set_verbosity(self.verbosity)

    def to_params(self) -> Dict[str, Any]:
        """Canonical parameter dict (analog of Config::ToString, config_auto.cpp)."""
        out = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v != f.default and not isinstance(f.default, dataclasses._MISSING_TYPE):
                out[f.name] = v
        return out


def _coerce(cfg: Config, key: str, value: Any) -> Any:
    """Coerce a string/user value to the field's declared type (Config::Set)."""
    current = getattr(cfg, key)
    ftype = type(current)
    if value is None:
        return current
    if key == "metric":
        if isinstance(value, str):
            value = [v.strip() for v in value.split(",") if v.strip() and v.strip() != "None"]
        elif isinstance(value, (list, tuple)):
            value = list(value)
        return value
    if key == "interaction_constraints":
        # string form "[0,1],[2,3]" (reference: config.cpp
        # Config::Str2FeatureVec interaction parsing)
        if isinstance(value, str):
            import re
            return [[int(x) for x in grp.split(",") if x.strip()]
                    for grp in re.findall(r"\[([^\]]*)\]", value)]
        return [list(map(int, grp)) for grp in value]
    if key in ("valid", "label_gain", "eval_at", "monotone_constraints", "feature_contri",
               "max_bin_by_feature", "auc_mu_weights", "cegb_penalty_feature_lazy",
               "cegb_penalty_feature_coupled", "hist_compaction_ladder"):
        if isinstance(value, str):
            parts = [v for v in value.split(",") if v]
            elem = float if key in ("label_gain", "feature_contri", "auc_mu_weights",
                                    "cegb_penalty_feature_lazy", "cegb_penalty_feature_coupled",
                                    "hist_compaction_ladder") else (
                str if key == "valid" else int)
            return [elem(v) for v in parts]
        return list(value)
    if isinstance(current, bool):
        if isinstance(value, str):
            return value.lower() in ("true", "1", "yes", "+")
        return bool(value)
    if isinstance(current, int) or (current is None and key == "seed"):
        return int(value)
    if isinstance(current, float):
        return float(value)
    return value


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a CLI ``key = value`` config file (reference: application.cpp:52-85,
    Config::KV2Map). Lines after '#' are comments."""
    params: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, value = line.split("=", 1)
            params[key.strip()] = value.strip()
    return params
