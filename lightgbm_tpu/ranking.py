"""Learning-to-rank objectives and metrics.

TPU-native re-design of the reference ranking stack
(reference: src/objective/rank_objective.hpp, src/metric/rank_metric.hpp,
src/metric/map_metric.hpp, src/metric/dcg_calculator.cpp).

The reference iterates queries with OpenMP and runs an O(n_q^2) pairwise
loop per query (rank_objective.hpp:142-227). Here queries are padded into a
dense ``[Q, M]`` block (M = max query size, power-of-2 rounded) and the
pairwise computation is a masked ``[Q, M, M]`` tensor program vmapped over
queries — dense compare/where/matmul work the TPU VPU likes, no
data-dependent shapes. Deviations from the reference, by design:

- the 1M-entry sigmoid lookup table (rank_objective.hpp:235-260) is replaced
  by computing the sigmoid directly — on TPU the transcendental is cheaper
  than a gather;
- ``std::stable_sort`` rank computation becomes ``jnp.argsort`` twice
  (rank -> position), stable, identical ordering for distinct scores.

Gradients per pair follow rank_objective.hpp:142-227 exactly: delta-NDCG
weighting with |discount(rank_h) - discount(rank_l)| * gap * inv_max_dcg,
optional score-distance regularization and the log2(1+S)/S lambda
normalization (``lambdarank_norm``).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .objectives import ObjectiveFunction
from .utils import log

K_EPSILON = 1e-15


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """reference: dcg_calculator.cpp:33-41 DefaultLabelGain (2^i - 1)."""
    gains = [0.0]
    for i in range(1, max_label):
        gains.append(float((1 << i) - 1))
    return np.asarray(gains, dtype=np.float64)


def _resolve_label_gain(config: Config) -> np.ndarray:
    if config.label_gain:
        return np.asarray(config.label_gain, dtype=np.float64)
    return default_label_gain()


def group_boundaries(groups: np.ndarray) -> np.ndarray:
    """Query sizes -> boundary offsets [Q+1] (reference: Metadata::SetQuery)."""
    groups = np.asarray(groups, dtype=np.int64).reshape(-1)
    return np.concatenate([[0], np.cumsum(groups)])


def _max_dcg_at_k(k: int, labels: np.ndarray, gains: np.ndarray) -> float:
    """reference: dcg_calculator.cpp:55-78 CalMaxDCGAtK."""
    lab = np.sort(labels.astype(np.int64))[::-1][:k]
    disc = 1.0 / np.log2(2.0 + np.arange(len(lab)))
    return float(np.sum(gains[lab] * disc))


class _PaddedQueries:
    """Host-side padding plan: scatter [N] doc arrays into [Q, M] blocks."""

    def __init__(self, groups: np.ndarray):
        bounds = group_boundaries(groups)
        self.num_queries = len(bounds) - 1
        sizes = np.diff(bounds)
        m = int(max(sizes.max(), 1))
        # round up to a multiple of 8 for lane-friendly padding
        self.m = int((m + 7) // 8 * 8)
        self.sizes = sizes
        self.bounds = bounds
        q = self.num_queries
        idx = np.zeros((q, self.m), dtype=np.int64)
        mask = np.zeros((q, self.m), dtype=bool)
        for i in range(q):
            c = sizes[i]
            idx[i, :c] = np.arange(bounds[i], bounds[i + 1])
            mask[i, :c] = True
        self.doc_index = idx          # [Q, M] gather indices into [N]
        self.mask = mask              # [Q, M] validity

    def gather(self, x: np.ndarray, fill: float = 0.0) -> np.ndarray:
        out = np.full((self.num_queries, self.m), fill, dtype=np.float64)
        out[self.mask] = np.asarray(x, dtype=np.float64)[
            self.doc_index[self.mask]]
        return out

    def scatter_back(self, padded: np.ndarray, n: int) -> np.ndarray:
        out = np.zeros((n,), dtype=np.float64)
        out[self.doc_index[self.mask]] = padded[self.mask]
        return out


# ---------------------------------------------------------------- objectives
class RankingObjective(ObjectiveFunction):
    """reference: rank_objective.hpp:25 RankingObjective."""

    def init(self, label, weight, groups=None) -> None:
        super().init(label, weight, groups)
        if groups is None:
            log.fatal("Ranking tasks require query information "
                      "(set group on the Dataset)")
        self.padding = _PaddedQueries(groups)
        p = self.padding
        self.q_label = jnp.asarray(p.gather(self.label_np), jnp.float32)
        self.q_mask = jnp.asarray(p.mask)
        self.doc_index = jnp.asarray(p.doc_index, jnp.int32)
        n = self.num_data
        # flat scatter target: position of each padded slot in the doc array
        self._n = n

    def _scatter_grads(self, lam_pad: jax.Array, hess_pad: jax.Array):
        """[Q, M] padded -> [N] flat, then apply doc weights."""
        flat_idx = self.doc_index.reshape(-1)
        lam = jnp.zeros((self._n,), jnp.float32).at[flat_idx].add(
            jnp.where(self.q_mask, lam_pad, 0.0).reshape(-1))
        hess = jnp.zeros((self._n,), jnp.float32).at[flat_idx].add(
            jnp.where(self.q_mask, hess_pad, 0.0).reshape(-1))
        return self._apply_weight(lam, hess)


class LambdarankNDCG(RankingObjective):
    """reference: rank_objective.hpp:98 LambdarankNDCG."""

    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        if self.sigmoid <= 0.0:
            log.fatal(f"Sigmoid param {self.sigmoid} should be greater than zero")
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        self.gains = _resolve_label_gain(config)

    def init(self, label, weight, groups=None) -> None:
        super().init(label, weight, groups)
        p = self.padding
        inv = np.zeros((p.num_queries,), dtype=np.float64)
        for i in range(p.num_queries):
            lab = self.label_np[p.bounds[i]:p.bounds[i + 1]]
            mx = _max_dcg_at_k(self.truncation_level, lab, self.gains)
            inv[i] = 1.0 / mx if mx > 0 else 0.0
        self.inv_max_dcg = jnp.asarray(inv, jnp.float32)
        self.q_gain = jnp.asarray(
            self.gains[self.padding.gather(self.label_np).astype(np.int64)],
            jnp.float32)
        self._grad_fn = jax.jit(self._padded_grads)

    def _padded_grads(self, q_score: jax.Array):
        """All-pairs lambda computation for every padded query at once.

        q_score: [Q, M] scores (invalid slots = -inf sentinel handled by mask).
        Returns ([Q, M] lambdas, [Q, M] hessians).
        """
        label = self.q_label            # [Q, M]
        gain = self.q_gain
        mask = self.q_mask
        sig = jnp.float32(self.sigmoid)

        neg_inf = jnp.float32(-1e30)
        s = jnp.where(mask, q_score, neg_inf)
        # rank of each doc under descending stable sort (argsort of argsort)
        order = jnp.argsort(-s, axis=1, stable=True)          # [Q, M]
        rank = jnp.argsort(order, axis=1, stable=True).astype(jnp.int32)
        discount = 1.0 / jnp.log2(2.0 + rank.astype(jnp.float32))

        best = jnp.max(s, axis=1, keepdims=True)
        valid_cnt = jnp.sum(mask, axis=1, keepdims=True)
        # worst = smallest valid score
        worst = jnp.min(jnp.where(mask, s, jnp.float32(1e30)), axis=1,
                        keepdims=True)

        # pair tensors [Q, M, M]: i = high candidate, j = low candidate
        li = label[:, :, None]
        lj = label[:, None, :]
        si = s[:, :, None]
        sj = s[:, None, :]
        gi = gain[:, :, None]
        gj = gain[:, None, :]
        di = discount[:, :, None]
        dj = discount[:, None, :]
        ri = rank[:, :, None]
        rj = rank[:, None, :]

        pair_ok = (mask[:, :, None] & mask[:, None, :]
                   & (li > lj)                        # i strictly higher label
                   & ((jnp.minimum(ri, rj)) < self.truncation_level))

        delta_score = si - sj
        dcg_gap = gi - gj
        paired_disc = jnp.abs(di - dj)
        delta_ndcg = dcg_gap * paired_disc * self.inv_max_dcg[:, None, None]
        norm_on = self.norm and True
        if norm_on:
            same = (best == worst)
            delta_ndcg = jnp.where(
                same[:, :, None] | ~pair_ok, delta_ndcg,
                delta_ndcg / (0.01 + jnp.abs(delta_score)))

        p_lambda = jax.nn.sigmoid(-sig * delta_score)     # 1/(1+e^{sig*ds})
        p_hess = p_lambda * (1.0 - p_lambda)
        p_lambda = jnp.where(pair_ok, -sig * delta_ndcg * p_lambda, 0.0)
        p_hess = jnp.where(pair_ok, sig * sig * delta_ndcg * p_hess, 0.0)

        # accumulate: high (i) gets +p_lambda, low (j) gets -p_lambda
        lam = jnp.sum(p_lambda, axis=2) - jnp.sum(p_lambda, axis=1)
        hess = jnp.sum(p_hess, axis=2) + jnp.sum(p_hess, axis=1)
        sum_lambdas = -2.0 * jnp.sum(p_lambda, axis=(1, 2))   # positive

        if norm_on:
            nf = jnp.where(sum_lambdas > 0,
                           jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, K_EPSILON),
                           1.0)
            lam = lam * nf[:, None]
            hess = hess * nf[:, None]
        return lam, hess

    def get_grad_hess(self, score: jax.Array):
        q_score = score[self.doc_index]
        lam, hess = self._grad_fn(q_score)
        return self._scatter_grads(lam, hess)


class RankXENDCG(RankingObjective):
    """reference: rank_objective.hpp:285 RankXENDCG (arxiv 1911.09798)."""

    name = "rank_xendcg"
    # gamma is re-drawn from a HOST numpy RNG every GetGradients call
    # (rank_objective.hpp re-samples per iteration); inside a jitted
    # training step the draw would freeze at trace time
    jit_safe_gradients = False

    def __init__(self, config: Config):
        super().__init__(config)
        self.seed = config.objective_seed if hasattr(config, "objective_seed") \
            else config.seed

    def init(self, label, weight, groups=None) -> None:
        super().init(label, weight, groups)
        self._rng = np.random.RandomState(self.seed)
        self._grad_fn = jax.jit(self._padded_grads)

    def _padded_grads(self, q_score: jax.Array, gamma: jax.Array):
        """reference: rank_objective.hpp:306-355, vectorized over queries."""
        mask = self.q_mask
        label = self.q_label
        neg_inf = jnp.float32(-1e30)
        s = jnp.where(mask, q_score, neg_inf)
        rho = jax.nn.softmax(s, axis=1)
        rho = jnp.where(mask, rho, 0.0)

        # Phi(l, g) = 2^int(l) - g (rank_objective.hpp:356-358); labels are
        # truncated toward zero like the reference's static_cast<int>
        phi = jnp.where(mask, jnp.exp2(jnp.trunc(label)) - gamma, 0.0)
        inv_den = 1.0 / jnp.maximum(jnp.sum(phi, axis=1, keepdims=True), K_EPSILON)

        # first-order terms
        t1 = jnp.where(mask, -phi * inv_den + rho, 0.0)
        lam = t1
        params = jnp.where(mask, t1 / jnp.maximum(1.0 - rho, K_EPSILON), 0.0)
        sum_l1 = jnp.sum(params, axis=1, keepdims=True)
        # second-order terms
        t2 = jnp.where(mask, rho * (sum_l1 - params), 0.0)
        lam = lam + t2
        params = jnp.where(mask, t2 / jnp.maximum(1.0 - rho, K_EPSILON), 0.0)
        sum_l2 = jnp.sum(params, axis=1, keepdims=True)
        # third-order terms
        lam = lam + jnp.where(mask, rho * (sum_l2 - params), 0.0)
        hess = jnp.where(mask, rho * (1.0 - rho), 0.0)

        # queries with <= 1 doc get zero gradients (rank_objective.hpp:311)
        few = jnp.sum(mask, axis=1, keepdims=True) <= 1
        lam = jnp.where(few, 0.0, lam)
        hess = jnp.where(few, 0.0, hess)
        return lam, hess

    def get_grad_hess(self, score: jax.Array):
        q_score = score[self.doc_index]
        gamma = jnp.asarray(
            self._rng.uniform(size=self.q_mask.shape).astype(np.float32))
        lam, hess = self._grad_fn(q_score, gamma)
        return self._scatter_grads(lam, hess)


def create_ranking_objective(config: Config) -> RankingObjective:
    if config.objective == "lambdarank":
        return LambdarankNDCG(config)
    if config.objective == "rank_xendcg":
        return RankXENDCG(config)
    log.fatal(f"Unknown ranking objective: {config.objective}")


# ------------------------------------------------------------------- metrics
def _query_weights(weight, bounds) -> Optional[np.ndarray]:
    """Per-query weight = MEAN of its doc weights (reference:
    src/io/metadata.cpp:467-471 query_weights_)."""
    if weight is None:
        return None
    w = np.asarray(weight, dtype=np.float64)
    nq = len(bounds) - 1
    return np.array([np.sum(w[bounds[i]:bounds[i + 1]]) /
                     max(bounds[i + 1] - bounds[i], 1) for i in range(nq)])


class NDCGMetric:
    """reference: rank_metric.hpp:19 NDCGMetric. Host-side (numpy)."""

    bigger_is_better = True

    def __init__(self, config: Config):
        self.eval_at = list(config.eval_at) if config.eval_at else [1, 2, 3, 4, 5]
        self.gains = _resolve_label_gain(config)
        self.name = [f"ndcg@{k}" for k in self.eval_at]

    def init(self, label, weight, groups=None) -> None:
        if groups is None:
            log.fatal("The NDCG metric requires query information")
        self.label = np.asarray(label, dtype=np.float64)
        self.bounds = group_boundaries(groups)
        self.num_queries = len(self.bounds) - 1
        self.query_weights = _query_weights(weight, self.bounds)
        self.inv_max = np.zeros((self.num_queries, len(self.eval_at)))
        for i in range(self.num_queries):
            lab = self.label[self.bounds[i]:self.bounds[i + 1]]
            for j, k in enumerate(self.eval_at):
                mx = _max_dcg_at_k(k, lab, self.gains)
                self.inv_max[i, j] = 1.0 / mx if mx > 0 else -1.0

    def eval(self, score: np.ndarray, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(-1)
        res = np.zeros(len(self.eval_at))
        total_w = 0.0
        for i in range(self.num_queries):
            w = 1.0 if self.query_weights is None else self.query_weights[i]
            total_w += w
            lab = self.label[self.bounds[i]:self.bounds[i + 1]]
            sc = score[self.bounds[i]:self.bounds[i + 1]]
            if self.inv_max[i, 0] <= 0:
                res += w  # all-negative query counts as NDCG=1
                continue
            order = np.argsort(-sc, kind="stable")
            disc = 1.0 / np.log2(2.0 + np.arange(len(lab)))
            g = self.gains[lab[order].astype(np.int64)]
            for j, k in enumerate(self.eval_at):
                kk = min(k, len(lab))
                res[j] += w * np.sum(g[:kk] * disc[:kk]) * self.inv_max[i, j]
        return list(res / max(total_w, K_EPSILON))


class MapMetric:
    """reference: map_metric.hpp:20 MapMetric (mean average precision @ k)."""

    bigger_is_better = True

    def __init__(self, config: Config):
        self.eval_at = list(config.eval_at) if config.eval_at else [1, 2, 3, 4, 5]
        self.name = [f"map@{k}" for k in self.eval_at]

    def init(self, label, weight, groups=None) -> None:
        if groups is None:
            log.fatal("The MAP metric requires query information")
        self.label = np.asarray(label, dtype=np.float64)
        self.bounds = group_boundaries(groups)
        self.num_queries = len(self.bounds) - 1
        self.query_weights = _query_weights(weight, self.bounds)

    def eval(self, score: np.ndarray, objective=None) -> List[float]:
        """reference: map_metric.hpp:58-84 CalMapAtK per query."""
        score = np.asarray(score, dtype=np.float64).reshape(-1)
        res = np.zeros(len(self.eval_at))
        total_w = 0.0
        for i in range(self.num_queries):
            w = 1.0 if self.query_weights is None else self.query_weights[i]
            total_w += w
            lab = self.label[self.bounds[i]:self.bounds[i + 1]]
            sc = score[self.bounds[i]:self.bounds[i + 1]]
            order = np.argsort(-sc, kind="stable")
            rel = lab[order] > 0.5
            npos_total = int(np.count_nonzero(rel))
            hits = np.cumsum(rel)
            prec = hits / (1.0 + np.arange(len(rel)))
            for j, k in enumerate(self.eval_at):
                kk = min(k, len(rel))
                if npos_total > 0:
                    # reference: map_metric.hpp sum_ap / min(npos, k)
                    res[j] += w * np.sum(prec[:kk] * rel[:kk]) / min(npos_total, kk)
                else:
                    res[j] += w  # queries without positives count as 1
        return list(res / max(total_w, K_EPSILON))


def create_ranking_metric(name: str, config: Config):
    if name == "ndcg":
        return NDCGMetric(config)
    if name == "map":
        return MapMetric(config)
    return None
