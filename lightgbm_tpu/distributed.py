"""Multi-host bootstrap: the analog of the reference's distributed init.

The reference bootstraps its socket/MPI mesh from ``machines`` +
``local_listen_port`` + ``num_machines`` (reference:
src/network/linkers_socket.cpp:24-63 parse machine list, identify own rank
by local-IP match :38, bind + full-mesh handshake;
src/application/application.cpp:167-178 CLI init; Dask injects the same
params per worker, python-package/lightgbm/dask.py:211-330).

On TPU the entire linker layer collapses into ``jax.distributed.initialize``:
after it, every process sees the GLOBAL device set, `jax.devices()` spans
all hosts, and the same shard_map programs the single-host learners run
scale over ICI/DCN with zero further changes — collectives are compiled
into the program, so there is no rank-tagged socket protocol to speak.

Usage (one call per process, before constructing any Booster):

    import lightgbm_tpu as lgb
    lgb.distributed.init()                       # env-based (TPU pods)
    # or explicitly, the reference's machine-list style:
    lgb.distributed.init(machines="10.0.0.1:12400,10.0.0.2:12400")
    # or from a config/params dict holding machines/num_machines:
    lgb.distributed.init(params={"machines": "...", "num_machines": 2})

Rank resolution mirrors linkers_socket.cpp:38: if ``process_id`` is not
given, the local host's addresses are matched against the machine list.
On managed TPU pods (GKE/Cloud TPU), call ``init()`` with no arguments —
JAX's cluster autodetection fills coordinator/rank from the environment.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from .utils import log

_initialized = False

# exit code a supervised rank uses when its collective watchdog fires —
# distinct from the fault harness's 137 kill so the supervisor can tell
# "rank died" from "rank declared the gang stalled"
WATCHDOG_EXIT_CODE = 97

# exit code a spawned child uses when it could not even come up (spawn/
# bootstrap failure before distributed init) — the supervisor classifies
# the rank as PERMANENTLY lost and shrinks the gang instead of burning
# same-size restarts on a machine that cannot start
SPAWN_FAIL_EXIT_CODE = 96

# exit code a supervised rank uses when the cross-rank integrity check
# (check_model_integrity) identifies IT as the minority whose model state
# silently diverged from the gang: the supervisor charges the corrupt
# rank's restart budget (like a hard kill — the rank's state is bad by
# majority evidence) and restarts the gang from the last valid checkpoint,
# or shrinks the rank away once the budget is exhausted
DIVERGENCE_EXIT_CODE = 95


def is_initialized() -> bool:
    return _initialized or _jax_already_initialized()


def _jax_already_initialized() -> bool:
    """True when jax.distributed was initialized (by us or externally)."""
    import jax
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception:
            pass
    try:
        from jax._src import distributed as jax_dist
        return jax_dist.global_state.client is not None
    except Exception:
        return False


def _local_addresses() -> set:
    addrs = {"127.0.0.1", "::1", "localhost", "0.0.0.0"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    # primary interface IP: a connected UDP socket reveals the address the
    # kernel would route from (no packet is sent)
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        addrs.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    return addrs


def _split_host_port(entry: str):
    """host[:port] -> (host, port-str|None); handles [v6]:port and bare
    IPv6 (which must not be split at its last hextet)."""
    if entry.startswith("["):
        host, _, rest = entry[1:].partition("]")
        return host, (rest[1:] if rest.startswith(":") else None)
    if entry.count(":") > 1:
        return entry, None        # bare IPv6
    host, _, port = entry.partition(":")
    return host, (port or None)


def _entry_matches_local(host: str, local: set) -> bool:
    if host in local:
        return True
    # the reference compares RESOLVED addresses (linkers_socket.cpp:38):
    # a machines entry may be an interface IP or FQDN that plain hostname
    # probing never surfaces
    try:
        for info in socket.getaddrinfo(host, None):
            if info[4][0] in local:
                return True
    except OSError:
        pass
    return False


def _rank_from_machines(machines: list,
                        listen_port: Optional[int] = None) -> Optional[int]:
    """Identify this process's rank by local-IP match (the reference's
    protocol, linkers_socket.cpp:38). With several processes on one host,
    ``listen_port`` (the reference's local_listen_port) disambiguates by
    exact host:port match; an ambiguous match without it is fatal rather
    than silently rank 0."""
    local = _local_addresses()
    parsed = [_split_host_port(m) for m in machines]
    matches = [i for i, (host, _port) in enumerate(parsed)
               if _entry_matches_local(host, local)]
    if listen_port is not None:
        exact = [i for i in matches
                 if parsed[i][1] == str(listen_port)]
        if len(exact) == 1:
            return exact[0]
    if len(matches) > 1:
        log.fatal(f"multiple machines entries match this host "
                  f"({[machines[i] for i in matches]}); set "
                  f"local_listen_port or process_id to disambiguate")
    return matches[0] if matches else None


def init(machines: Optional[str] = None,
         num_machines: Optional[int] = None,
         process_id: Optional[int] = None,
         coordinator_address: Optional[str] = None,
         params: Optional[dict] = None,
         local_device_ids=None,
         connect_retries: int = 5,
         connect_backoff: float = 1.0,
         connect_timeout: Optional[float] = None) -> None:
    """Initialize multi-host training (idempotent).

    Args:
      machines: comma-separated "host:port,host:port,..." — the reference's
        ``machines`` parameter (config.h:989). The FIRST entry is the
        coordinator.
      num_machines: process count; defaults to len(machines).
      process_id: this process's rank; default: local-IP match against the
        machine list (linkers_socket.cpp:38) or the JAX env autodetection.
      coordinator_address: overrides the coordinator (host:port).
      params: a params/config mapping — ``machines``/``num_machines``/
        ``local_listen_port``/``time_out`` are read from it when the
        explicit args are absent (so CLI configs written for the reference
        work unchanged).
      local_device_ids: forwarded to ``jax.distributed.initialize``.
      connect_retries: attempts to reach the coordinator before giving up
        (a slow-starting rank 0 must not fail the whole cluster — the
        reference's socket linker retries its connect the same way,
        linkers_socket.cpp TryBind/Connect loops).
      connect_backoff: initial retry delay in seconds; doubles per attempt
        (capped at 30s).
      connect_timeout: overall deadline in seconds across retries
        (defaults to the ``time_out`` parameter when given via params).
    """
    global _initialized
    if _initialized:
        log.warning("distributed.init called twice; ignoring")
        return
    import jax
    if _jax_already_initialized():
        # standard JAX practice initializes jax.distributed once at process
        # startup; treat that as ours rather than crashing on re-init
        log.info("jax.distributed already initialized externally; adopting")
        _initialized = True
        return

    listen_port = None
    if params:
        get = params.get if hasattr(params, "get") else \
            lambda k, d=None: getattr(params, k, d)
        machines = machines or get("machines") or None
        num_machines = num_machines or int(get("num_machines") or 0) or None
        lp = get("local_listen_port")
        listen_port = int(lp) if lp else None
        if connect_timeout is None:
            to = get("time_out")
            connect_timeout = float(to) if to else None

    mlist = [m.strip() for m in machines.split(",") if m.strip()] \
        if machines else []
    if mlist:
        if num_machines is None:
            num_machines = len(mlist)
        if coordinator_address is None:
            coordinator_address = mlist[0]
        if process_id is None:
            process_id = _rank_from_machines(mlist, listen_port)
            if process_id is None:
                log.fatal(f"none of this host's addresses match the "
                          f"machines list {mlist} (set process_id "
                          f"explicitly)")

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_machines is not None:
        kwargs["num_processes"] = num_machines
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    _initialize_with_backoff(kwargs, connect_retries, connect_backoff,
                             connect_timeout)
    _initialized = True
    log.info(f"distributed: process {jax.process_index()} of "
             f"{jax.process_count()}, {len(jax.devices())} global devices")


def _initialize_with_backoff(kwargs: dict, retries: int, backoff: float,
                             timeout: Optional[float]) -> None:
    """``jax.distributed.initialize`` under bounded exponential backoff: a
    coordinator (rank 0) that is still starting up must not fail the
    cluster; a coordinator that never comes up must fail with an error
    naming the address that was unreachable."""
    import time
    import jax
    attempts = max(1, int(retries))
    delay = max(0.0, float(backoff))
    deadline = (time.monotonic() + timeout) if timeout else None
    for attempt in range(1, attempts + 1):
        try:
            jax.distributed.initialize(**kwargs)
            return
        except (ValueError, TypeError):
            # configuration errors (malformed address, bad argument
            # combinations) are permanent: fail fast, don't sleep on them
            raise
        except Exception as e:  # jax raises backend-specific error types
            out_of_time = deadline is not None \
                and time.monotonic() + delay > deadline
            if attempt >= attempts or out_of_time:
                addr = kwargs.get("coordinator_address") \
                    or os.environ.get("JAX_COORDINATOR_ADDRESS") \
                    or "<env-autodetected coordinator>"
                log.fatal(
                    f"could not connect to the distributed coordinator at "
                    f"{addr} after {attempt} attempt(s)"
                    + (f" within {timeout:g}s" if out_of_time else "")
                    + f": {e}")
            log.warning(f"coordinator connect attempt {attempt}/{attempts} "
                        f"failed ({e}); retrying in {delay:.1f}s")
            time.sleep(delay)
            delay = min(max(delay, 0.1) * 2, 30.0)


def barrier(name: str = "barrier", timeout: Optional[float] = None) -> None:
    """Cross-process synchronization point (no-op single-process). Used by
    the checkpoint writer so no rank races past a checkpoint another rank
    may later resume from.

    Prefers the distributed COORDINATION-SERVICE barrier (pure gRPC — no
    XLA computation, so it works on every backend and takes a hard
    deadline, the analog of the reference's socket ``time_out``,
    linkers_socket.cpp TimeOut) over ``sync_global_devices`` (a
    device collective). With a ``collective_deadline`` watchdog armed, the
    barrier inherits its deadline: a peer that died or hung before
    reaching the barrier surfaces as a DistributedTimeoutError (or a
    supervised watchdog exit) naming the suspects instead of an
    indefinite wait."""
    import jax
    if jax.process_count() <= 1:
        return
    wd = _active_health.watchdog if _active_health is not None else None
    if timeout is None and wd is not None:
        timeout = wd.deadline
    client = None
    try:
        from jax._src import distributed as jax_dist
        client = jax_dist.global_state.client
    except Exception:
        pass
    with watchdog_phase(f"barrier:{name}"):
        if client is not None:
            try:
                client.wait_at_barrier(
                    f"lgbm_tpu_{name}",
                    int((timeout or 3600.0) * 1000))
                return
            except DistributedTimeoutError:
                raise
            except Exception as e:
                # the coordination client's error type varies by jax
                # version: classify timeouts by message
                msg = str(e)
                if "DEADLINE_EXCEEDED" in msg or "imed out" in msg \
                        or "BarrierTimedOut" in msg:
                    _barrier_timed_out(name, wd, e)
                raise
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def _coordination_client():
    """The jax distributed coordination-service client (pure gRPC — works
    on every backend, including this container's CPU backend that cannot
    run cross-process XLA computations); None single-process or when jax
    exposes no client."""
    import jax
    if jax.process_count() <= 1:
        return None
    try:
        from jax._src import distributed as jax_dist
        return jax_dist.global_state.client
    except Exception:
        return None


_exchange_seq = 0


def exchange_host(tag: str, payload: str,
                  timeout: Optional[float] = None) -> List[str]:
    """Allgather a SMALL host-side string across processes, returning the
    per-rank payloads in rank order. This is the swappable collective
    floor the sharded-checkpoint protocol stands on: it prefers the
    coordination-service key-value store (pure gRPC, like ``barrier``), so
    it works even where cross-process XLA collectives don't (this
    container's CPU backend), and falls back to
    ``multihost_utils.process_allgather`` on clusters without a
    coordination client. Single-process: returns ``[payload]``.

    Callers must invoke it in lockstep on every rank with the same
    ``tag`` (keys are sequence-numbered per process, so lockstep keeps
    them agreed). Payloads should stay small (shard metadata, row counts
    — not data)."""
    global _exchange_seq
    import jax
    nproc = jax.process_count()
    if nproc <= 1:
        return [payload]
    rank = jax.process_index()
    client = _coordination_client()
    wd = _active_health.watchdog if _active_health is not None else None
    if timeout is None:
        timeout = wd.deadline if wd is not None else 600.0
    with watchdog_phase(f"exchange:{tag}"):
        if client is not None:
            _exchange_seq += 1
            prefix = f"lgbm_tpu_xchg/{tag}/{_exchange_seq}"
            client.key_value_set(f"{prefix}/r{rank}", payload)
            out = []
            for r in range(nproc):
                out.append(client.blocking_key_value_get(
                    f"{prefix}/r{r}", int(timeout * 1000)))
            # NO cleanup: deleting a key here races peers that have not
            # read it yet (their blocking get would then wait out the full
            # timeout and fail a healthy gang). Keys are sequence-
            # namespaced and the KV store lives only as long as the gang's
            # coordination service, so the leak is bounded and harmless.
            return out
        # no coordination client: fall back to an XLA-level allgather of
        # the utf-8 bytes padded to the max length
        import numpy as np
        from jax.experimental import multihost_utils
        raw = payload.encode()
        ln = np.asarray([len(raw)], np.int32)
        lens = np.asarray(multihost_utils.process_allgather(ln)).reshape(-1)
        width = max(1, int(lens.max()))
        buf = np.zeros((width,), np.uint8)
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
        gathered = np.asarray(
            multihost_utils.process_allgather(buf)).reshape(nproc, width)
        return [bytes(gathered[r, :int(lens[r])].tobytes()).decode()
                for r in range(nproc)]


def repartition_rows(old_ranges, row_start: int, row_count: int,
                     fetch_shard):
    """Reassemble one rank's row slice ``[row_start, row_start+row_count)``
    of a globally row-partitioned array from shards written under a
    DIFFERENT (or the same) partition — the load half of resume-at-a-
    different-world-size.

    Args:
      old_ranges: per-old-rank ``(row_start, row_count)`` pairs in rank
        order, tiling ``[0, sum(counts))`` contiguously.
      row_start, row_count: the slice the calling rank needs under the NEW
        partition.
      fetch_shard: ``fetch_shard(old_rank) -> np.ndarray`` returning that
        old rank's shard array (rows first). Called ONLY for old shards
        that overlap the requested slice, so a same-partition resume
        touches exactly its own shard.

    Returns the concatenated rows (np.ndarray), bit-identical to the
    original global array's slice — re-partitioning is pure row movement,
    so resume at any world size starts from the exact same per-row state.
    Raises ValueError when the old ranges do not tile the requested slice.
    """
    import numpy as np
    lo, hi = int(row_start), int(row_start) + int(row_count)
    if row_count == 0:
        # preserve trailing dims + dtype (multiclass caches are [n, k]):
        # an empty slice must still merge cleanly with non-empty peers
        if old_ranges:
            return fetch_shard(0)[:0]
        return np.zeros((0,), np.float32)
    pieces = []
    covered = lo
    for old_rank, (s, c) in enumerate(old_ranges):
        s, e = int(s), int(s) + int(c)
        if e <= lo or s >= hi:
            continue
        a, b = max(s, lo), min(e, hi)
        if a != covered:
            raise ValueError(
                f"shard ranges do not tile rows [{lo}, {hi}): gap at row "
                f"{covered} (old rank {old_rank} covers [{s}, {e}))")
        shard = fetch_shard(old_rank)
        if shard.shape[0] != c:
            raise ValueError(
                f"shard for old rank {old_rank} has {shard.shape[0]} rows, "
                f"its recorded partition says {c}")
        pieces.append(shard[a - s:b - s])
        covered = b
    if covered != hi:
        raise ValueError(
            f"shard ranges do not tile rows [{lo}, {hi}): rows "
            f"[{covered}, {hi}) are not covered by any shard")
    return pieces[0] if len(pieces) == 1 else np.concatenate(pieces, axis=0)


def _barrier_timed_out(name: str, wd, cause) -> None:
    """A deadlined barrier expired: some peer never arrived. Route through
    the watchdog's diagnosis when one is armed (supervised ranks exit for
    the gang supervisor); otherwise raise a diagnosable error directly."""
    global _last_diagnosis
    snap = dict(_progress.snapshot(), phase=f"barrier:{name}")
    if wd is not None:
        if wd.supervised:
            wd._fire(snap)            # writes diagnosis, then os._exit
        _last_diagnosis = wd._diagnose(snap)
    else:
        _last_diagnosis = {"rank": 0, "iteration": snap["iter"],
                           "phase": snap["phase"], "suspects": None}
    raise DistributedTimeoutError() from cause


# ===================================================== training supervision
# Heartbeat + collective-deadline watchdog: the detection half of the gang
# supervisor (lightgbm_tpu/supervisor.py holds the restart half). The
# reference survives a dead worker through per-socket recv timeouts
# (linkers_socket.cpp TimeOut on every Recv); jax collectives have no such
# deadline — a killed or hung rank stalls every shard_map psum forever. The
# watchdog restores the reference's property: a bounded wait, then a
# DIAGNOSABLE error naming the suspect rank(s) and the last completed
# iteration.
#
#   - Every rank runs a heartbeat thread that reports
#     (rank, last-completed iteration, current in-step iteration) to rank 0
#     over a lightweight TCP side-channel (newline-JSON request/response;
#     the address comes from LGBM_TPU_HEARTBEAT_ADDR, set by the
#     supervisor, or an explicit start_health call). Rank 0's reply carries
#     the aggregated table, so EVERY rank can name suspects, not just 0.
#   - The watchdog thread checks the current phase (boosting step or
#     cross-process barrier) against ``collective_deadline``. On expiry it
#     writes a JSON diagnosis (LGBM_TPU_DIAG_DIR), then either hard-exits
#     with WATCHDOG_EXIT_CODE (supervised mode — the supervisor tears down
#     the gang and relaunches from the latest checkpoint) or raises
#     DistributedTimeoutError in the main thread.

_SUPERVISED_ENV = "LGBM_TPU_SUPERVISED"
_HEARTBEAT_ADDR_ENV = "LGBM_TPU_HEARTBEAT_ADDR"
_DIAG_DIR_ENV = "LGBM_TPU_DIAG_DIR"
_RESTART_COUNT_ENV = "LGBM_TPU_RESTART_COUNT"

_last_diagnosis: Optional[dict] = None


class DistributedTimeoutError(Exception):
    """A collective (boosting step or barrier) exceeded the configured
    ``collective_deadline``. Carries the diagnosing rank, the last
    completed iteration, and the suspect rank(s) the heartbeat table
    implicates. Constructed argument-free by the watchdog's asynchronous
    raise, in which case the message comes from the last diagnosis."""

    def __init__(self, *args, rank=None, iteration=None, suspects=None,
                 phase=None):
        diag = _last_diagnosis or {}
        self.rank = rank if rank is not None else diag.get("rank")
        self.iteration = iteration if iteration is not None \
            else diag.get("iteration")
        self.suspects = suspects if suspects is not None \
            else diag.get("suspects")
        self.phase = phase if phase is not None else diag.get("phase")
        if not args:
            args = (format_timeout_message(self.rank, self.iteration,
                                           self.suspects, self.phase,
                                           diag.get("deadline")),)
        super().__init__(*args)


def format_timeout_message(rank, iteration, suspects, phase,
                           deadline) -> str:
    if suspects:
        sus = "rank(s) " + ", ".join(str(s) for s in suspects)
    elif suspects is not None:
        sus = "none identified (heartbeat table shows all ranks current)"
    else:
        sus = "unknown rank (no heartbeat table)"
    return (f"collective deadline"
            + (f" ({deadline:g}s)" if deadline else "")
            + f" exceeded on rank {rank} in {phase or 'step'}: "
            f"last completed iteration {iteration}; suspect {sus}. "
            f"The gang is stalled — restart it from the latest checkpoint "
            f"(lightgbm_tpu.supervisor does this automatically).")


class _Progress:
    """Per-process training progress the heartbeat reports and the
    watchdog judges against: a stack of active phases (step / barrier)
    plus the last COMPLETED boosting iteration."""

    def __init__(self):
        self.lock = threading.Lock()
        self.last_iter = -1            # last completed boosting iteration
        self.step_iter = -1            # iteration currently inside a step
        self.steps_done = 0            # steps completed IN THIS PROCESS —
        #   the compile-exemption clock: last_iter is the GLOBAL iteration
        #   and starts at k on a resumed incarnation, which would strip
        #   the fresh process's first-step/first-eval compile exemptions
        self.phases = []               # [(label, start_monotonic)]
        self.last_transition = None    # monotonic time of last begin/end

    def reset(self) -> None:
        """Fresh training run: clear completed-iteration history so the
        first-step compile exemption applies again."""
        with self.lock:
            self.last_iter = -1
            self.step_iter = -1
            self.steps_done = 0
            self.phases = []
            self.last_transition = None

    def begin(self, label: str, iteration: Optional[int] = None) -> None:
        with self.lock:
            now = time.monotonic()
            self.phases.append((label, now))
            self.last_transition = now
            if iteration is not None:
                self.step_iter = iteration

    def end(self, iteration: Optional[int] = None) -> None:
        with self.lock:
            if self.phases:
                self.phases.pop()
            self.last_transition = time.monotonic()
            if iteration is not None:
                if iteration > self.last_iter:
                    self.steps_done += 1
                self.last_iter = iteration
                if not self.phases:
                    self.step_iter = -1

    def snapshot(self) -> dict:
        with self.lock:
            now = time.monotonic()
            top = self.phases[-1] if self.phases else None
            return {"iter": self.last_iter, "step": self.step_iter,
                    "steps_done": self.steps_done,
                    "phase": top[0] if top else None,
                    "phase_elapsed": (now - top[1]) if top else 0.0,
                    "idle_elapsed": (now - self.last_transition)
                    if self.last_transition is not None else 0.0}


_progress = _Progress()


def notify_step_begin(iteration: int, label: str = "step") -> None:
    """Mark entry into boosting iteration ``iteration`` (the watchdog's
    clock starts; the heartbeat starts reporting it as in-flight)."""
    _progress.begin(f"{label}:{iteration}", iteration)


def notify_step_end(iteration: int) -> None:
    """Mark completion of boosting iteration ``iteration``."""
    _progress.end(iteration)


def notify_step_retry(iteration: int) -> None:
    """Re-arm the step clock for a RETRIED iteration (the OOM degradation
    ladder): the failed attempt's elapsed time must not be charged to the
    retry, and the retry recompiles the degraded programs — so it gets the
    same compile exemption as a first step (the watchdog skips
    ``step-retry:`` phases; degradation is single-process only, so no peer
    is left waiting on an exempted collective). Counters are untouched:
    the iteration did not complete."""
    _progress.end()
    _progress.begin(f"step-retry:{iteration}", iteration)


class watchdog_phase:
    """Context manager marking a non-step collective phase (barriers,
    allgathers) so the watchdog times it too. Reentrant; no-op overhead
    when no watchdog is armed (the progress stack is a few list ops)."""

    def __init__(self, label: str):
        self.label = label

    def __enter__(self):
        _progress.begin(self.label)
        return self

    def __exit__(self, *exc):
        _progress.end()
        return False


class HeartbeatMonitor:
    """Rank liveness over a TCP side-channel.

    Rank 0 runs the aggregation server; every rank (0 included) feeds its
    progress in every ``interval`` seconds and receives the aggregated
    table back. The table maps rank -> {iter, step, age} where ``age`` is
    seconds since that rank's last report reached rank 0."""

    def __init__(self, rank: int, nproc: int, addr: str,
                 interval: float = 5.0):
        self.rank = int(rank)
        self.nproc = int(nproc)
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.interval = max(0.2, float(interval))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._server_table: Dict[int, dict] = {}   # rank0: rank -> report
        self._table: Dict[int, dict] = {}          # last aggregated view
        self._threads = []
        self._server_sock = None

    # ------------------------------------------------------------- server
    def _serve(self) -> None:
        srv = self._server_sock
        srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="lgbm-hb-conn")
            t.start()
            self._threads.append(t)

    def _handle(self, conn) -> None:
        conn.settimeout(max(4 * self.interval, 10.0))
        try:
            fh = conn.makefile("rw", encoding="utf-8", newline="\n")
            for line in fh:
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                now = time.monotonic()
                with self._lock:
                    self._server_table[int(msg.get("rank", -1))] = {
                        "iter": msg.get("iter", -1),
                        "step": msg.get("step", -1),
                        "recv": now}
                    reply = json.dumps({"table": self._aggregated()})
                fh.write(reply + "\n")
                fh.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _aggregated(self) -> dict:
        # caller HOLDS self._lock (mutates and iterates the table)
        mine = _progress.snapshot()
        now = time.monotonic()
        self._server_table[self.rank] = {"iter": mine["iter"],
                                         "step": mine["step"], "recv": now}
        out = {str(r): {"iter": e["iter"], "step": e["step"],
                        "age": round(now - e["recv"], 3)}
               for r, e in self._server_table.items()}
        # mirror into the health gauges (bench.py JSON / postmortems):
        # heartbeat age + last completed iteration per rank
        from .utils import profiling
        for r, e in out.items():
            profiling.set_gauge(f"heartbeat_age_rank{r}", e["age"])
            profiling.set_gauge(f"last_iter_rank{r}", e["iter"])
        return out

    # ------------------------------------------------------------- client
    def _beat(self) -> None:
        fh = None
        while not self._stop.is_set():
            if fh is None:
                try:
                    conn = socket.create_connection(self.addr, timeout=5.0)
                    conn.settimeout(max(4 * self.interval, 10.0))
                    fh = conn.makefile("rw", encoding="utf-8", newline="\n")
                except OSError:
                    self._stop.wait(self.interval)
                    continue
            mine = _progress.snapshot()
            try:
                fh.write(json.dumps({"rank": self.rank,
                                     "iter": mine["iter"],
                                     "step": mine["step"],
                                     "t": time.time()}) + "\n")
                fh.flush()
                reply = json.loads(fh.readline())
                with self._lock:
                    self._table = {int(r): dict(e) for r, e in
                                   reply.get("table", {}).items()}
            except (OSError, ValueError):
                try:
                    fh.close()
                except OSError:
                    pass
                fh = None
            self._stop.wait(self.interval)
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    # -------------------------------------------------------------- api
    def start(self) -> "HeartbeatMonitor":
        if self.rank == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(self.addr)
            srv.listen(max(self.nproc, 8))
            self._server_sock = srv
            t = threading.Thread(target=self._serve, daemon=True,
                                 name="lgbm-hb-server")
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._beat, daemon=True,
                             name="lgbm-hb-client")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass

    def table(self) -> Dict[int, dict]:
        """Latest aggregated liveness table (rank -> iter/step/age)."""
        if self.rank == 0:
            with self._lock:
                return {int(r): dict(e)
                        for r, e in self._aggregated().items()}
        with self._lock:
            return {r: dict(e) for r, e in self._table.items()}

    def suspects(self, my_step: int, my_iter: int = -1) -> Optional[list]:
        """Ranks implicated in a stall: dead (stale heartbeat), missing
        (never reported), or lagging (their reported progress — completed
        iteration or in-flight step — is behind this rank's: the hung-rank
        signature, where the process is alive and its heartbeat fresh but
        it never dispatched the step everyone else is blocked in).
        Returns None (unknown) when the table is empty — an unreplied
        heartbeat must not masquerade as confident evidence implicating
        every rank including the caller."""
        table = self.table()
        if not table:
            return None
        out = set()
        stale_after = max(3 * self.interval, 5.0)
        my_progress = max(my_step, my_iter)
        for r in range(self.nproc):
            e = table.get(r)
            if e is None:
                out.add(r)
                continue
            progress = max(e.get("step", -1), e.get("iter", -1))
            if e.get("age", 0.0) > stale_after:
                out.add(r)
            elif my_progress >= 0 and progress < my_progress \
                    and r != self.rank:
                out.add(r)
        return sorted(out)


class CollectiveWatchdog:
    """Deadline monitor over the progress stack. ``deadline`` seconds after
    a phase (boosting step / barrier) begins without ending, the watchdog
    diagnoses the stall and terminates it — supervised ranks exit with
    WATCHDOG_EXIT_CODE for the gang supervisor to reap; unsupervised runs
    get a DistributedTimeoutError raised in the main thread."""

    def __init__(self, deadline: float, rank: int = 0,
                 heartbeat: Optional[HeartbeatMonitor] = None,
                 supervised: Optional[bool] = None,
                 diag_dir: Optional[str] = None):
        self.deadline = float(deadline)
        self.rank = int(rank)
        self.heartbeat = heartbeat
        self.supervised = (os.environ.get(_SUPERVISED_ENV) == "1"
                           if supervised is None else bool(supervised))
        self.diag_dir = diag_dir if diag_dir is not None \
            else os.environ.get(_DIAG_DIR_ENV)
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._main_thread = threading.main_thread()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CollectiveWatchdog":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="lgbm-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        tick = min(0.25, self.deadline / 4)
        while not self._stop.wait(tick):
            snap = _progress.snapshot()
            if snap["phase"] is None:
                # between steps: the training loop itself has gone quiet —
                # the HUNG rank's own signature (its peers see a stalled
                # step; it sees nothing moving). Judged only after TWO
                # steps completed IN THIS PROCESS: the first between-steps
                # interval holds the initial valid-set eval's jit compile,
                # which — like the first step's own compile — says nothing
                # about a stalled peer and must not kill a healthy gang
                # (in-process count, so resumed/relaunched incarnations
                # keep the exemption for THEIR first interval too).
                if snap["steps_done"] >= 2 \
                        and snap["idle_elapsed"] > self.deadline:
                    snap = dict(snap, phase="between-steps (host-side)")
                    self._fire(snap)
                    return
                continue
            # compile warm-up exemption: the FIRST boosting step THIS
            # PROCESS runs includes jit compilation, whose wall time has
            # nothing to do with a stalled collective — step phases are
            # judged only once one in-process step completed. Barriers and
            # other explicitly marked collective phases (no compile
            # inside) are always judged; a gang member dying before anyone
            # finishes its first step is caught by the supervisor's
            # incarnation timeout.
            if snap["phase"].startswith("step:") and snap["steps_done"] < 1:
                continue
            # an OOM-degraded retry recompiles the shrunk programs: same
            # rationale as the first-step exemption (and single-process by
            # construction — gangs fail-stop on OOM, so no stalled peer
            # hides behind this phase)
            if snap["phase"].startswith("step-retry:"):
                continue
            if snap["phase_elapsed"] > self.deadline:
                self._fire(snap)
                return

    def _diagnose(self, snap: dict) -> dict:
        suspects = None
        table = None
        if self.heartbeat is not None:
            try:
                suspects = self.heartbeat.suspects(snap["step"],
                                                   snap["iter"])
                table = {str(r): e for r, e in
                         self.heartbeat.table().items()}
            except Exception:
                pass
        return {"rank": self.rank, "iteration": snap["iter"],
                "stalled_iteration": snap["step"], "phase": snap["phase"],
                "elapsed": round(snap["phase_elapsed"], 3),
                "deadline": self.deadline, "suspects": suspects,
                "heartbeat_table": table,
                # wall + monotonic stamps: the post-mortem analyzer
                # orders this fire against OOM rungs and flight records
                "t": time.time(), "t_mono": time.monotonic(),
                "kind": "watchdog"}

    def _fire(self, snap: dict) -> None:
        global _last_diagnosis
        diag = self._diagnose(snap)
        _last_diagnosis = diag
        self._fired.set()
        msg = format_timeout_message(diag["rank"], diag["iteration"],
                                     diag["suspects"], diag["phase"],
                                     self.deadline)
        log.warning(f"watchdog: {msg}")
        # flush the flight recorder NOW (the training thread is stalled
        # inside the very collective being diagnosed) and embed its path
        # in the diagnosis: the supervisor report then references a
        # per-iteration post-mortem, not just the final stack state
        try:
            from . import telemetry
            diag["flight_recorder"] = telemetry.flush_recorder(
                f"watchdog: {msg}")
        except Exception:
            pass
        if self.diag_dir:
            try:
                os.makedirs(self.diag_dir, exist_ok=True)
                with open(os.path.join(
                        self.diag_dir,
                        f"watchdog_rank{self.rank}.json"), "w") as fh:
                    json.dump(diag, fh, indent=1)
            except OSError:
                pass
        if self.supervised:
            # a rank blocked inside a native collective cannot be unstuck
            # from Python: exit with the watchdog code and let the
            # supervisor tear down and relaunch the gang
            import sys
            sys.stderr.write(f"[watchdog] {msg}\n")
            sys.stderr.flush()
            os._exit(WATCHDOG_EXIT_CODE)
        # unsupervised: asynchronously raise in the main thread. This lands
        # as soon as the main thread runs Python bytecode again — it
        # un-sticks Python-level stalls (the fault harness's hang loop, a
        # slow host phase); a thread parked inside a native collective only
        # sees it on return, which is the best Python can do without a
        # supervisor process.
        import ctypes
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_long(self._main_thread.ident),
            ctypes.py_object(DistributedTimeoutError))

    @property
    def fired(self) -> bool:
        return self._fired.is_set()


class _Health:
    """The per-training supervision bundle: optional heartbeat + optional
    watchdog, started together by engine.train and stopped in its
    finally."""

    def __init__(self, heartbeat, watchdog):
        self.heartbeat = heartbeat
        self.watchdog = watchdog

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.heartbeat is not None:
            self.heartbeat.stop()
        global _active_health
        if _active_health is self:
            _active_health = None


_active_health: Optional[_Health] = None


def start_health(config=None, heartbeat_addr: Optional[str] = None) -> _Health:
    """Start training supervision for this process from config:

    - a HeartbeatMonitor when ``heartbeat_interval`` > 0, this is a
      multi-process run, and a side-channel address is known (the
      LGBM_TPU_HEARTBEAT_ADDR env the supervisor sets, or
      ``heartbeat_addr``);
    - a CollectiveWatchdog when ``collective_deadline`` > 0.

    Idempotent per training run; returns a handle whose ``stop()`` the
    caller owns. With neither enabled the handle is inert."""
    global _active_health
    if _active_health is not None:
        return _Health(None, None)    # nested train(): inert handle
    import jax
    interval = float(getattr(config, "heartbeat_interval", 0.0) or 0.0)
    deadline = float(getattr(config, "collective_deadline", 0.0) or 0.0)
    addr = heartbeat_addr or os.environ.get(_HEARTBEAT_ADDR_ENV)
    try:
        rank, nproc = jax.process_index(), jax.process_count()
    except Exception:
        rank, nproc = 0, 1
    if interval > 0 or deadline > 0:
        _progress.reset()   # fresh run: first-step compile exemption anew
    heartbeat = None
    if interval > 0 and nproc > 1 and addr:
        try:
            heartbeat = HeartbeatMonitor(rank, nproc, addr,
                                         interval).start()
        except OSError as e:
            log.warning(f"heartbeat disabled: cannot reach side-channel "
                        f"{addr}: {e}")
    watchdog = None
    if deadline > 0:
        watchdog = CollectiveWatchdog(deadline, rank,
                                      heartbeat=heartbeat).start()
    health = _Health(heartbeat, watchdog)
    if heartbeat is not None or watchdog is not None:
        _active_health = health
    return health


def health_snapshot() -> dict:
    """Health telemetry for bench.py JSON and checkpoint manifests:
    restart count (from the supervisor's env), this process's progress,
    the per-rank heartbeat table when a monitor is live, and every OOM
    degradation event this process stepped down (an operator reading a
    manifest can see a job is running DEGRADED rather than discovering it
    at the bill)."""
    snap = _progress.snapshot()
    out = {
        "restart_count": int(os.environ.get(_RESTART_COUNT_ENV, "0") or 0),
        "last_iteration": snap["iter"],
        "in_step_iteration": snap["step"],
    }
    h = _active_health
    if h is not None and h.heartbeat is not None:
        out["heartbeat"] = {str(r): {"iter": e.get("iter", -1),
                                     "step": e.get("step", -1),
                                     "age": e.get("age", -1.0)}
                            for r, e in h.heartbeat.table().items()}
        out["heartbeat_interval"] = h.heartbeat.interval
    if h is not None and h.watchdog is not None:
        out["collective_deadline"] = h.watchdog.deadline
    if _degradations:
        out["degradations"] = list(_degradations)
    # serving-layer gauges (queue depth, in-flight rows, shed/timeout
    # counts, latency percentiles — lightgbm_tpu/serving.py): surfaced
    # here so an operator reading a manifest or bench JSON sees the serve
    # plane's health next to the training plane's
    from .utils import profiling
    serve = {k: v for k, v in profiling.gauges().items()
             if k.startswith("serve_")}
    if serve:
        out["serve"] = serve
    # memory gauges (the flight recorder samples them per iteration —
    # telemetry_memory): HBM in-use/peak + host RSS watermarks, so a
    # checkpoint manifest or bench JSON shows what the run COST in
    # memory, not just what it did. Absent until the first sample (CPU
    # backends record only the host fields).
    mem = {k: int(v) for k, v in profiling.gauges().items()
           if k in ("hbm_bytes_in_use", "hbm_peak_bytes",
                    "host_rss_bytes", "host_rss_peak_bytes")}
    if mem:
        out["memory"] = mem
    # flight-recorder post-mortem path BY REFERENCE (telemetry.py): a
    # checkpoint manifest or bench JSON embedding this snapshot tells an
    # operator where the per-iteration ring flushes, without inlining it
    try:
        from . import telemetry
        fr = telemetry.recorder_path()
        if fr:
            out["flight_recorder"] = fr
    except Exception:
        pass
    return out


def heartbeat_ages() -> Optional[Dict[str, float]]:
    """Per-rank heartbeat ages (seconds since last report) when a
    heartbeat monitor is live in this process, else None. The cheap
    host-side accessor the flight recorder records each iteration."""
    h = _active_health
    if h is None or h.heartbeat is None:
        return None
    try:
        return {str(r): float(e.get("age", -1.0))
                for r, e in h.heartbeat.table().items()}
    except Exception:
        return None


# ====================================================== training integrity
# The verification half of the fail-silent story: the fail-stop machinery
# above (heartbeats, watchdog, supervisor) catches ranks that DIE or HANG;
# this layer catches ranks whose state silently diverged (bit flips, bad
# DIMMs, kernel nondeterminism) and jobs that keep running but degraded
# (OOM fallbacks). The reference's distributed learners stay correct only
# because every rank executes bit-identical reductions — here that
# invariant is CHECKED: every ``integrity_check_period`` iterations the
# ranks exchange a cheap fingerprint of the global model state over the
# coordination service and majority-vote any mismatch.

# OOM degradation events this process recorded (models/gbdt.py
# _maybe_degrade_oom): surfaced through health_snapshot() and therefore
# every later checkpoint manifest's health section
_degradations: List[dict] = []


def record_degradation(event: dict) -> dict:
    """Record one degradation event (kind/iteration/level/action/error).
    Returns the STORED dict (the caller's is copied), so episode-style
    callers (serve shedding) can update one recorded event in place
    instead of growing the log per occurrence.

    Every stored event gains a wall timestamp (``t``), a MONOTONIC
    timestamp (``t_mono`` — post-mortem timelines order OOM rungs
    against watchdog fires with it, immune to wall-clock steps) and,
    when the caller didn't supply one, the training loop's active
    iteration (from the progress tracker; -1 before any step)."""
    event = dict(event)
    event["seq"] = len(_degradations)
    event.setdefault("t", time.time())
    event["t_mono"] = time.monotonic()
    if "iteration" not in event:
        try:
            event["iteration"] = int(_progress.snapshot()["iter"])
        except Exception:
            event["iteration"] = -1
    _degradations.append(event)
    from .utils import profiling
    # the gauge is the OOM ladder's (PR 8 failure-mode table) — serve
    # shed/swap events share the log but must not inflate it
    profiling.set_gauge("oom_degradations",
                        float(sum(1 for d in _degradations
                                  if "oom" in d.get("kind", ""))))
    return event


def degradations() -> List[dict]:
    """Degradation events recorded so far (in order)."""
    return list(_degradations)


def reset_degradations() -> None:
    """Clear the process-level degradation log. Called when a NEW
    training run initializes (GBDT._init_train) so a later booster's
    health snapshots — and therefore its checkpoint manifests — don't
    report an earlier, unrelated booster's events as their own."""
    _degradations.clear()
    from .utils import profiling
    profiling.set_gauge("oom_degradations", 0.0)


class RankDivergenceError(Exception):
    """The cross-rank integrity check found ranks whose model state does
    not match the gang's majority. ``corrupt_ranks`` names the minority
    (the ranks whose state diverged); with ``indeterminate`` no majority
    exists (e.g. a 1:1 split at world size 2) and the listed ranks are
    merely the disagreeing parties — restart the whole gang from the last
    checkpoint."""

    def __init__(self, iteration: int, corrupt_ranks, table,
                 indeterminate: bool = False):
        self.iteration = int(iteration)
        self.corrupt_ranks = list(corrupt_ranks)
        self.table = table
        self.indeterminate = bool(indeterminate)
        if indeterminate:
            msg = (f"model-state divergence detected at iteration "
                   f"{iteration}: ranks {self.corrupt_ranks} disagree and "
                   f"no majority exists — cannot name the corrupt rank; "
                   f"restart the gang from the last valid checkpoint")
        else:
            msg = (f"model-state divergence detected at iteration "
                   f"{iteration}: rank(s) {self.corrupt_ranks} hold state "
                   f"that differs from the gang's majority (silent "
                   f"corruption — bit flip, bad memory, or "
                   f"nondeterministic kernel). Restart the corrupt "
                   f"rank(s) from the last valid checkpoint "
                   f"(lightgbm_tpu.supervisor does this automatically).")
        super().__init__(msg)


def model_fingerprint(boosting) -> dict:
    """Cheap fingerprint of one rank's view of the global model state:

    - ``trees``: sha256 over every tree's structure AND values (split
      feature/threshold-bin per node, leaf values) — rank-symmetric by the
      SPMD contract, so it is comparable across EVERY rank;
    - ``score``: sha256 of the exact f32 train-score-cache bytes over this
      rank's row range — comparable only between ranks holding the same
      rows (all of them when replicated; recorded with the row range so
      the vote groups pre-partitioned ranks correctly).

    Reading it flushes the async host-tree mirrors and fetches the score
    cache — a per-``integrity_check_period`` cost, not per-iteration."""
    import hashlib
    import numpy as np
    h = hashlib.sha256()
    for ht in boosting.host_trees:
        nl = int(ht.num_leaves)
        nn = max(nl - 1, 0)
        h.update(np.int32(nl).tobytes())
        h.update(np.ascontiguousarray(
            np.asarray(ht.split_feature[:nn], np.int32)).tobytes())
        h.update(np.ascontiguousarray(
            np.asarray(ht.threshold_bin[:nn], np.int64)).tobytes())
        h.update(np.ascontiguousarray(
            np.asarray(ht.leaf_value[:nl], np.float64)).tobytes())
    score = np.ascontiguousarray(
        np.asarray(boosting.train_score, np.float32))
    ts = boosting.train_set
    row_start = int(getattr(ts, "local_row_start", 0) or 0) \
        if ts is not None else 0
    return {
        "rank": jax_rank(),
        "trees": h.hexdigest(),
        "score": hashlib.sha256(score.tobytes()).hexdigest(),
        "row_start": row_start,
        "row_count": int(score.shape[0]),
    }


def jax_rank() -> int:
    import jax
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def divergence_verdict(entries):
    """Majority vote over per-rank fingerprints. Returns
    ``(corrupt_ranks, indeterminate)``: the minority rank(s) whose
    fingerprints differ from a strict majority, or — when no strict
    majority exists for some disputed component — every disagreeing rank
    with ``indeterminate=True``. Tree hashes vote globally (they are
    rank-symmetric); score checksums vote only within groups of ranks
    holding the SAME row range (pre-partitioned ranks hold disjoint rows
    whose checksums differ by design)."""
    from collections import Counter
    suspects = set()
    indeterminate = False

    def vote(group, key):
        nonlocal indeterminate
        counts = Counter(key(e) for e in group)
        if len(counts) <= 1:
            return
        _, best_n = counts.most_common(1)[0]
        if best_n * 2 <= len(group):
            indeterminate = True
            suspects.update(int(e["rank"]) for e in group)
        else:
            best = counts.most_common(1)[0][0]
            suspects.update(int(e["rank"]) for e in group
                            if key(e) != best)

    vote(entries, lambda e: e["trees"])
    by_range: Dict[tuple, list] = {}
    for e in entries:
        by_range.setdefault(
            (int(e.get("row_start", 0)), int(e.get("row_count", -1))),
            []).append(e)
    for group in by_range.values():
        if len(group) > 1:
            vote(group, lambda e: e["score"])
    return sorted(suspects), indeterminate


def check_model_integrity(boosting, iteration: int,
                          timeout: Optional[float] = None) -> None:
    """Cross-rank divergence check, called in lockstep on every rank
    every ``integrity_check_period`` iterations (engine.train). Exchanges
    each rank's :func:`model_fingerprint` over the coordination service
    (pure gRPC — works on backends without cross-process XLA) and
    majority-votes mismatches.

    Clean gang: returns. Divergence, unsupervised: raises
    :class:`RankDivergenceError` on every rank, naming the minority.
    Divergence, supervised (LGBM_TPU_SUPERVISED=1): the CORRUPT rank
    writes a ``divergence_rank{r}.json`` diagnosis and exits with
    ``DIVERGENCE_EXIT_CODE`` so the supervisor restarts the gang from the
    last valid checkpoint charging that rank's restart budget (a rank
    that keeps diverging is shrunk away); honest ranks log and continue —
    the supervisor tears them down and relaunches. No-op single-process."""
    import jax
    if jax.process_count() <= 1:
        return
    from .utils import profiling
    mine = model_fingerprint(boosting)
    payloads = exchange_host(f"integrity_{iteration}", json.dumps(mine),
                             timeout=timeout)
    entries = [json.loads(p) for p in payloads]
    corrupt, indeterminate = divergence_verdict(entries)
    profiling.set_gauge("integrity_checks_run",
                        profiling.gauges().get("integrity_checks_run", 0.0)
                        + 1.0)
    profiling.set_gauge("integrity_last_iteration", float(iteration))
    # dedup marker: the checkpoint callback votes before every save but
    # must not re-vote an iteration engine.train already certified
    boosting._integrity_checked_iter = int(iteration)
    if not corrupt:
        return
    table = {str(e["rank"]): {"trees": e["trees"][:16],
                              "score": e["score"][:16]} for e in entries}
    err = RankDivergenceError(iteration, corrupt, table,
                              indeterminate=indeterminate)
    rank = mine["rank"]
    supervised = os.environ.get(_SUPERVISED_ENV) == "1"
    if supervised and not indeterminate:
        if rank in corrupt:
            # write the diagnosis the supervisor folds into its report,
            # then exit with the divergence code: by majority evidence
            # THIS rank's state is bad, and a checkpoint restore is the
            # only way back to the gang's truth
            diag_dir = os.environ.get(_DIAG_DIR_ENV)
            diag = {"rank": rank, "iteration": int(iteration),
                    "corrupt_ranks": corrupt, "fingerprints": table,
                    "kind": "divergence",
                    "t": time.time(), "t_mono": time.monotonic()}
            try:
                from . import telemetry
                diag["flight_recorder"] = telemetry.flush_recorder(
                    f"divergence: rank {rank} voted corrupt at iteration "
                    f"{iteration}")
            except Exception:
                pass
            if diag_dir:
                try:
                    os.makedirs(diag_dir, exist_ok=True)
                    with open(os.path.join(
                            diag_dir, f"divergence_rank{rank}.json"),
                            "w") as fh:
                        json.dump(diag, fh, indent=1)
                except OSError:
                    pass
            import sys
            sys.stderr.write(f"[integrity] {err}\n")
            sys.stderr.flush()
            os._exit(DIVERGENCE_EXIT_CODE)
        # honest majority rank: its state is good — log and keep going;
        # the supervisor reaps the corrupt rank's exit, tears this gang
        # down and relaunches it from the last valid checkpoint
        log.warning(f"integrity check: {err} (this rank is in the "
                    f"majority; awaiting supervisor restart)")
        return
    raise err


def shutdown() -> None:
    global _initialized
    if not _initialized:
        return
    import jax
    jax.distributed.shutdown()
    _initialized = False


def maybe_init_from_config(config) -> None:
    """Auto-init when a Booster is constructed with num_machines > 1 and
    distributed training was not explicitly initialized (the CLI flow,
    application.cpp:167-178: Network::Init happens before training)."""
    if _initialized:
        return
    if _jax_already_initialized():
        return
    nm = int(getattr(config, "num_machines", 1) or 1)
    if nm > 1:
        # params=config also carries local_listen_port for same-host rank
        # disambiguation
        init(num_machines=nm, params=config)


def spawn(fn, nproc: int = 2, args: tuple = (),
          per_rank_args: Optional[list] = None,
          devices_per_proc: Optional[int] = None,
          timeout: Optional[float] = 600.0):
    """Run ``fn(rank, *args)`` in ``nproc`` freshly spawned local processes
    wired into one jax.distributed cluster, and return rank 0's result —
    the single-host analog of the reference's Dask orchestration
    (python-package/lightgbm/dask.py:211-330 _train: find open ports,
    inject machines/num_machines/local_listen_port per worker, run local
    fits, return the rank-0 model; examples/parallel_learning's mlist
    flow). Co-location is the caller's: ``fn`` typically slices its rank's
    rows and calls ``load_partitioned`` + ``train``.

    ``fn`` must be picklable (a module-level function). Each child calls
    ``distributed.init`` before ``fn`` runs; ``devices_per_proc`` forces a
    virtual CPU device count (tests), otherwise children inherit the
    environment. ``timeout`` is the OVERALL deadline for all ranks; a
    child that dies without reporting fails fast with its exit code.
    With ``per_rank_args`` (length nproc), rank r is called
    ``fn(r, per_rank_args[r], *args)`` — each child ships ONLY its own
    payload (a worker's data partition must not be pickled to every other
    worker). Returns rank 0's return value (must be picklable); raises
    RuntimeError with the failing rank's traceback on error.
    """
    import multiprocessing as mp
    import queue as _queue
    import time as _time

    if per_rank_args is not None and len(per_rank_args) != nproc:
        raise ValueError(f"per_rank_args has {len(per_rank_args)} entries "
                         f"for {nproc} ranks")
    port = free_port()
    machines = ",".join(f"127.0.0.1:{port}" for _ in range(nproc))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(
        target=_spawn_child,
        args=(q, fn, r, nproc, machines, devices_per_proc,
              args if per_rank_args is None
              else (per_rank_args[r],) + tuple(args)))
        for r in range(nproc)]
    for p in procs:
        p.start()
    results = {}
    deadline = None if timeout is None else _time.monotonic() + timeout
    try:
        while len(results) < nproc:
            try:
                rank, ok, payload = q.get(timeout=1.0)
            except _queue.Empty:
                # a segfaulted/OOM-killed child never enqueues: fail fast
                # with the dead rank identified instead of waiting out the
                # full deadline
                for r, p in enumerate(procs):
                    if r not in results and not p.is_alive() \
                            and p.exitcode not in (0, None):
                        raise RuntimeError(
                            f"distributed.spawn rank {r} died with exit "
                            f"code {p.exitcode} before reporting")
                if deadline is not None and _time.monotonic() > deadline:
                    missing = [r for r in range(nproc) if r not in results]
                    raise RuntimeError(
                        f"distributed.spawn timed out after {timeout}s "
                        f"waiting for ranks {missing}")
                continue
            if not ok:
                raise RuntimeError(
                    f"distributed.spawn rank {rank} failed:\n{payload}")
            results[rank] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)
            if p.is_alive():          # SIGTERM swallowed in native code
                p.kill()
                p.join(timeout=10)
    return results.get(0)


def free_port() -> int:
    """Grab an ephemeral localhost port (bind-then-close; shared by
    ``spawn`` and the multi-host test harness so the idiom lives once)."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def prepare_cpu_device_env(env, devices_per_proc: int) -> None:
    """Force ``devices_per_proc`` virtual CPU devices in an environment
    mapping (child-process setup shared by ``spawn`` and the test
    harnesses): pins JAX_PLATFORMS=cpu, clears JAX_NUM_CPU_DEVICES (which
    would override the XLA flag), and rewrites
    --xla_force_host_platform_device_count."""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_NUM_CPU_DEVICES", None)
    flags = [t for t in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in t]
    flags.append(
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    env["XLA_FLAGS"] = " ".join(flags)


def _spawn_child(q, fn, rank, nproc, machines, devices_per_proc, args):
    import traceback
    from .utils import faults
    # spawn-fail injection point: the child dies BEFORE bootstrap (the
    # "machine cannot start" shape — bad image, dead host, lost quota) so
    # the supervisor's permanent-loss classification can be exercised
    faults.maybe_fail_spawn(rank)
    try:
        if devices_per_proc is not None:
            prepare_cpu_device_env(os.environ, devices_per_proc)
            import jax
            jax.config.update("jax_platforms", "cpu")
        init(machines=machines, num_machines=nproc, process_id=rank)
        result = fn(rank, *args)
        # pre-pickle INSIDE the try: Queue.put pickles later, in a feeder
        # thread, so an unpicklable return value would otherwise vanish
        # (child exits 0, parent waits out the full deadline)
        import pickle
        pickle.dumps(result)
        q.put((rank, True, result))
    except BaseException:
        q.put((rank, False, traceback.format_exc()))


def _train_part(rank, part, params, num_boost_round, train_kwargs):
    """Per-worker body of ``train_distributed`` (module-level so spawn can
    pickle it): build the local pre-partitioned Dataset, run the standard
    train loop (collectives ride the jitted programs), return the model
    text — the exact shape of the reference's dask ``_train_part``
    (python-package/lightgbm/dask.py:73-124)."""
    from .engine import train as _train
    ds = load_partitioned(part["data"], label=part.get("label"),
                          weight=part.get("weight"),
                          init_score=part.get("init_score"),
                          params=params)
    booster = _train(params, ds, num_boost_round, **train_kwargs)
    return booster.model_to_string()


def train_distributed(params, parts, num_boost_round: int = 100,
                      devices_per_proc: Optional[int] = None,
                      timeout: Optional[float] = 900.0,
                      **train_kwargs):
    """Distributed training over pre-partitioned data, orchestrated like
    the reference's Dask layer (python-package/lightgbm/dask.py:211-330
    ``_train``: co-locate partitions per worker, find an open port, inject
    machines/num_machines per worker, run local fits, return the rank-0
    model).

    Args:
      params: training params; ``tree_learner`` defaults to "data" and must
        be one of data/voting/feature (the same restriction the reference's
        dask layer enforces, dask.py:301-311).
      parts: one dict per worker — {"data": X, "label": y,
        "weight": optional, "init_score": optional}. Each worker sees ONLY
        its part (the reference's data_parallel pre-partitioned mode:
        data never leaves its machine, dataset_loader.cpp:182-258).
      num_boost_round: boosting rounds.
      devices_per_proc: force N virtual CPU devices per worker (tests).
      timeout: overall deadline handed to ``spawn``.
      **train_kwargs: forwarded to ``engine.train`` in each worker.

    Returns the trained Booster (rank 0's model, loaded locally).
    """
    params = dict(params or {})
    learner = str(params.get("tree_learner", "data") or "data")
    allowed = {"data", "voting", "feature"}
    if learner not in allowed:
        log.fatal(f"train_distributed requires tree_learner in {allowed} "
                  f"(got {learner!r}) — the reference's dask layer has the "
                  f"same restriction (dask.py:301-311)")
    params["tree_learner"] = learner
    if "num_machines" in params:
        nm = int(params["num_machines"])
        if nm != len(parts):
            log.fatal(f"num_machines={nm} but {len(parts)} parts given")
    model_str = spawn(_train_part, nproc=len(parts),
                      args=(params, num_boost_round, dict(train_kwargs)),
                      per_rank_args=list(parts),
                      devices_per_proc=devices_per_proc, timeout=timeout)
    from .booster import Booster
    return Booster(params=params, model_str=model_str)


def allgather_f64(arr):
    """``process_allgather`` that PRESERVES float64 bits by gathering the
    raw bytes: with jax x64 disabled, a plain allgather round-trips
    through f32 device arrays and truncates. Returns [nproc, *arr.shape].
    """
    import numpy as np
    from jax.experimental import multihost_utils
    a = np.ascontiguousarray(np.asarray(arr, np.float64))
    g = np.ascontiguousarray(np.asarray(
        multihost_utils.process_allgather(a.view(np.uint8))))
    return g.reshape((-1,) + a.shape[:-1]
                     + (a.shape[-1] * 8,)).view(np.float64)


# ------------------------------------------------ distributed data loading
def load_partitioned(data, label=None, weight=None, init_score=None,
                     params: Optional[dict] = None,
                     feature_name="auto", categorical_feature="auto"):
    """Pre-partitioned multi-host Dataset: each process passes ITS OWN row
    slice; bin mappers are fitted from an allgathered row sample so every
    process agrees, and the binned matrix becomes one GLOBAL row-sharded
    device array over the full mesh.

    The analog of the reference's distributed loading (reference:
    dataset_loader.cpp:1046-1128 feature-sharded bin finding merged by
    Network::Allgather, :843 pre-partitioned per-machine loading,
    Metadata::CheckOrPartition dataset.h:86). Here the SAMPLE is what
    crosses hosts (a few hundred KB) — each process samples
    bin_construct_sample_cnt / num_processes of its local rows, the
    samples allgather, and identical mappers are fitted everywhere; the
    full data never leaves its host.

    Returns a constructed ``Dataset`` whose ``bins`` is a global jax.Array
    sharded over processes; ``num_data`` is the GLOBAL row count while
    label/weight stay process-local. Pass it straight to ``lgb.train`` /
    ``Booster`` with ``tree_learner="data"`` (or voting): scores,
    gradients and the leaf-id vector all stay process-local / row-sharded
    through the whole boosting loop (the reference's per-machine score
    partition, score_updater.hpp — memory per machine FALLS as machines
    are added, docs/Experiments.rst:228-242), with EFB bundling and the
    feature-major fast path both active. Metrics evaluate on each
    process's local partition, like the reference's per-machine metric
    logs. Not supported: dart, linear_tree, rollback_one_iter.
    """
    import jax
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import binning
    from .basic import Dataset, _to_2d_float
    from .config import Config
    from .parallel.data_parallel import make_mesh

    config = Config.from_params(dict(params or {}))
    X = _to_2d_float(data)
    n_local, f = X.shape
    nproc = jax.process_count()

    # ---- distributed bin finding: allgather a per-process row sample
    per_proc = max(1, config.bin_construct_sample_cnt // max(nproc, 1))
    idx = binning.sample_indices(n_local, per_proc,
                                 config.data_random_seed + jax.process_index())
    sample_local = np.ascontiguousarray(X[idx]).astype(np.float64)
    # pad to a common row count so allgather shapes agree
    pad = per_proc - sample_local.shape[0]
    if pad > 0:
        sample_local = np.pad(sample_local, ((0, pad), (0, 0)),
                              constant_values=np.nan)
        valid_local = np.concatenate([np.ones(len(idx), bool),
                                      np.zeros(pad, bool)])
    else:
        valid_local = np.ones(per_proc, bool)
    if nproc > 1:
        # bit-exact f64 sample gather (a plain allgather truncates to f32
        # with x64 off, making bin bounds differ from a 1-process run)
        gathered = allgather_f64(sample_local)
        valid = np.asarray(
            multihost_utils.process_allgather(valid_local)).reshape(-1)
        sample = gathered.reshape(-1, f)[valid]
        local_counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([n_local], np.int32)))
        n_global = int(local_counts.sum())
    else:
        sample = sample_local[valid_local]
        local_counts = np.asarray([[n_local]])
        n_global = n_local

    ds = Dataset(X, label=label, weight=weight, init_score=init_score,
                 params=dict(params or {}), feature_name=feature_name,
                 categorical_feature=categorical_feature)
    names = ([f"Column_{i}" for i in range(f)]
             if feature_name in ("auto", None) else list(feature_name))
    cats = ds._resolve_categorical(f, names)
    cat_set = set(int(c) for c in cats)
    from .basic import _load_forced_bins
    forced = _load_forced_bins(config, f, cats)
    filter_cnt = binning.filter_cnt_for_sample(config, len(sample), n_global)
    mappers = [binning.fit_mapper_for_column(
        j, np.asarray(sample[:, j]), len(sample), config, cat_set,
        filter_cnt, forced) for j in range(f)]

    # bin the LOCAL rows against the agreed mappers, then assemble the
    # global row-sharded device matrix (each process contributes only its
    # addressable shards)
    ds.mappers = mappers
    ds.used_features = np.array(
        [j for j, m in enumerate(mappers) if not m.is_trivial], np.int32)
    ds.num_data = n_global
    ds.num_total_features = f
    ds._feature_names = names
    # EFB over the agreed (allgathered) sample: identical inputs on every
    # process -> identical bundle assignment, so the bundled column layout
    # needs no further cross-host negotiation (the analog of the
    # reference's sample-driven FastFeatureBundling, dataset.cpp:239).
    # enable_bundle=false skips the bundling machinery ENTIRELY (plain
    # per-feature columns) rather than building singleton bundles — the
    # layout load_partitioned_chunks produces, so the chunked and
    # monolithic loaders are bit-comparable with bundling off
    if config.enable_bundle:
        ds._run_bundling(sample, len(sample), config)
    else:
        ds.bundles = None
    if ds.bundles is not None and len(ds.bundles):
        ds._build_feature_meta_bundled(config)
        local_bins = ds._bin_columns(X)
    else:
        ds.bundles = None
        ds._build_feature_meta(config)
        used = [mappers[j] for j in ds.used_features]
        local_bins = binning.bin_data(
            X[:, ds.used_features] if len(ds.used_features)
            else np.zeros((n_local, 0)), used)
    dtype = np.uint8 if ds.max_num_bins <= 256 else np.int32
    _shard_local_bins(ds, local_bins.astype(dtype), local_counts)
    g = ds.num_used_features()
    log.info(f"pre-partitioned dataset: {n_local} local rows of "
             f"{n_global} global, {len(ds.used_features)} used features"
             + (f" (bundled into {g} columns)" if ds.bundles else ""))
    return ds


def _shard_local_bins(ds, local_bins, local_counts) -> None:
    """Assemble a rank's LOCAL binned rows into the global row-sharded
    device matrix and finish the pre-partitioned Dataset bookkeeping —
    the shared tail of ``load_partitioned`` (monolithic local matrix) and
    ``load_partitioned_chunks`` (streamed local chunks). ``local_counts``
    is every rank's local row count in rank order (array or list)."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .parallel.data_parallel import make_mesh

    nproc = jax.process_count()
    n_local = int(local_bins.shape[0])
    counts = [int(c) for c in np.asarray(local_counts).reshape(-1)]
    # pad local rows to a common per-process count divisible by the local
    # device count so the global sharding has equal shards; padded rows are
    # excluded from histograms by the zero-padded sample mask the grower
    # applies
    n_loc_dev = jax.local_device_count()
    max_local = max(counts)
    target = -(-max_local // n_loc_dev) * n_loc_dev
    if target > n_local:
        local_bins = np.pad(local_bins, ((0, target - n_local), (0, 0)))
    mesh = make_mesh(axis="shard")
    sharding = NamedSharding(mesh, P("shard", None))
    if nproc > 1:
        ds.bins = multihost_utils.host_local_array_to_global_array(
            local_bins, mesh, P("shard", None))
    else:
        ds.bins = jax.device_put(jax.numpy.asarray(local_bins), sharding)
    # the feature-major copy (doubles the dominant array) is built LAZILY
    # by the prepart-aware Dataset.bins_T property, so histogram methods
    # that never read it (scatter/binloop) pay nothing
    ds.raw_data_np = None
    ds.is_pre_partitioned = True
    ds.num_local_data = n_local
    # global row partition bookkeeping for sharded checkpoints: this
    # rank's first global row and every rank's local row count (the
    # PARTITION.json the checkpoint writer records; see checkpoint.py)
    rank = jax.process_index()
    ds.partition_counts = counts
    ds.local_row_start = int(sum(counts[:rank]))
    ds._constructed = True
    if ds.free_raw_data:
        ds.data = None


def merge_feature_sketches(sketches, tag: str = "construct"):
    """Allgather per-feature construct sketches as JSON over
    ``exchange_host`` and fold them together IN RANK ORDER — the
    streaming twin of the reference's distributed bin finding
    (dataset_loader.cpp:1046-1128: per-machine FindBin merged by
    Network::Allgather). Deterministic: every rank receives the same
    payloads in the same order and merges identically, so the mappers
    fitted from the result agree bit-exactly everywhere (float values
    serialize via repr, which round-trips f64). Single process: returns
    the input unchanged. Payload size is bounded by
    ``num_features * sketch_max_size`` distinct values — the sketch, not
    the data, is what crosses hosts."""
    import jax

    from . import binning

    if jax.process_count() <= 1:
        return list(sketches)
    sketches = list(sketches)
    # agree on the feature count FIRST (one tiny exchange): a mismatch
    # must fail loudly here — discovered later it would desync the
    # batched exchange below into a lockstep hang
    nfs = [int(json.loads(p)) for p in
           exchange_host(f"sketch_{tag}_nf", json.dumps(len(sketches)))]
    if len(set(nfs)) != 1:
        log.fatal(f"pre-partitioned chunk sources disagree on feature "
                  f"count across ranks: {nfs}")
    # exchange_host's contract is SMALL payloads (its KV store has no
    # chunking): a saturated sketch is ~sketch_max_size repr'd f64s per
    # feature (~25 B each), so features are exchanged in batches bounded
    # to a few MB. Batch boundaries derive only from values every rank
    # agrees on (feature count + the config's sketch_max_size), keeping
    # the per-batch tags in lockstep.
    max_size = max((sk.max_size for sk in sketches), default=0)
    per_batch = (len(sketches) if not max_size
                 else max(1, (4 << 20) // max(1, max_size * 25)))
    merged: List = []
    for b0 in range(0, len(sketches), per_batch):
        batch = sketches[b0:b0 + per_batch]
        payload = json.dumps([sk.to_dict() for sk in batch])
        parts = exchange_host(f"sketch_{tag}_b{b0}", payload)
        batch_merged = [binning.FeatureSketch.from_dict(d)
                        for d in json.loads(parts[0])]
        for r, part in enumerate(parts[1:], start=1):
            dicts = json.loads(part)
            if len(dicts) != len(batch_merged):
                # a zip would silently truncate and fit subtly-wrong
                # mappers deterministically on every rank — fail loudly
                # instead, like sketch_chunks' mid-stream width check
                log.fatal(f"rank {r} sketched {len(dicts)} features in "
                          f"batch {b0}, rank 0 sketched "
                          f"{len(batch_merged)}: pre-partitioned chunk "
                          f"sources disagree on feature count")
            for sk, d in zip(batch_merged, dicts):
                sk.merge(binning.FeatureSketch.from_dict(d))
        merged.extend(batch_merged)
    return merged


def load_partitioned_chunks(chunks, label=None, weight=None, init_score=None,
                            params: Optional[dict] = None,
                            feature_name="auto",
                            categorical_feature="auto"):
    """Streaming pre-partitioned loader: each process folds ITS OWN row
    chunks into per-feature sketches (host memory O(chunk) — the raw
    local matrix never materializes), the sketches merge across ranks
    over ``exchange_host`` (:func:`merge_feature_sketches`), identical
    BinMappers are fitted everywhere from the merged summaries, and each
    rank bins its chunks straight into its shard of the global
    row-sharded bin matrix. The chunked twin of :func:`load_partitioned`
    for the 100M-row regime where even one host's row slice dwarfs RAM.

    ``chunks``: this rank's local chunk source (``binning.chunk_factory``
    forms: callable/sequence/2-D array), each chunk ``[rows, F]`` or an
    ``(X, y)`` pair whose label parts concatenate into the local label.
    EFB bundling does not apply (it needs sampled row patterns; dense
    chunk columns map 1:1 to device columns like the dense monolithic
    construct) — for parity against ``load_partitioned`` run that side
    with ``enable_bundle=false``. Same training contract as
    ``load_partitioned``: label/weight stay process-local,
    ``tree_learner="data"``/voting, no dart/linear_tree."""
    import time as _time

    import jax
    import numpy as np

    from . import binning
    from .basic import Dataset, _load_forced_bins
    from .config import Config
    from .utils import profiling

    config = Config.from_params(dict(params or {}))
    profiling.drop_gauges("construct_")   # this construction's gauges only
    factory = binning.chunk_factory(chunks, config.construct_chunk_rows)
    peak = [0]

    def track(nbytes, mult=1):
        peak[0] = max(peak[0], mult * int(nbytes))

    t0 = _time.time()
    with profiling.timer("sketch_pass"):
        sketches, n_local, sizes, chunk_labels = binning.sketch_chunks(
            factory, max_size=config.sketch_max_size, track_bytes=track)
        merged = merge_feature_sketches(sketches)
    sketch_s = _time.time() - t0
    del sketches
    f = len(merged)
    n_global = int(merged[0].total_cnt) if f else 0
    counts = [int(json.loads(p)) for p in
              exchange_host("prepart_chunk_rows", json.dumps(int(n_local)))]
    assert sum(counts) == n_global or f == 0, (counts, n_global)

    if chunk_labels is not None:
        if label is not None:
            log.fatal("labels were passed both to load_partitioned_chunks "
                      "and in the chunk stream; pass one or the other")
        label = chunk_labels
    ds = Dataset(None, label=label, weight=weight, init_score=init_score,
                 params=dict(params or {}), feature_name=feature_name,
                 categorical_feature=categorical_feature)
    names = ([f"Column_{i}" for i in range(f)]
             if feature_name in ("auto", None) else list(feature_name))
    ds._feature_names = names
    cats = ds._resolve_categorical(f, names)
    forced = _load_forced_bins(config, f, cats)
    mappers = binning.fit_mappers_from_sketches(merged, n_global, config,
                                                cats, forced_bounds=forced)
    ds.mappers = mappers
    ds.used_features = np.array(
        [j for j, m in enumerate(mappers) if not m.is_trivial], np.int32)
    ds.num_data = n_global
    ds.num_total_features = f
    ds.bundles = None
    ds._build_feature_meta(config)

    # second pass: bin each local chunk into its slot of the local shard
    # (host per-chunk bin_data: the shard crosses into the global array
    # as a host-local contribution, so the rows are needed host-side)
    used = [mappers[j] for j in ds.used_features]
    uf = ds.used_features
    dtype = np.uint8 if ds.max_num_bins <= 256 else np.int32
    local_bins = np.zeros((n_local, max(len(uf), 1)), dtype)
    t0 = _time.time()
    with profiling.timer("bin_pass"):
        # shared host bin-pass helper: ref-dropping iteration (<= the
        # current chunk + its f64 column copy resident) and a LOUD
        # failure when the source under-yields on re-iteration
        binning.bin_chunks_host(factory, used, uf, local_bins, track)
    bin_s = _time.time() - t0
    profiling.set_gauge("construct_sketch_s", sketch_s)
    profiling.set_gauge("construct_bin_s", bin_s)
    profiling.set_gauge("construct_peak_bytes", float(peak[0]))
    profiling.set_gauge("construct_rows", float(n_local))
    ds.construct_stats = {
        "sketch_pass": round(sketch_s, 6), "bin_pass": round(bin_s, 6),
        "peak_host_bytes": int(peak[0]), "rows": int(n_local),
    }
    _shard_local_bins(ds, local_bins, counts)
    g = ds.num_used_features()
    log.info(f"pre-partitioned streaming dataset: {n_local} local rows of "
             f"{n_global} global in {len(sizes)} chunks "
             f"(peak raw {peak[0]} bytes), {len(ds.used_features)} used "
             f"features across {g} columns")
    return ds
