"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data loader, parser and runtime in C++
(reference: src/io/parser.cpp, src/io/dataset_loader.cpp); the TPU build
keeps the same split — JAX/XLA for device compute, C++ for host-side IO —
with a build-on-first-use shared library (no pybind11 in this image; plain
C ABI + ctypes)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libtextparser.so")
_SRC = os.path.join(_HERE, "text_parser.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

FMT_NAMES = {0: "csv", 1: "tsv", 2: "libsvm"}


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _LIB_PATH]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0 and os.path.exists(_LIB_PATH)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            if not _build():
                _build_failed = True
                return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ltp_parse_file.restype = ctypes.c_void_p
        lib.ltp_parse_file.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int]
        lib.ltp_parse_buffer.restype = ctypes.c_void_p
        lib.ltp_parse_buffer.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                         ctypes.c_int, ctypes.c_int]
        lib.ltp_rows.restype = ctypes.c_int64
        lib.ltp_rows.argtypes = [ctypes.c_void_p]
        lib.ltp_cols.restype = ctypes.c_int64
        lib.ltp_cols.argtypes = [ctypes.c_void_p]
        lib.ltp_format.restype = ctypes.c_int
        lib.ltp_format.argtypes = [ctypes.c_void_p]
        lib.ltp_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.ltp_data.argtypes = [ctypes.c_void_p]
        lib.ltp_error.restype = ctypes.c_char_p
        lib.ltp_error.argtypes = [ctypes.c_void_p]
        lib.ltp_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def parse_text_file(path: str, has_header: bool = False,
                    num_threads: int = 0) -> Tuple[np.ndarray, str]:
    """Parse a CSV/TSV/LibSVM data file into a dense [rows, cols] float64
    matrix (column 0 is by convention the label for the reference's example
    files). Falls back to numpy parsing when the native build is
    unavailable. Returns (matrix, format_name)."""
    lib = _load()
    if lib is None:
        return _parse_text_file_py(path, has_header)
    handle = lib.ltp_parse_file(path.encode(), int(has_header), num_threads)
    if not handle:
        raise OSError(f"could not open data file: {path}")
    try:
        err = lib.ltp_error(handle).decode()
        if err:
            raise ValueError(f"parse error in {path}: {err}")
        rows, cols = lib.ltp_rows(handle), lib.ltp_cols(handle)
        fmt = FMT_NAMES.get(lib.ltp_format(handle), "csv")
        buf = np.ctypeslib.as_array(lib.ltp_data(handle),
                                    shape=(rows, cols)).copy()
        return buf, fmt
    finally:
        lib.ltp_free(handle)


def parse_buffer(data: bytes, has_header: bool = False,
                 num_threads: int = 0) -> Tuple[np.ndarray, str]:
    """Parse an in-memory text chunk (line-aligned) into a dense float64
    matrix — the streaming unit of two-round loading (cli.py). Falls back
    to numpy when the native build is unavailable."""
    lib = _load()
    if lib is None:
        import io
        text = data.decode()
        skip = 1 if has_header else 0
        first = text.split("\n", 1)[0]
        delim = "," if "," in first else None
        mat = np.loadtxt(io.StringIO(text), delimiter=delim, skiprows=skip,
                         ndmin=2)
        return mat, ("csv" if delim == "," else "tsv")
    handle = lib.ltp_parse_buffer(data, len(data), int(has_header),
                                  num_threads)
    if not handle:
        raise ValueError("could not parse data chunk")
    try:
        err = lib.ltp_error(handle).decode()
        if err:
            raise ValueError(f"parse error in chunk: {err}")
        rows, cols = lib.ltp_rows(handle), lib.ltp_cols(handle)
        fmt = FMT_NAMES.get(lib.ltp_format(handle), "csv")
        buf = np.ctypeslib.as_array(lib.ltp_data(handle),
                                    shape=(rows, cols)).copy()
        return buf, fmt
    finally:
        lib.ltp_free(handle)


def _parse_text_file_py(path: str, has_header: bool) -> Tuple[np.ndarray, str]:
    """Pure-python fallback (slow path)."""
    with open(path) as fh:
        first = fh.readline()
    skip = 1 if has_header else 0
    if ":" in first and any(c.isdigit() for c in first.split(":")[0][-3:]):
        from sklearn.datasets import load_svmlight_file
        X, y = load_svmlight_file(path)
        mat = np.concatenate([y.reshape(-1, 1), np.asarray(X.todense())], axis=1)
        return mat, "libsvm"
    delim = "," if "," in first else None
    mat = np.loadtxt(path, delimiter=delim, skiprows=skip, ndmin=2)
    return mat, ("csv" if delim == "," else "tsv")
