// Native text-data parser: the data-loader hot path.
//
// TPU-native equivalent of the reference's C++ parsing pipeline
// (reference: src/io/parser.cpp CSVParser/TSVParser/LibSVMParser with
// Parser::CreateParser format auto-detection, and the chunked reading of
// src/io/dataset_loader.cpp LoadTextDataToMemory). Design differences from
// the reference: we parse straight into a dense row-major double matrix
// (the TPU pipeline consumes a dense [N, F] block to bin on device), and we
// parallelize by splitting the mmap'd file into per-thread line-aligned
// chunks instead of a producer/consumer pipeline reader.
//
// Exposed as a tiny C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC -pthread \
//            text_parser.cpp -o libtextparser.so

#include <cctype>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Format { FMT_CSV = 0, FMT_TSV = 1, FMT_LIBSVM = 2 };

struct ParseResult {
  std::vector<double> data;  // row-major rows x cols
  int64_t rows = 0;
  int64_t cols = 0;
  int format = FMT_CSV;
  std::string error;
};

// fast double parse wrapper; strtod handles inf/nan/scientific
inline double ParseDouble(const char* p, char** end) {
  return std::strtod(p, end);
}

inline bool IsBlankLine(const char* p, const char* e) {
  while (p < e) {
    if (!std::isspace(static_cast<unsigned char>(*p))) return false;
    ++p;
  }
  return true;
}

// format auto-detection from a sample line
// (reference: parser.cpp DetermineDataFormat-equivalent sampling logic)
int DetectFormat(const char* line, const char* end) {
  bool has_colon = false, has_tab = false, has_comma = false;
  for (const char* p = line; p < end; ++p) {
    if (*p == ':') has_colon = true;
    else if (*p == '\t') has_tab = true;
    else if (*p == ',') has_comma = true;
  }
  if (has_colon) return FMT_LIBSVM;
  if (has_tab) return FMT_TSV;
  if (has_comma) return FMT_CSV;
  return FMT_TSV;  // whitespace-separated parses via the TSV tokenizer
}

// split the buffer into line ranges [begin, end) excluding the newline
void SplitLines(const char* buf, size_t len,
                std::vector<std::pair<const char*, const char*>>* lines) {
  const char* p = buf;
  const char* file_end = buf + len;
  while (p < file_end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', file_end - p));
    const char* e = nl ? nl : file_end;
    const char* trimmed = e;
    while (trimmed > p && (trimmed[-1] == '\r')) --trimmed;
    if (!IsBlankLine(p, trimmed)) lines->emplace_back(p, trimmed);
    p = nl ? nl + 1 : file_end;
  }
}

// number of delimited columns in one CSV/TSV line
int64_t CountColumns(const char* p, const char* e, char delim) {
  int64_t n = 1;
  for (; p < e; ++p)
    if (*p == delim) ++n;
  return n;
}

void ParseDelimitedRange(const std::vector<std::pair<const char*, const char*>>& lines,
                         size_t lo, size_t hi, char delim, int64_t cols,
                         double* out) {
  for (size_t i = lo; i < hi; ++i) {
    const char* p = lines[i].first;
    const char* e = lines[i].second;
    double* row = out + static_cast<int64_t>(i) * cols;
    int64_t c = 0;
    while (p <= e && c < cols) {
      if (p == e || *p == delim) {
        row[c++] = std::nan("");  // empty field -> NaN (reference: common.h Atof "")
        if (p == e) break;
        ++p;
        continue;
      }
      char* endp = nullptr;
      double v = ParseDouble(p, &endp);
      if (endp == p) {  // unparsable token (e.g. "na") -> NaN, skip token
        v = std::nan("");
        while (p < e && *p != delim) ++p;
      } else {
        p = endp;
        while (p < e && *p != delim) ++p;  // tolerate trailing spaces
      }
      row[c++] = v;
      if (p < e && *p == delim) ++p;
      else if (p >= e) break;
    }
    for (; c < cols; ++c) row[c] = std::nan("");
  }
}

// whitespace-separated variant (the reference's TSV parser also accepts
// single spaces; example files use tabs)
void ParseWhitespaceRange(const std::vector<std::pair<const char*, const char*>>& lines,
                          size_t lo, size_t hi, int64_t cols, double* out) {
  for (size_t i = lo; i < hi; ++i) {
    const char* p = lines[i].first;
    const char* e = lines[i].second;
    double* row = out + static_cast<int64_t>(i) * cols;
    int64_t c = 0;
    while (p < e && c < cols) {
      while (p < e && std::isspace(static_cast<unsigned char>(*p))) ++p;
      if (p >= e) break;
      char* endp = nullptr;
      double v = ParseDouble(p, &endp);
      if (endp == p) {
        v = std::nan("");
        while (p < e && !std::isspace(static_cast<unsigned char>(*p))) ++p;
      } else {
        p = endp;
      }
      row[c++] = v;
    }
    for (; c < cols; ++c) row[c] = std::nan("");
  }
}

// LibSVM: "label idx:val idx:val ..." with idx >= 0; absent entries are 0
// (reference: parser.cpp LibSVMParser; zeros match the reference's sparse
// semantics where missing pairs are zero, not NaN)
void ParseLibSVMRange(const std::vector<std::pair<const char*, const char*>>& lines,
                      size_t lo, size_t hi, int64_t cols, double* out) {
  for (size_t i = lo; i < hi; ++i) {
    const char* p = lines[i].first;
    const char* e = lines[i].second;
    double* row = out + static_cast<int64_t>(i) * cols;
    std::memset(row, 0, sizeof(double) * cols);
    char* endp = nullptr;
    row[0] = ParseDouble(p, &endp);  // label
    p = endp;
    while (p < e) {
      while (p < e && std::isspace(static_cast<unsigned char>(*p))) ++p;
      if (p >= e) break;
      long idx = std::strtol(p, &endp, 10);
      if (endp == p || *endp != ':') {  // qid:... or junk -> skip token
        while (p < e && !std::isspace(static_cast<unsigned char>(*p))) ++p;
        continue;
      }
      p = endp + 1;
      double v = ParseDouble(p, &endp);
      p = endp;
      if (idx >= 0 && idx + 1 < cols) row[idx + 1] = v;
    }
  }
}

int64_t MaxLibSVMIndex(const std::vector<std::pair<const char*, const char*>>& lines,
                       size_t lo, size_t hi) {
  int64_t mx = -1;
  for (size_t i = lo; i < hi; ++i) {
    const char* p = lines[i].first;
    const char* e = lines[i].second;
    while (p < e) {
      const char* colon = static_cast<const char*>(memchr(p, ':', e - p));
      if (!colon) break;
      const char* q = colon;
      while (q > p && std::isdigit(static_cast<unsigned char>(q[-1]))) --q;
      if (q < colon) {
        long idx = std::strtol(q, nullptr, 10);
        if (idx > mx) mx = idx;
      }
      p = colon + 1;
    }
  }
  return mx;
}

ParseResult* ParseBuffer(const char* buf, size_t len, int has_header,
                         int num_threads) {
  auto* res = new ParseResult();
  std::vector<std::pair<const char*, const char*>> lines;
  SplitLines(buf, len, &lines);
  if (has_header && !lines.empty()) lines.erase(lines.begin());
  if (lines.empty()) {
    res->error = "no data rows";
    return res;
  }
  res->format = DetectFormat(lines[0].first, lines[0].second);
  size_t n = lines.size();
  if (num_threads <= 0)
    num_threads = static_cast<int>(std::thread::hardware_concurrency());
  num_threads = std::max(1, std::min<int>(num_threads, 32));
  size_t chunk = (n + num_threads - 1) / num_threads;

  // column count
  int64_t cols;
  if (res->format == FMT_LIBSVM) {
    std::vector<int64_t> mx(num_threads, -1);
    std::vector<std::thread> th;
    for (int t = 0; t < num_threads; ++t) {
      size_t lo = t * chunk, hi = std::min(n, lo + chunk);
      if (lo >= hi) continue;
      th.emplace_back([&, t, lo, hi] { mx[t] = MaxLibSVMIndex(lines, lo, hi); });
    }
    for (auto& x : th) x.join();
    int64_t m = -1;
    for (auto v : mx) m = std::max(m, v);
    cols = m + 2;  // label + features 0..m
  } else {
    char delim = res->format == FMT_CSV ? ',' : '\t';
    bool has_delim =
        memchr(lines[0].first, delim, lines[0].second - lines[0].first) != nullptr;
    if (res->format == FMT_TSV && !has_delim) res->format = 3;  // whitespace
    if (res->format == 3) {
      // count whitespace-separated tokens on the first line
      const char* p = lines[0].first;
      const char* e = lines[0].second;
      cols = 0;
      while (p < e) {
        while (p < e && std::isspace(static_cast<unsigned char>(*p))) ++p;
        if (p >= e) break;
        ++cols;
        while (p < e && !std::isspace(static_cast<unsigned char>(*p))) ++p;
      }
    } else {
      cols = CountColumns(lines[0].first, lines[0].second, delim);
    }
  }
  res->rows = static_cast<int64_t>(n);
  res->cols = cols;
  res->data.resize(res->rows * cols);

  std::vector<std::thread> th;
  for (int t = 0; t < num_threads; ++t) {
    size_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) continue;
    th.emplace_back([&, lo, hi] {
      if (res->format == FMT_LIBSVM)
        ParseLibSVMRange(lines, lo, hi, cols, res->data.data());
      else if (res->format == 3)
        ParseWhitespaceRange(lines, lo, hi, cols, res->data.data());
      else
        ParseDelimitedRange(lines, lo, hi,
                            res->format == FMT_CSV ? ',' : '\t', cols,
                            res->data.data());
    });
  }
  for (auto& x : th) x.join();
  if (res->format == 3) res->format = FMT_TSV;
  return res;
}

}  // namespace

extern "C" {

// Parse a text file. Returns an opaque handle (nullptr on IO error).
void* ltp_parse_file(const char* path, int has_header, int num_threads) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  // +1 NUL terminator: strtod on the final token of a file without a
  // trailing newline must not read past the buffer
  std::vector<char> buf(static_cast<size_t>(size) + 1, '\0');
  size_t got = size > 0 ? std::fread(buf.data(), 1, size, f) : 0;
  std::fclose(f);
  return ParseBuffer(buf.data(), got, has_header, num_threads);
}

void* ltp_parse_buffer(const char* buf, int64_t len, int has_header,
                       int num_threads) {
  // copy into a NUL-terminated buffer: the caller's memory need not be
  // terminated and strtod can scan one past the last token
  std::vector<char> owned(buf, buf + static_cast<size_t>(len));
  owned.push_back('\0');
  return ParseBuffer(owned.data(), static_cast<size_t>(len), has_header,
                     num_threads);
}

int64_t ltp_rows(void* h) { return static_cast<ParseResult*>(h)->rows; }
int64_t ltp_cols(void* h) { return static_cast<ParseResult*>(h)->cols; }
int ltp_format(void* h) { return static_cast<ParseResult*>(h)->format; }
const char* ltp_error(void* h) {
  return static_cast<ParseResult*>(h)->error.c_str();
}
const double* ltp_data(void* h) {
  return static_cast<ParseResult*>(h)->data.data();
}
void ltp_free(void* h) { delete static_cast<ParseResult*>(h); }

}  // extern "C"
