"""Training entry points: train() and cv().

Mirrors the reference's Python engine (reference:
python-package/lightgbm/engine.py:14-470): parameter munging, the
callbacks-before/after-iteration protocol, early stopping via
``EarlyStopException`` (engine.py:244-272), and stratified/group-aware CV
folds (engine.py:281-470).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Dataset
from .booster import Booster
from .callback import CallbackEnv, EarlyStopException
from .config import PARAM_ALIASES
from .utils import log


def _resolve_num_boost_round(params: Dict[str, Any], num_boost_round: int) -> int:
    for alias, canonical in PARAM_ALIASES.items():
        if canonical == "num_iterations" and alias in params:
            return int(params.pop(alias))
    return int(params.pop("num_iterations", num_boost_round))


def train(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[dict] = None,
          verbose_eval="warn", learning_rates=None,
          keep_training_booster: bool = False, callbacks=None,
          resume_from: Optional[str] = None) -> Booster:
    """Train a booster (reference: engine.py:14-278).

    ``resume_from``: a checkpoint directory written by the
    ``callback.checkpoint`` callback — training restores the full trainer
    state (trees, score caches, RNG/drop state, eval history, early-stop
    counters) from the newest VALID checkpoint and continues at the saved
    iteration, reproducing the uninterrupted run bit-identically; when the
    directory holds no valid checkpoint, training starts from scratch with
    a warning. Pass the same params/datasets/callbacks as the original run
    (a params or dataset mismatch is rejected)."""
    params = copy.deepcopy(params)
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if fobj is not None:
        params["objective"] = "none"
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    first_metric_only = params.get("first_metric_only", False)

    # continued training (reference: engine.py:163-169 — the init model's
    # predictions seed the score caches, and its trees stay in the ensemble)
    loaded = None
    if init_model is not None:
        from .config import Config
        from .io.model_text import load_model
        if isinstance(init_model, Booster):
            loaded = load_model(init_model.model_to_string(),
                                Config.from_params(params))
        else:
            with open(init_model) as fh:
                loaded = load_model(fh.read(), Config.from_params(params))
        if loaded.num_trees > 0:
            if train_set.data is None:
                log.fatal("Cannot use init_model with a Dataset whose raw "
                          "data was freed")
            # pandas category columns must map through the SAME category ->
            # code lists as the init model, or the loaded trees' thresholds
            # silently misalign with the new Dataset's codes (reference:
            # basic.py train/predict pandas_categorical contract)
            pc = {int(k): list(v)
                  for k, v in (loaded.meta.get("pandas_categorical")
                               or {}).items()}
            if pc:
                if train_set._constructed:
                    if {int(k): list(v)
                            for k, v in train_set.pandas_categorical.items()} \
                            != pc:
                        log.fatal(
                            "train and init_model pandas categorical columns "
                            "do not match: construct the training Dataset "
                            "from data with the same category lists")
                else:
                    train_set.pandas_categorical = pc
            train_set.init_score = loaded.predict_raw(train_set.data)
            for vs in (valid_sets or []):
                if vs is train_set:
                    continue
                if vs.data is None:
                    log.fatal("Cannot use init_model with a validation "
                              "Dataset whose raw data was freed")
                vs.init_score = loaded.predict_raw(vs.data)

    # construct the training data BEFORE the booster so the phase is
    # attributable in the TIMETAG table (streaming construction nests its
    # sketch_pass / bin_pass / h2d_overlap sub-scopes under this),
    # replicating Booster.__init__'s exact pre-construct protocol: params
    # merge first (max_bin etc. in TRAIN params must reach binning), then
    # the multi-machine bootstrap. A pre-constructed (load_partitioned)
    # dataset no-ops through.
    if not train_set._constructed:
        from . import distributed
        from .config import Config
        from .utils import profiling
        merged = dict(train_set.params or {})
        merged.update(params)
        train_set.params = merged
        distributed.maybe_init_from_config(Config.from_params(params))
        with profiling.timer("construct"):
            train_set.construct()
    booster = Booster(params=params, train_set=train_set)
    if loaded is not None and loaded.num_trees > 0:
        booster._boosting.loaded = loaded
        booster._boosting.loaded_iters = loaded.num_iteration
    valid_sets = valid_sets or []
    valid_names = valid_names or []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            booster._boosting.config.is_provide_training_metric = True
            continue
        name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
        if vs.reference is None:
            vs.reference = train_set
        booster.add_valid(vs, name)

    cbs = set(callbacks or [])
    if verbose_eval is True or (isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool)):
        period = 1 if verbose_eval is True else verbose_eval
        cbs.add(callback_mod.print_evaluation(period))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        import jax
        if (getattr(train_set, "is_pre_partitioned", False)
                and jax.process_count() > 1):
            # metrics evaluate on each process's LOCAL partition (the
            # reference's per-machine metric semantics): local values
            # differ, so per-process stopping decisions would desync the
            # SPMD collectives and hang
            log.fatal("early_stopping_rounds is not supported with "
                      "multi-process pre-partitioned training: metrics "
                      "are per-process local, so stopping decisions would "
                      "diverge across processes")
        cbs.add(callback_mod.early_stopping(early_stopping_rounds, first_metric_only))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))

    cbs_before = sorted((c for c in cbs if getattr(c, "before_iteration", False)),
                        key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted((c for c in cbs if not getattr(c, "before_iteration", False)),
                       key=lambda c: getattr(c, "order", 0))
    # the checkpoint callback captures stateful-callback state through the
    # booster (checkpoint.capture_state reads booster._callbacks)
    booster._callbacks = cbs_before + cbs_after

    start_iter = 0
    if resume_from is not None:
        # pre-partitioned runs resume from SHARDED checkpoints: each rank
        # reassembles its process-local score caches from the shard files
        # under the current partition (checkpoint.restore_booster), so the
        # gang may even come back at a different world size; a legacy
        # rank-0-only checkpoint is rejected there with a clear error.
        from . import checkpoint as checkpoint_mod
        ckpt = checkpoint_mod.CheckpointManager(resume_from).load_latest_valid()
        if ckpt is None:
            log.warning(f"resume_from={resume_from!r}: no valid checkpoint "
                        f"found; training from scratch")
        else:
            cb_states = checkpoint_mod.restore_booster(booster, ckpt)
            start_iter = int(ckpt.state["boosting"]["iter"])
            for cb in booster._callbacks:
                key = getattr(cb, "ckpt_key", None)
                if key in cb_states and hasattr(cb, "set_state"):
                    cb.set_state(cb_states[key])
            log.info(f"resumed from checkpoint {ckpt.path} at iteration "
                     f"{start_iter}")
            from . import compile_cache
            if getattr(booster.config, "compile_warmup", True) \
                    and compile_cache.configure(booster.config):
                # AOT-warm the training programs NOW, before the loop:
                # with the persistent compilation cache a restarted
                # incarnation deserializes the fused step from disk here
                # and reaches its first iteration with zero XLA compiles.
                # ONLY with a cache configured — jax's AOT compile does
                # not feed the jit call cache, so a cacheless warmup
                # would be a pure duplicate compile
                booster._boosting.warm_start()

    from . import distributed
    from .utils import faults
    fault_plan = faults.plan_from(booster.config)
    # training supervision: heartbeat (multi-process liveness) and the
    # collective_deadline watchdog — a dead/hung peer must surface as a
    # diagnosable DistributedTimeoutError (or a supervised gang restart),
    # never an indefinite collective stall
    health = distributed.start_health(booster.config)
    # cross-rank divergence detection (the training-integrity layer): every
    # integrity_check_period iterations the ranks exchange a model-state
    # fingerprint and majority-vote mismatches — run BEFORE the after-
    # iteration callbacks so a checkpoint is never written from state the
    # gang has already voted corrupt. No-op single-process / when 0.
    import jax
    integ_period = int(getattr(booster.config, "integrity_check_period", 0)
                       or 0)
    integ_on = integ_period > 0 and jax.process_count() > 1
    boosting = booster._boosting
    # --- K-iterations-per-dispatch handshake (boost_rounds_per_dispatch):
    # only THIS loop may let one update() consume a whole K-block (it
    # advances its round counter by the consumed count below); a manual
    # Booster.update loop or cv() never opts in and keeps per-iteration
    # semantics. Callbacks/eval run at block boundaries, so:
    #   - a checkpoint callback period must be a multiple of K (a
    #     mid-block checkpoint cannot exist — the block is one atomic
    #     dispatch — so misaligned periods are REJECTED, loudly);
    #   - per-iteration parameter schedules (reset_parameter /
    #     learning_rates) disable blocking for the run — their values
    #     must apply per iteration, not per block.
    k_block = max(1, int(getattr(booster.config,
                                 "boost_rounds_per_dispatch", 1)))
    if k_block > 1 and hasattr(boosting, "_block_rounds"):
        # the schedule fallback is decided FIRST: with blocking disabled
        # the run is per-iteration, where any checkpoint period is valid
        # — rejecting it would refuse a run that executes fine
        if any(getattr(cb, "is_reset_parameter", False)
               for cb in cbs_before):
            log.info(f"boost_rounds_per_dispatch={k_block} disabled for "
                     f"this run: a reset_parameter/learning_rates "
                     f"callback applies per-iteration values the block "
                     f"dispatch cannot honor")
            boosting._block_disable = True
        else:
            for cb in (cbs_before + cbs_after):
                p = getattr(cb, "ckpt_period", None)
                if p and p > 0 and p % k_block != 0:
                    log.fatal(
                        f"checkpoint period {p} is not a multiple of "
                        f"boost_rounds_per_dispatch={k_block}: a "
                        f"K-iteration block is one atomic dispatch, so a "
                        f"mid-block checkpoint cannot be captured. Use a "
                        f"period that is a multiple of {k_block}, or set "
                        f"boost_rounds_per_dispatch=1.")
        boosting._block_target = num_boost_round
    try:
        i = start_iter
        while i < num_boost_round:
            faults.maybe_kill(fault_plan, i)
            faults.maybe_hang(fault_plan, i)
            for cb in cbs_before:
                cb(CallbackEnv(model=booster, params=params, iteration=i,
                               begin_iteration=0, end_iteration=num_boost_round,
                               evaluation_result_list=None))
            it_before = boosting.iter
            booster.update(fobj=fobj)
            # a K-block consumes several iterations in one update() —
            # advance by what actually happened (1 everywhere else)
            consumed = max(1, boosting.iter - it_before)
            i += consumed
            # fire whenever a period boundary was CROSSED in the consumed
            # span, not only when i lands exactly on one — today blocks
            # cannot engage multi-process (fused requires one process),
            # but this keeps the divergence-check frequency exact if that
            # ever changes
            if integ_on and (i // integ_period) > \
                    ((i - consumed) // integ_period):
                distributed.check_model_integrity(boosting, i - 1)

            evaluation_result_list = []
            if valid_sets or boosting.config.is_provide_training_metric:
                evaluation_result_list = booster.eval_set(feval)
            try:
                for cb in cbs_after:
                    cb(CallbackEnv(model=booster, params=params,
                                   iteration=i - 1,
                                   begin_iteration=0, end_iteration=num_boost_round,
                                   evaluation_result_list=evaluation_result_list))
            except EarlyStopException as es:
                booster.best_iteration = es.best_iteration + 1
                for item in es.best_score:
                    booster.best_score.setdefault(item[0], {})[item[1]] = item[2]
                break
        # judge every still-deferred numerics sentinel (the fused path's
        # flag words are fetched lazily; without this flush a NaN born in
        # the final rounds could go unreported)
        boosting._flush_sentinel()
    except BaseException as e:
        # a dying run flushes its flight recorder (telemetry.py): the
        # per-iteration ring + this reason are the post-mortem — the NaN
        # sentinel verdict, watchdog diagnosis or OOM ladder history is
        # on disk before the exception unwinds. THIS booster's recorder,
        # not the module slot: in multi-booster processes (cv folds) the
        # module slot holds the last-configured booster's ring.
        if hasattr(boosting, "_flush_flight"):
            boosting._flush_flight(
                f"train-error: {type(e).__name__}: {str(e)[:300]}")
        raise
    finally:
        boosting._block_target = None
        health.stop()
    # clean end: flush only when a durable telemetry dir was configured
    # (telemetry_dir / supervised diag dir / checkpoint_path) — ordinary
    # runs must not litter temp dirs with post-mortems nobody asked for
    fr = getattr(boosting, "_flight", None)
    if fr is not None and fr.directory:
        fr.flush("train-end")
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference: engine.py:281-317)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict[str, Any],
                  seed: int, stratified: bool, shuffle: bool):
    """reference: engine.py:319-376 _make_n_folds."""
    full_data.construct()
    num_data = full_data.num_data
    if folds is not None:
        if not hasattr(folds, "__iter__") and hasattr(folds, "split"):
            group = full_data.get_group()
            if group is not None:
                group_idx = np.repeat(np.arange(len(group)), group)
                folds = folds.split(X=np.empty(num_data), groups=group_idx)
            else:
                folds = folds.split(X=np.empty(num_data))
        return list(folds)
    rng = np.random.RandomState(seed)
    label = full_data.get_label()
    if stratified:
        # stratified fold assignment by label
        idx = np.arange(num_data)
        assignment = np.zeros(num_data, dtype=np.int64)
        for lv in np.unique(label):
            sel = idx[label == lv]
            if shuffle:
                rng.shuffle(sel)
            assignment[sel] = np.arange(len(sel)) % nfold
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        assignment = np.zeros(num_data, dtype=np.int64)
        assignment[idx] = np.arange(num_data) % nfold
    out = []
    for f in range(nfold):
        test_idx = np.nonzero(assignment == f)[0]
        train_idx = np.nonzero(assignment != f)[0]
        out.append((train_idx, test_idx))
    return out


def _agg_cv_result(raw_results):
    """reference: engine.py:378-390."""
    cvmap = {}
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = f"{one_line[0]} {one_line[1]}"
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, []).append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k], float(np.std(v)))
            for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv: bool = True, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """Cross-validation (reference: engine.py:392-470)."""
    params = copy.deepcopy(params)
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics
    if params.get("objective") in ("binary",) or str(params.get("objective", "")).startswith("multiclass"):
        pass
    else:
        stratified = False

    folds = _make_n_folds(train_set, folds, nfold, params, seed, stratified, shuffle)
    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, params.copy())
        fold_data.append((tr, te))

    results: Dict[str, List[float]] = {}
    boosters = []
    for tr, te in fold_data:
        b = Booster(params=params, train_set=tr)
        b.add_valid(te, "valid")
        boosters.append(b)
        cvbooster._append(b)

    cbs = set(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds, verbose=False))
    if verbose_eval:
        period = 1 if verbose_eval is True else int(verbose_eval)
        cbs.add(callback_mod.print_evaluation(period, show_stdv))
    cbs_after = sorted((c for c in cbs if not getattr(c, "before_iteration", False)),
                       key=lambda c: getattr(c, "order", 0))

    for i in range(num_boost_round):
        raw = []
        for b in boosters:
            b.update(fobj=fobj)
            if eval_train_metric:
                raw.append(b.eval_set(feval))
            else:
                raw.append(b.eval_valid(feval))
        agg = _agg_cv_result(raw)
        for _, key, mean, _, std in agg:
            results.setdefault(f"{key}-mean", []).append(mean)
            results.setdefault(f"{key}-stdv", []).append(std)
        try:
            for cb in cbs_after:
                cb(CallbackEnv(model=cvbooster, params=params, iteration=i,
                               begin_iteration=0, end_iteration=num_boost_round,
                               evaluation_result_list=agg))
        except EarlyStopException as es:
            cvbooster.best_iteration = es.best_iteration + 1
            for k in list(results.keys()):
                results[k] = results[k][:cvbooster.best_iteration]
            break
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return results
