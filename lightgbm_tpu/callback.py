"""Training callbacks (reference: python-package/lightgbm/callback.py).

Implements the reference's callback protocol: each callback receives a
``CallbackEnv`` tuple before/after every iteration; ``early_stopping`` raises
``EarlyStopException`` (reference: callback.py:146-241, engine.py:244-272).
"""

from __future__ import annotations

import collections
from typing import Callable, List

from .utils import log

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    """reference: callback.py:14-24."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """reference: callback.py:52-73."""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            log.info(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


log_evaluation = print_evaluation


def record_evaluation(eval_result: dict) -> Callable:
    """reference: callback.py:75-104."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)

    def _get_state():
        return {d: {m: list(v) for m, v in metrics.items()}
                for d, metrics in eval_result.items()}

    def _set_state(state):
        eval_result.clear()
        for d, metrics in state.items():
            eval_result[d] = collections.OrderedDict(
                (m, list(v)) for m, v in metrics.items())
    _callback.order = 20
    _callback.ckpt_key = "record_evaluation"
    _callback.get_state = _get_state
    _callback.set_state = _set_state
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedules (reference: callback.py:106-144).
    Values may be lists (indexed by iteration) or callables iteration->value."""

    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key!r} has to equal to 'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    # per-iteration schedules and boost_rounds_per_dispatch K-blocks are
    # incompatible (a block dispatch bakes ONE value for K iterations):
    # engine.train reads this flag and falls back to K=1 for the run
    _callback.is_reset_parameter = True
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """reference: callback.py:146-241."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(env.params.get(alias, "") == "dart"
                             for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and eval metric"
                             " is required for evaluation")
        if verbose:
            log.info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1]
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # bigger is better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _final_iteration_check(env, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if verbose:
                log.info("Did not meet early stopping. Best iteration is:\n"
                         f"[{best_iter[i] + 1}]\t"
                         + "\t".join(_format_eval_result(x) for x in best_score_list[i]))
            raise EarlyStopException(best_iter[i], best_score_list[i])

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if not enabled[0]:
            return
        for i in range(len(env.evaluation_result_list)):
            score = env.evaluation_result_list[i][2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                # the whole result list at the best iteration (callback.py:200)
                best_score_list[i] = env.evaluation_result_list
            eval_name = env.evaluation_result_list[i][1]
            if first_metric_only and first_metric[0] != eval_name:
                continue
            if env.evaluation_result_list[i][0] == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info("Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]\t"
                             + "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            _final_iteration_check(env, eval_name, i)

    def _get_state():
        # cmp_op closures can't pickle: persist the bigger-is-better flags
        # and rebuild the comparators on restore
        return {"best_score": list(best_score), "best_iter": list(best_iter),
                "best_score_list": list(best_score_list),
                "bigger": [op(1.0, 0.0) for op in cmp_op],
                "enabled": enabled[0], "first_metric": first_metric[0]}

    def _set_state(state):
        del best_score[:], best_iter[:], best_score_list[:], cmp_op[:]
        best_score.extend(state["best_score"])
        best_iter.extend(state["best_iter"])
        best_score_list.extend(state["best_score_list"])
        for bigger in state["bigger"]:
            cmp_op.append((lambda x, y: x > y) if bigger
                          else (lambda x, y: x < y))
        enabled[0] = state["enabled"]
        first_metric[0] = state["first_metric"]
    _callback.order = 30
    _callback.ckpt_key = "early_stopping"
    _callback.get_state = _get_state
    _callback.set_state = _set_state
    return _callback


def checkpoint(directory: str, period: int = 1, keep: int = 2) -> Callable:
    """Atomic training checkpoints every ``period`` iterations (see
    lightgbm_tpu/checkpoint.py for the layout and guarantees). Resume with
    ``train(..., resume_from=directory)`` — kill-at-k + resume reproduces
    the uninterrupted run bit-identically. ``keep`` >= 2 retains a
    fallback when the newest checkpoint is later found truncated/corrupt.

    Runs at order 40 — after ``record_evaluation`` (20) and
    ``early_stopping`` (30) — so the callback states it captures are
    current through the checkpointed iteration."""
    from .checkpoint import CheckpointManager
    state = {"mgr": None, "warned": False}

    def _callback(env: CallbackEnv) -> None:
        model = env.model
        boosting = getattr(model, "_boosting", None)
        if boosting is None or not hasattr(boosting, "get_trainer_state"):
            if not state["warned"]:
                state["warned"] = True
                log.warning("checkpoint callback: model does not support "
                            "trainer-state capture (cv / loaded boosters "
                            "are not checkpointable); skipping")
            return
        if period <= 0 or (env.iteration + 1) % period != 0:
            return
        if state["mgr"] is None:
            state["mgr"] = CheckpointManager(directory, keep=keep,
                                             config=model.config)
        # with divergence detection armed, a checkpoint written BETWEEN
        # votes could capture corruption born since the last vote — the
        # restore would then reload it and burn the rank's restart budget
        # on a checkpoint the gang never certified. Vote before capturing
        # state, so every published checkpoint is voted-clean (skipped
        # when engine.train already voted this very iteration; the guard
        # and the config are rank-symmetric, so the exchange stays in
        # lockstep).
        integ = int(getattr(boosting.config, "integrity_check_period", 0)
                    or 0)
        if integ > 0 \
                and getattr(boosting, "_integrity_checked_iter", None) \
                != env.iteration:
            from . import distributed
            distributed.check_model_integrity(boosting, env.iteration)
        state["mgr"].save(model, env.iteration + 1)
    _callback.order = 40
    # engine.train validates this against boost_rounds_per_dispatch: a
    # period that is not a multiple of K can never fire at a block
    # boundary and is rejected up front
    _callback.ckpt_period = period
    return _callback
