"""Evaluation metrics.

Analog of the reference metric layer (reference: src/metric/*.hpp, abstract
interface include/LightGBM/metric.h:24-44). Each metric exposes
``name``, ``bigger_is_better`` and ``eval(raw_score, objective) -> float``.
Like the reference, metrics receive RAW scores and apply the objective's
``ConvertOutput`` where the reference does (e.g. regression metrics convert
Poisson/Gamma/Tweedie log-scores, regression_metric.hpp:60-75; binary logloss
uses the sigmoid via the objective).

Implementations are host-side numpy (metrics run once per iteration on small
outputs); the AUC sorted-scan mirrors binary_metric.hpp:159-268.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .config import Config
from .utils import log


class Metric:
    name = "base"
    bigger_is_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, label: np.ndarray, weight: Optional[np.ndarray],
             groups: Optional[np.ndarray] = None) -> None:
        self.label = np.asarray(label, dtype=np.float64)
        self.weight = np.asarray(weight, dtype=np.float64) if weight is not None else None
        self.sum_weight = (float(np.sum(self.weight)) if self.weight is not None
                           else float(len(self.label)))
        self.groups = groups

    def _wavg(self, values: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(values * self.weight) / self.sum_weight)
        return float(np.mean(values))

    def _convert(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None:
            import jax.numpy as jnp
            return np.asarray(objective.convert_output(jnp.asarray(score)))
        return score

    def eval(self, score: np.ndarray, objective=None) -> float:
        raise NotImplementedError


# ----------------------------------------------------------- regression
class L2Metric(Metric):
    """reference: regression_metric.hpp (L2Metric: average squared loss)."""
    name = "l2"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        return self._wavg((score - self.label) ** 2)


class RMSEMetric(L2Metric):
    name = "rmse"

    def eval(self, score, objective=None):
        return float(np.sqrt(super().eval(score, objective)))


class L1Metric(Metric):
    name = "l1"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        return self._wavg(np.abs(score - self.label))


class QuantileMetric(Metric):
    """reference: regression_metric.hpp QuantileMetric."""
    name = "quantile"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        alpha = self.config.alpha
        delta = self.label - score
        loss = np.where(delta < 0, (alpha - 1.0) * delta, alpha * delta)
        return self._wavg(loss)


class HuberMetric(Metric):
    name = "huber"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        a = self.config.alpha
        d = np.abs(score - self.label)
        loss = np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
        return self._wavg(loss)


class FairMetric(Metric):
    name = "fair"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        c = self.config.fair_c
        x = np.abs(score - self.label)
        loss = c * x - c * c * np.log1p(x / c)
        return self._wavg(loss)


class PoissonMetric(Metric):
    """reference: regression_metric.hpp PoissonMetric: score is the mean
    (converted); loss = score - label*log(score)."""
    name = "poisson"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        eps = 1e-10
        return self._wavg(score - self.label * np.log(np.maximum(score, eps)))


class MAPEMetric(Metric):
    name = "mape"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        return self._wavg(np.abs((self.label - score) / np.maximum(1.0, np.abs(self.label))))


class GammaMetric(Metric):
    """reference: regression_metric.hpp GammaMetric (negative log-likelihood)."""
    name = "gamma"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        eps = 1e-10
        psi = 1.0
        theta = -1.0 / np.maximum(score, eps)
        a = psi
        b = -np.log(-theta)
        c = (1.0 / psi * np.log(self.label / psi)
             - np.log(self.label) - 0.0)  # lgamma(1/psi)=0 for psi=1
        return self._wavg(-((self.label * theta - b) / a + c))


class GammaDevianceMetric(Metric):
    """reference: regression_metric.hpp GammaDevianceMetric."""
    name = "gamma_deviance"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        # reference: LossOnPoint = tmp - log(tmp) - 1 per row, but the
        # AverageLoss override (regression_metric.hpp:291-293) returns
        # sum_loss * 2 and IGNORES sum_weights — i.e. 2x the weighted SUM,
        # not a mean.
        frac = self.label / (score + 1e-9)
        loss = -np.log(np.maximum(frac, 1e-300)) + frac - 1.0
        if self.weight is not None:
            loss = loss * self.weight
        return 2.0 * float(np.sum(loss))


class TweedieMetric(Metric):
    name = "tweedie"

    def eval(self, score, objective=None):
        score = self._convert(score, objective)
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(score, eps)
        a = self.label * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return self._wavg(-a + b)


# --------------------------------------------------------------- binary
class BinaryLoglossMetric(Metric):
    """reference: binary_metric.hpp BinaryLoglossMetric."""
    name = "binary_logloss"

    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        eps = 1e-15
        prob = np.clip(prob, eps, 1.0 - eps)
        y = (self.label > 0).astype(np.float64)
        return self._wavg(-(y * np.log(prob) + (1 - y) * np.log(1 - prob)))


class BinaryErrorMetric(Metric):
    """reference: binary_metric.hpp BinaryErrorMetric."""
    name = "binary_error"

    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        y = (self.label > 0).astype(np.float64)
        pred = (prob > 0.5).astype(np.float64)
        return self._wavg((pred != y).astype(np.float64))


class AUCMetric(Metric):
    """Weighted AUC via descending-score sweep
    (reference: binary_metric.hpp:159-268 AUCMetric)."""
    name = "auc"
    bigger_is_better = True

    def eval(self, score, objective=None):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(-score, kind="stable")
        ys, ws = y[order], w[order]
        # group ties by score value
        ss = score[order]
        boundary = np.concatenate([[True], ss[1:] != ss[:-1]])
        grp = np.cumsum(boundary) - 1
        npos_g = np.bincount(grp, weights=ys * ws)
        ntot_g = np.bincount(grp, weights=ws)
        nneg_g = ntot_g - npos_g
        total_pos = np.sum(ys * ws)
        total_neg = np.sum(ws) - total_pos
        # positives pair with negatives ranked strictly below (later groups in
        # the descending sweep) plus half of the tied group
        cum_neg_incl = np.cumsum(nneg_g)
        neg_below = total_neg - cum_neg_incl
        auc_sum = np.sum(npos_g * (neg_below + nneg_g * 0.5))
        if total_pos <= 0 or total_neg <= 0:
            return 1.0
        return float(auc_sum / (total_pos * total_neg))


class AveragePrecisionMetric(Metric):
    """reference: binary_metric.hpp:270+ AveragePrecisionMetric."""
    name = "average_precision"
    bigger_is_better = True

    def eval(self, score, objective=None):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(-score, kind="stable")
        ys, ws, ss = y[order], w[order], np.asarray(score)[order]
        # tied scores form ONE threshold group whose precision is taken
        # AFTER including the whole group (binary_metric.hpp:270+ sweep)
        boundary = np.concatenate([[True], ss[1:] != ss[:-1]])
        grp = np.cumsum(boundary) - 1
        pos_g = np.bincount(grp, weights=ys * ws)
        tot_g = np.bincount(grp, weights=ws)
        cum_pos = np.cumsum(pos_g)
        cum_tot = np.cumsum(tot_g)
        total_pos = cum_pos[-1]
        if total_pos <= 0 or total_pos == np.sum(ws):
            return 1.0
        accum = float(np.sum(pos_g * (cum_pos / cum_tot)))
        return accum / float(total_pos)


# ------------------------------------------------------------ multiclass
class MultiLoglossMetric(Metric):
    """reference: multiclass_metric.hpp MultiSoftmaxLoglossMetric."""
    name = "multi_logloss"

    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        eps = 1e-15
        yi = self.label.astype(np.int64)
        p = np.clip(prob[np.arange(len(yi)), yi], eps, 1.0)
        return self._wavg(-np.log(p))


class MultiErrorMetric(Metric):
    """reference: multiclass_metric.hpp MultiErrorMetric (top-k)."""
    name = "multi_error"

    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        yi = self.label.astype(np.int64)
        k = self.config.multi_error_top_k
        true_p = prob[np.arange(len(yi)), yi][:, None]
        # error when the true class's prob is not among the top-k
        # (reference counts ties in favor of correctness)
        rank = np.sum(prob > true_p, axis=1)
        return self._wavg((rank >= k).astype(np.float64))


class AucMuMetric(Metric):
    """reference: multiclass_metric.hpp:138-183 auc_mu (pairwise class AUC
    averaged over class pairs)."""
    name = "auc_mu"
    bigger_is_better = True

    def eval(self, score, objective=None):
        # the reference ranks by RAW score distances from the separating
        # hyperplane (multiclass_metric.hpp:238-266) — no softmax; with
        # auc_mu_weights the decision value is (W_i - W_j) . score
        s_raw = np.asarray(score)
        yi = self.label.astype(np.int64)
        k = s_raw.shape[1]
        w = self.weight if self.weight is not None else np.ones(len(yi))
        amw = list(self.config.auc_mu_weights or [])
        if amw:
            if len(amw) != k * k:
                log.fatal(f"auc_mu_weights must have {k * k} elements")
            W = np.asarray(amw, np.float64).reshape(k, k)
        else:
            W = 1.0 - np.eye(k)
        aucs = []
        for a in range(k):
            for b in range(a + 1, k):
                mask = (yi == a) | (yi == b)
                if not mask.any():
                    continue
                curr_v = W[a] - W[b]
                t1 = curr_v[a] - curr_v[b]
                d = t1 * (s_raw[mask] @ curr_v)
                sub = AUCMetric(self.config)
                sub.init((yi[mask] == a).astype(np.float64), w[mask])
                aucs.append(sub.eval(d, None))
        return float(np.mean(aucs)) if aucs else 1.0


# ---------------------------------------------------------- cross-entropy
class CrossEntropyMetric(Metric):
    """reference: xentropy_metric.hpp CrossEntropyMetric."""
    name = "cross_entropy"

    def eval(self, score, objective=None):
        p = self._convert(score, objective)
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        y = self.label
        return self._wavg(-(y * np.log(p) + (1 - y) * np.log(1 - p)))


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        hhat = np.log1p(np.exp(score))  # converted output
        eps = 1e-15
        p = np.clip(1.0 - np.exp(-hhat), eps, 1 - eps)
        y = self.label
        return self._wavg(-(y * np.log(p) + (1 - y) * np.log(1 - p)))


class KLDivMetric(Metric):
    """reference: xentropy_metric.hpp KullbackLeiblerDivergence."""
    name = "kullback_leibler"

    def eval(self, score, objective=None):
        p = self._convert(score, objective)
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        y = np.clip(self.label, eps, 1 - eps)
        ce = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        ent = -(y * np.log(y) + (1 - y) * np.log(1 - y))
        return self._wavg(ce - ent)


_REGISTRY = {}
for _cls in [L2Metric, RMSEMetric, L1Metric, QuantileMetric, HuberMetric,
             FairMetric, PoissonMetric, MAPEMetric, GammaMetric,
             GammaDevianceMetric, TweedieMetric, BinaryLoglossMetric,
             BinaryErrorMetric, AUCMetric, AveragePrecisionMetric,
             MultiLoglossMetric, MultiErrorMetric, AucMuMetric,
             CrossEntropyMetric, CrossEntropyLambdaMetric, KLDivMetric]:
    _REGISTRY[_cls.name] = _cls


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """reference: src/metric/metric.cpp Metric::CreateMetric."""
    if name in ("ndcg", "map"):
        from .ranking import create_ranking_metric
        return create_ranking_metric(name, config)
    if name in _REGISTRY:
        return _REGISTRY[name](config)
    log.warning(f"Unknown metric: {name}")
    return None


def default_metric_for_objective(objective: str) -> List[str]:
    """Objective -> default metric (reference: config.cpp GetMetricType)."""
    mapping = {
        "regression": ["l2"], "regression_l1": ["l1"], "huber": ["huber"],
        "fair": ["fair"], "poisson": ["poisson"], "quantile": ["quantile"],
        "mape": ["mape"], "gamma": ["gamma"], "tweedie": ["tweedie"],
        "binary": ["binary_logloss"],
        "multiclass": ["multi_logloss"], "multiclassova": ["multi_logloss"],
        "cross_entropy": ["cross_entropy"],
        "cross_entropy_lambda": ["cross_entropy_lambda"],
        "lambdarank": ["ndcg"], "rank_xendcg": ["ndcg"],
    }
    return mapping.get(objective, [])
