"""Pallas TPU kernel for the histogram tile pass.

The fused re-design of the CUDA histogram kernels (reference:
src/treelearner/kernels/histogram_16_64_256.cu:16-120 — per-workgroup
shared-memory sub-histograms with atomic adds). On TPU there are no atomics;
instead each grid step builds the per-feature bin one-hot IN VMEM and
contracts it with the (leaf-slot x stat) channel matrix on the MXU,
accumulating into a VMEM-resident [F*B, P*S] output that is flushed once.

Why a kernel at all: the XLA formulation (histogram.py "onehot") must
materialize the ``[C, F*B]`` one-hot in HBM — ~300 GB of traffic per full
pass at Higgs scale, which bounds the pass at ~370-450 ms. Fused, the
one-hot never leaves VMEM and the pass is bounded by the bin-compare VPU
work (~75 G ops) plus the matmuls.

Two precision modes share one kernel body (``hilo`` flag):

- hilo=True (the fast default): the rhs carries [hi || lo] bf16 halves of
  the f32 channels; both halves' products accumulate in f32 on the MXU, so
  the recombined sum carries ~16-17 mantissa bits of input precision
  (~2^-17 relative rounding) with exact counts — comparable to (slightly
  coarser than) the reference GPU's float32 histograms (gpu_use_dp=false,
  docs/GPU-Performance.rst:133-140), at 2 bf16 MXU passes.
- hilo=False: f32 rhs contracted at Precision.HIGHEST (6 bf16 passes) —
  the precise alternative.

The leaf-channel RHS (leaf one-hot x stats, P*S columns padded to the
128-lane boundary) is prepared by XLA — it is small (~2% of the one-hot's
traffic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_PAD = 128          # lane width; P*S channels are padded up to this


def _hist_kernel(binsT_ref, rhs_ref, out_ref, *, f, b, c, mode):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rhs = rhs_ref[...]     # [C, 2*PAD] bf16 | [C, PAD] f32 | [C, PAD] int8
    binsT = binsT_ref[...]                               # [F, C] int8
    oh_dtype = {"hilo": jnp.bfloat16, "highest": jnp.float32,
                "q8": jnp.int8}[mode]
    acc_dtype = jnp.int32 if mode == "q8" else jnp.float32
    prec = jax.lax.Precision.HIGHEST if mode == "highest" else None
    # Feature packing: with b <= 64 bins a single feature's one-hot fills
    # only b of the MXU's 128 output rows, so the matmul runs at b/128
    # utilization. Pack g = 128//b features side by side into one
    # [C, g*b] one-hot (disjoint lane ranges, so a plain sum builds the
    # OR) — the max_bin=63 configuration then drives full 128-row MXU
    # tiles instead of half-empty ones.
    g = max(1, _PAD // b) if b <= _PAD else 1
    for j0 in range(0, f, g):                            # static unroll
        m = min(g, f - j0)
        iota = jax.lax.broadcasted_iota(jnp.int32, (c, m * b), 1)
        oh = None
        for k in range(m):
            col = binsT[j0 + k, :].astype(jnp.int32) + k * b   # [C]
            hit = (col[:, None] == iota).astype(oh_dtype)      # [C, m*B]
            oh = hit if oh is None else oh + hit
        acc = jax.lax.dot_general(
            oh, rhs, (((0,), (0,)), ((), ())), precision=prec,
            preferred_element_type=acc_dtype)
        if mode == "hilo":
            acc = acc[:, :_PAD] + acc[:, _PAD:]          # recombine halves
        out_ref[j0 * b:(j0 + m) * b, :] += acc


@functools.partial(jax.jit, static_argnames=("num_bins", "block", "mode"))
def _hist_pallas_call(binsT, rhs, *, num_bins, block, mode):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    f, n = binsT.shape
    c = block
    nblk = n // c
    w = 2 * _PAD if mode == "hilo" else _PAD
    out_dtype = jnp.int32 if mode == "q8" else jnp.float32
    kernel = functools.partial(_hist_kernel, f=f, b=num_bins, c=c, mode=mode)
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((f, c), lambda i: (0, i)),
            pl.BlockSpec((c, w), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f * num_bins, _PAD), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((f * num_bins, _PAD), out_dtype),
        # CompilerParams was TPUCompilerParams before jax 0.5
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("arbitrary",),
            # the default 16M scoped-vmem cap rejects the q8 mode at full
            # Higgs scale (measured 2026-07-30: int8 accumulation needed a
            # 28.31M stack allocation at block=2048, F=28, B=255); the
            # kernel's working set is still far below the 128M physical
            # VMEM, so raise the cap rather than shrink the block
            vmem_limit_bytes=100 * 1024 * 1024),
    )(binsT, rhs)


def _prep_rhs(binsT, stats, leaf_ids, sel, block, q8=False):
    """Shared prep: pad rows to the block size and build the leaf-onehot x
    stat channel matrix [N, _PAD] (f32, or int8 for the q8 mode)."""
    f, n = binsT.shape
    p = sel.shape[0]
    s = stats.shape[1]
    assert p * s <= _PAD, (p, s)
    c = min(block, max(512, -(-n // 512) * 512))
    pad = -n % c
    if pad:
        binsT = jnp.pad(binsT, ((0, 0), (0, pad)))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))
        leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
    lo = leaf_ids[:, None] == sel[None, :]                         # [N, P]
    if q8:
        rhs = jnp.where(lo[:, :, None], stats[:, None, :],
                        jnp.int8(0)).reshape(-1, p * s)
    else:
        rhs = (lo.astype(jnp.float32)[:, :, None]
               * stats.astype(jnp.float32)[:, None, :]).reshape(-1, p * s)
    rhs = jnp.pad(rhs, ((0, 0), (0, _PAD - p * s)))
    return binsT, rhs, c


def split_hilo(rhs: jax.Array) -> jax.Array:
    """f32 [N, W] -> [hi || lo] bf16 [N, 2W]: the two halves' exact-product
    contributions recombine to ~16-17 mantissa bits of input precision."""
    rhs_hi = rhs.astype(jnp.bfloat16)
    rhs_lo = (rhs - rhs_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.concatenate([rhs_hi, rhs_lo], axis=1)


def histogram_tiles_pallas_mode(binsT, stats, leaf_ids, sel, num_bins,
                                block=2048, mode="hilo"):
    """[P, F, B, S] histogram tile via the fused kernel.

    ``mode``: "hilo" (2-pass bf16, the fast f32 default), "highest"
    (6-pass, precise), or "q8" (int8 stats -> exact int32 histograms for
    the quantized-gradient training mode; ~2x hilo's MXU rate).
    Takes the FEATURE-MAJOR bin matrix [F, N].

    The grid is ``ceil(N / block)`` row steps, so the grower's row
    compaction (ops/histogram.py compact_rows) shrinks the kernel's grid
    in proportion to the ladder rung: a [F, N/8] compacted buffer runs an
    8x smaller grid than the full pass, same per-step working set.
    """
    f = binsT.shape[0]
    p = sel.shape[0]
    s = stats.shape[1]
    binsT, rhs, c = _prep_rhs(binsT, stats, leaf_ids, sel, block,
                              q8=(mode == "q8"))
    if mode == "hilo":
        rhs = split_hilo(rhs)
    out = _hist_pallas_call(binsT, rhs, num_bins=num_bins, block=c,
                            mode=mode)
    return out[:, :p * s].reshape(f, num_bins, p, s).transpose(2, 0, 1, 3)


def histogram_tiles_pallas(binsT: jax.Array, stats: jax.Array,
                           leaf_ids: jax.Array, sel: jax.Array,
                           num_bins: int, block: int = 2048) -> jax.Array:
    """[P, F, B, S] histogram tile via the fused kernel, HIGHEST precision.

    Args mirror histogram.py histogram_tiles but take the FEATURE-MAJOR bin
    matrix [F, N] (contiguous per-feature rows for the kernel's block
    loads).
    """
    return histogram_tiles_pallas_mode(binsT, stats, leaf_ids, sel,
                                       num_bins, block, mode="highest")


def histogram_tiles_pallas_hilo(binsT: jax.Array, stats: jax.Array,
                                leaf_ids: jax.Array, sel: jax.Array,
                                num_bins: int, block: int = 2048) -> jax.Array:
    """[P, F, B, S] histogram tile via the fused kernel, hi/lo bf16 matmuls
    (the fast default — see the module docstring's precision model)."""
    return histogram_tiles_pallas_mode(binsT, stats, leaf_ids, sel,
                                       num_bins, block, mode="hilo")
