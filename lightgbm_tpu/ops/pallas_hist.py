"""Pallas TPU kernels for the histogram tile pass — the primary TPU path.

The fused re-design of the CUDA histogram kernels (reference:
src/treelearner/kernels/histogram_16_64_256.cu:16-120 — per-workgroup
shared-memory sub-histograms with atomic adds). On TPU there are no atomics;
instead each grid step builds the per-feature bin one-hot IN VMEM and
contracts it with the (leaf-slot x stat) channel matrix on the MXU,
accumulating into a VMEM-resident [F*B, P*S] output that is flushed once.

Three fusions keep the pass's HBM traffic at the bin matrix itself:

1. **In-kernel leaf channels.** The (leaf-onehot x stats) RHS is built
   inside the grid step from the raw ``[N]`` leaf ids and ``[N, S]`` stats.
   The previous design prepared an ``[N, 128]`` f32 RHS in XLA — ~18x the
   HBM bytes of the int8 bin matrix it accompanied (25x+ in the hilo mode's
   bf16-pair form), written and re-read every pass. Fused, the RHS never
   exists outside VMEM: per-pass traffic drops to
   ``bins + stats + leaf_ids + output``.

2. **In-kernel row gather.** The compaction ladder (ops/histogram.py,
   the DataPartition analog) used to materialize a compacted ``[F, N/r]``
   bin-matrix copy in HBM (``jnp.take``) that the kernel then re-read. The
   gather form of the kernel instead takes the ladder's row-index buffer
   directly (scalar-prefetched to SMEM) and DMAs the pending rows' bin
   columns / stats / leaf ids from the HBM-resident full arrays into VMEM
   scratch inside the grid step — the paged-attention idiom at row
   granularity. The compacted copy is never materialized; per-pass traffic
   is the touched rows plus the index buffer. (Row-granularity DMA is
   latency- not bandwidth-bound; the ladder only selects this form when the
   rung is <= N/2, where the full-pass alternative reads >= 2x the bytes.)

3. **Quantized-gradient mode.** ``mode="q8"`` contracts int8 stats with the
   int8 one-hot on the MXU's int8 path (~2x the bf16 rate) with EXACT int32
   accumulation; the grower rescales to f32 once per tile, at split-gain
   time (models/grower.py quant8). ``Config.quantized_grad`` turns this
   into an end-to-end training mode: int8 grad/hess with stochastic
   rounding, following the XGBoost-GPU recipe (arXiv:1706.08359 §5).

Two float precision modes share the same kernel body (``mode``):

- "hilo" (the fast default): the RHS is split into [hi || lo] bf16 halves
  of the f32 channels IN KERNEL; both halves' products accumulate in f32 on
  the MXU, so the recombined sum carries ~16-17 mantissa bits of input
  precision (~2^-17 relative rounding) with exact counts — comparable to
  (slightly coarser than) the reference GPU's float32 histograms
  (gpu_use_dp=false, docs/GPU-Performance.rst:133-140), at 2 bf16 MXU
  passes.
- "highest": f32 RHS contracted at Precision.HIGHEST (6 bf16 passes) — the
  precise alternative, selected by ``deterministic=true``.

``interpret=True`` runs any kernel through the Pallas interpreter so the
whole pipeline (including the DMA gather) is testable on CPU hosts
(``Config.hist_pallas_interpret``); tier-1 parity suites run this way.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

_PAD = 128          # lane width; P*S channels are padded up to this


def _chan_layout(p: int, s: int):
    """Static per-lane channel layout: output lane q carries stat channel
    ``s_of_q[q]`` of tile slot ``p_of_q[q]`` (q < p*s; higher lanes are
    dead padding). Matches the ``reshape(-1, p*s)`` layout of the XLA
    formulations so outputs slice/reshape identically."""
    q = np.arange(_PAD)
    valid = q < p * s
    p_of_q = np.where(valid, np.minimum(q // s, p - 1), 0)
    s_of_q = np.where(valid, q % s, 0)
    return p_of_q, s_of_q, valid


def chan_leaf_table(sel: jax.Array, s: int) -> jax.Array:
    """[1, _PAD] int32: the leaf id each output lane accumulates, or -9 for
    dead lanes. Built in XLA from the tile selection ``sel`` (tiny — P
    int32 values), consumed whole by every grid step."""
    p = sel.shape[0]
    p_of_q, _, valid = _chan_layout(p, s)
    return jnp.where(jnp.asarray(valid),
                     sel[jnp.asarray(p_of_q)], jnp.int32(-9))[None, :]


def split_hilo(rhs: jax.Array) -> jax.Array:
    """f32 [N, W] -> [hi || lo] bf16 [N, 2W]: the two halves' exact-product
    contributions recombine to ~16-17 mantissa bits of input precision."""
    rhs_hi = rhs.astype(jnp.bfloat16)
    rhs_lo = (rhs - rhs_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.concatenate([rhs_hi, rhs_lo], axis=1)


def _accumulate(binsT_blk, leaf_blk, stats_blk, chan_leaf, vmask, out_ref,
                *, f, b, c, s, mode):
    """Shared fused compute body: build the leaf-channel RHS and the packed
    bin one-hot for one row block entirely in VMEM and contract on the MXU.

    binsT_blk: [F, C] int8 bin columns for this block's rows.
    leaf_blk:  [C] int32 leaf slot per row.
    stats_blk: [C, S] f32 (or int8 for q8) per-row statistics.
    chan_leaf: [_PAD] int32 leaf id per output lane (-9 = dead lane).
    vmask:     [C] bool row validity (gather padding) or None.
    """
    # --- leaf-channel RHS [C, _PAD]: lane q carries stats[:, q mod S]
    # where the row's leaf id matches the lane's slot, else 0. The layout
    # is periodic, so the expansion is a static tile+slice (no gather, no
    # captured index constants — both would fail kernel tracing).
    reps = -(-_PAD // max(s, 1))
    stat_chan = jnp.concatenate([stats_blk] * reps, axis=1)[:, :_PAD]
    # lanes q >= P*S carry garbage stat values here; their chan_leaf is -9
    # so ``match`` zeroes them below
    match = leaf_blk[:, None] == chan_leaf[None, :]          # [C, _PAD]
    if vmask is not None:
        match = match & vmask[:, None]
    oh_dtype = {"hilo": jnp.bfloat16, "highest": jnp.float32,
                "q8": jnp.int8}[mode]
    acc_dtype = jnp.int32 if mode == "q8" else jnp.float32
    prec = jax.lax.Precision.HIGHEST if mode == "highest" else None
    if mode == "q8":
        rhs = jnp.where(match, stat_chan, jnp.int8(0))
    else:
        rhs = jnp.where(match, stat_chan.astype(jnp.float32),
                        jnp.float32(0.0))
        if mode == "hilo":
            rhs = split_hilo(rhs)                            # [C, 2*_PAD]
    # Feature packing: with b <= 64 bins a single feature's one-hot fills
    # only b of the MXU's 128 output rows, so the matmul runs at b/128
    # utilization. Pack g = 128//b features side by side into one
    # [C, g*b] one-hot (disjoint lane ranges, so a plain sum builds the
    # OR) — the max_bin=63 configuration then drives full 128-row MXU
    # tiles instead of half-empty ones.
    g = max(1, _PAD // b) if b <= _PAD else 1
    for j0 in range(0, f, g):                                # static unroll
        m = min(g, f - j0)
        iota = jax.lax.broadcasted_iota(jnp.int32, (c, m * b), 1)
        oh = None
        for k in range(m):
            col = binsT_blk[j0 + k, :].astype(jnp.int32) + k * b     # [C]
            hit = (col[:, None] == iota).astype(oh_dtype)            # [C, m*B]
            oh = hit if oh is None else oh + hit
        acc = jax.lax.dot_general(
            oh, rhs, (((0,), (0,)), ((), ())), precision=prec,
            preferred_element_type=acc_dtype)
        if mode == "hilo":
            acc = acc[:, :_PAD] + acc[:, _PAD:]              # recombine
        out_ref[j0 * b:(j0 + m) * b, :] += acc


def _fused_kernel(binsT_ref, leaf_ref, stats_ref, chan_ref, out_ref,
                  *, f, b, c, s, mode):
    """Full-pass fused kernel: leaf channels built in kernel, rows streamed
    block-by-block straight from the bin matrix (fusion 1)."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    _accumulate(binsT_ref[...], leaf_ref[0, :], stats_ref[...],
                chan_ref[0, :], None, out_ref, f=f, b=b, c=c, s=s, mode=mode)


def _dma_gather_rows(idx_ref, binsT_hbm, leaf_hbm, stats_hbm, bins_s, leaf_s,
                     stats_s, sem_b, sem_l, sem_s, *, i, c, n):
    """Shared DMA body of the gather kernels: issue grid step ``i``'s
    per-row copies back-to-back into the VMEM scratch buffers, then drain
    them (same src/dst shapes -> same byte counts, so c waits per stream
    drain exactly the c started copies). Padding entries (idx >= n) clamp
    to row n-1; the CALLER masks them out of the leaf match via the
    prefetched index values."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _copies(k):
        j = jnp.minimum(idx_ref[i * c + k], n - 1)
        return (
            pltpu.make_async_copy(binsT_hbm.at[:, pl.ds(j, 1)],
                                  bins_s.at[:, pl.ds(k, 1)], sem_b),
            pltpu.make_async_copy(leaf_hbm.at[:, pl.ds(j, 1)],
                                  leaf_s.at[:, pl.ds(k, 1)], sem_l),
            pltpu.make_async_copy(stats_hbm.at[pl.ds(j, 1), :],
                                  stats_s.at[pl.ds(k, 1), :], sem_s),
        )

    def start(k, _):
        for dma in _copies(k):
            dma.start()
        return 0

    jax.lax.fori_loop(0, c, start, 0)

    def wait(k, _):
        for dma in _copies(0):
            dma.wait()
        return 0

    jax.lax.fori_loop(0, c, wait, 0)


def _gather_kernel(idx_ref, binsT_hbm, leaf_hbm, stats_hbm, idxv_ref,
                   chan_ref, out_ref, bins_s, leaf_s, stats_s,
                   sem_b, sem_l, sem_s, *, f, b, c, s, mode, n):
    """Compacted-pass fused kernel (fusion 2): the grid step DMAs the
    pending rows' bin columns, leaf ids and stats from the HBM-resident
    FULL arrays into VMEM scratch using the scalar-prefetched row-index
    buffer, then runs the same compute body. The compacted ``[F, N/r]``
    copy the XLA ladder used to write/re-read is never materialized.

    Per-row DMA is latency-bound, not bandwidth-bound — the three copy
    streams (bins column, stats row, leaf id) are issued back-to-back for
    the whole block before the first wait, so the DMA engines pipeline
    across rows. ``idx`` entries >= n are ladder padding: their source is
    clamped to row n-1 and the row is masked out of the leaf match."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    _dma_gather_rows(idx_ref, binsT_hbm, leaf_hbm, stats_hbm, bins_s,
                     leaf_s, stats_s, sem_b, sem_l, sem_s, i=i, c=c, n=n)

    vmask = idxv_ref[0, :] < n
    _accumulate(bins_s[...], leaf_s[0, :], stats_s[...], chan_ref[0, :],
                vmask, out_ref, f=f, b=b, c=c, s=s, mode=mode)


def _compiler_params():
    from jax.experimental.pallas import tpu as pltpu
    # CompilerParams was TPUCompilerParams before jax 0.5
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    return cls(
        dimension_semantics=("arbitrary",),
        # the default 16M scoped-vmem cap rejects the q8 mode at full
        # Higgs scale (measured 2026-07-30: int8 accumulation needed a
        # 28.31M stack allocation at block=2048, F=28, B=255); the
        # kernel's working set is still far below the 128M physical
        # VMEM, so raise the cap rather than shrink the block
        vmem_limit_bytes=100 * 1024 * 1024)


def _out_spec(f, num_bins, mode):
    out_dtype = jnp.int32 if mode == "q8" else jnp.float32
    return jax.ShapeDtypeStruct((f * num_bins, _PAD), out_dtype)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block", "mode", "interpret"))
def _fused_call(binsT, leaf2d, stats, chan, *, num_bins, block, mode,
                interpret=False):
    """Full-pass launch: N must be padded to a ``block`` multiple (pad leaf
    ids with -2 so padding matches no lane)."""
    from jax.experimental import pallas as pl
    f, n = binsT.shape
    s = stats.shape[1]
    c = block
    nblk = n // c
    kernel = functools.partial(_fused_kernel, f=f, b=num_bins, c=c, s=s,
                               mode=mode)
    kw = ({"interpret": True} if interpret
          else {"compiler_params": _compiler_params()})
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((f, c), lambda i: (0, i)),
            pl.BlockSpec((1, c), lambda i: (0, i)),
            pl.BlockSpec((c, s), lambda i: (i, 0)),
            pl.BlockSpec((1, _PAD), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((f * num_bins, _PAD), lambda i: (0, 0)),
        out_shape=_out_spec(f, num_bins, mode),
        **kw,
    )(binsT, leaf2d, stats, chan)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block", "mode", "interpret"))
def _fused_gather_call(idx, binsT, leaf2d, stats, idx2d, chan, *, num_bins,
                       block, mode, interpret=False):
    """Compacted-pass launch: ``idx`` [M] (M a ``block`` multiple, padded
    with n) indexes rows of the FULL binsT/leaf/stats, which stay HBM
    resident (memory_space ANY) and are gathered in kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    f, n = binsT.shape
    s = stats.shape[1]
    m = idx.shape[0]
    c = block
    nblk = m // c
    kernel = functools.partial(_gather_kernel, f=f, b=num_bins, c=c, s=s,
                               mode=mode, n=n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),            # binsT [F, N]
            pl.BlockSpec(memory_space=pltpu.ANY),            # leaf  [1, N]
            pl.BlockSpec(memory_space=pltpu.ANY),            # stats [N, S]
            pl.BlockSpec((1, c), lambda i, idx_ref: (0, i)),  # idx2d
            pl.BlockSpec((1, _PAD), lambda i, idx_ref: (0, 0)),  # chan
        ],
        out_specs=pl.BlockSpec((f * num_bins, _PAD),
                               lambda i, idx_ref: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((f, c), binsT.dtype),
            pltpu.VMEM((1, c), jnp.int32),
            pltpu.VMEM((c, s), stats.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    kw = ({"interpret": True} if interpret
          else {"compiler_params": _compiler_params()})
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_spec(f, num_bins, mode),
        **kw,
    )(idx, binsT, leaf2d, stats, idx2d, chan)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def histogram_tiles_pallas_mode(binsT, stats, leaf_ids, sel, num_bins,
                                block=2048, mode="hilo", idx=None,
                                interpret=False):
    """[P, F, B, S] histogram tile via the fused kernel.

    ``mode``: "hilo" (2-pass bf16, the fast f32 default), "highest"
    (6-pass, precise), or "q8" (int8 stats -> exact int32 histograms for
    the quantized-gradient training mode; ~2x hilo's MXU rate).
    Takes the FEATURE-MAJOR bin matrix [F, N].

    ``idx``: optional [M] int32 compacted row-index buffer (the compaction
    ladder's output, ops/histogram.py compact_indices; entries >= N are
    padding). When given, the GATHER form of the kernel runs: binsT/stats/
    leaf_ids stay HBM resident and only the indexed rows are DMA'd into
    VMEM inside the grid step — the grid is ``ceil(M / block)`` instead of
    ``ceil(N / block)`` and no compacted copy is materialized. Without it
    the full-pass form streams all N rows (the grower picks idx via its
    ladder dispatch, so every rung compiles once).

    ``interpret=True`` runs the kernel through the Pallas interpreter
    (CPU-testable; Config.hist_pallas_interpret).
    """
    f, n = binsT.shape
    p = sel.shape[0]
    s = stats.shape[1]
    assert p * s <= _PAD, (p, s)
    chan = chan_leaf_table(sel, s)
    leaf2d = leaf_ids[None, :].astype(jnp.int32)
    if mode != "q8":
        stats = stats.astype(jnp.float32)
    if idx is not None:
        c = min(block, max(128, _round_up(idx.shape[0], 128)))
        mpad = _round_up(idx.shape[0], c)
        idx = idx.astype(jnp.int32)
        if mpad != idx.shape[0]:
            idx = jnp.pad(idx, (0, mpad - idx.shape[0]),
                          constant_values=n)
        out = _fused_gather_call(idx, binsT, leaf2d, stats, idx[None, :],
                                 chan, num_bins=num_bins, block=c,
                                 mode=mode, interpret=interpret)
    else:
        c = min(block, max(512, _round_up(n, 512)))
        pad = _round_up(n, c) - n
        if pad:
            # loop-invariant: XLA hoists these pads out of the grower's
            # while_loop, so the padded copies are built once per program,
            # not once per pass
            binsT = jnp.pad(binsT, ((0, 0), (0, pad)))
            stats = jnp.pad(stats, ((0, pad), (0, 0)))
            leaf2d = jnp.pad(leaf2d, ((0, 0), (0, pad)),
                             constant_values=-2)
        out = _fused_call(binsT, leaf2d, stats, chan, num_bins=num_bins,
                          block=c, mode=mode, interpret=interpret)
    return out[:, :p * s].reshape(f, num_bins, p, s).transpose(2, 0, 1, 3)


def histogram_tiles_pallas(binsT: jax.Array, stats: jax.Array,
                           leaf_ids: jax.Array, sel: jax.Array,
                           num_bins: int, block: int = 2048,
                           idx=None, interpret: bool = False) -> jax.Array:
    """[P, F, B, S] histogram tile via the fused kernel, HIGHEST precision.

    Args mirror histogram.py histogram_tiles but take the FEATURE-MAJOR bin
    matrix [F, N] (contiguous per-feature rows for the kernel's block
    loads).
    """
    return histogram_tiles_pallas_mode(binsT, stats, leaf_ids, sel,
                                       num_bins, block, mode="highest",
                                       idx=idx, interpret=interpret)


def histogram_tiles_pallas_hilo(binsT: jax.Array, stats: jax.Array,
                                leaf_ids: jax.Array, sel: jax.Array,
                                num_bins: int, block: int = 2048,
                                idx=None, interpret: bool = False
                                ) -> jax.Array:
    """[P, F, B, S] histogram tile via the fused kernel, hi/lo bf16 matmuls
    (the fast default — see the module docstring's precision model)."""
    return histogram_tiles_pallas_mode(binsT, stats, leaf_ids, sel,
                                       num_bins, block, mode="hilo",
                                       idx=idx, interpret=interpret)


# ------------------------------------------------- split-finding epilogue
#
# The fused split epilogue (ISSUE 12): after the last grid step has
# accumulated the tile's histogram planes in VMEM, the kernel (a) derives
# each DERIVED sibling's plane in-register as parent - computed-sibling —
# sibling pairs occupy ADJACENT slot pairs (computed even, derived odd),
# so the sibling's lanes are a STATIC s-lane shift, no dynamic lane
# gather — and (b) runs the numerical split-gain scan (ops/split.py
# numerical_candidates, the same jnp ops as the XLA twin) over every
# slot's plane, reducing each (leaf, feature) to one best candidate.
# Only the [P, F, CAND_CHANNELS] table and the (still-parent-needed)
# plane leave VMEM; the grower's split phase never touches [L, F, B, S]
# planes again.


def _epilogue_lanes(sel: jax.Array, derive: jax.Array, s: int,
                    q_scale=None):
    """Per-lane epilogue tables: (derive_lane [1, _PAD] int32, qscale_lane
    [1, _PAD] f32). Lane q belongs to slot p_of_q; derived slots read the
    sibling's lanes at q - s in the kernel."""
    p = sel.shape[0]
    p_of_q, s_of_q, valid = _chan_layout(p, s)
    dl = (jnp.asarray(valid)
          & derive[jnp.asarray(p_of_q)]
          & (sel[jnp.asarray(p_of_q)] >= 0)).astype(jnp.int32)[None, :]
    if q_scale is None:
        ql = jnp.ones((1, _PAD), jnp.float32)
    else:
        ql = q_scale[jnp.asarray(s_of_q)][None, :].astype(jnp.float32)
    return dl, ql


def _epilogue_params(pv: jax.Array):
    """Rebuild the 7 numerical-scan SplitParams fields from the packed
    scalar vector the kernel loads (unused fields zeroed)."""
    from .split import SplitParams
    z = jnp.float32(0.0)
    return SplitParams(
        lambda_l1=pv[0], lambda_l2=pv[1], max_delta_step=pv[2],
        path_smooth=pv[3], min_data_in_leaf=pv[4],
        min_sum_hessian_in_leaf=pv[5], min_gain_to_split=pv[6],
        cat_l2=z, cat_smooth=z, max_cat_threshold=jnp.int32(0),
        min_data_per_group=z, max_cat_to_onehot=jnp.int32(0),
        monotone_penalty=z, cegb_tradeoff=z, cegb_penalty_split=z)


def _epilogue_compute(acc, parent, derive_lane, qscale, la, fm, pv, *,
                      f, b, p, s, mode, with_monotone):
    """Shared epilogue body (kernel AND the XLA twin go through the same
    ops): dequantize (q8), derive odd-slot siblings by the static lane
    shift, then scan. Returns (full plane [F*B, _PAD], cand [P, F, C])."""
    from .split import _round_fence, numerical_candidates
    params = _epilogue_params(pv)
    if mode == "q8":
        # the dequant product must round to concrete bits BEFORE the
        # sibling subtraction below — XLA otherwise contracts the
        # multiply into the subtract (fused multiply-sub) differently
        # per compilation context (e.g. across compaction-rung branches),
        # breaking the ladder-invariance the exact integer accumulation
        # guarantees (see ops/split.py _round_fence)
        plane = _round_fence(acc.astype(jnp.float32) * qscale, params)
    else:
        plane = acc
    # derived slot q reads its computed sibling at lane q - s (adjacent
    # slot pair), stat channel preserved
    shifted = jnp.concatenate(
        [jnp.zeros((f * b, s), jnp.float32), plane[:, :_PAD - s]], axis=1)
    full = jnp.where(derive_lane != 0, parent - shifted, plane)
    pf = full[:, :p * s].reshape(f, b, p, s).transpose(2, 0, 1, 3)
    cand = numerical_candidates(
        pf, la[:, 0], la[:, 1], la[:, 2], la[:, 3],
        fm[:, 0].astype(jnp.int32), fm[:, 1].astype(jnp.int32),
        fm[:, 2].astype(jnp.int32), fm[:, 3].astype(jnp.int32),
        params, with_monotone=with_monotone,
        leaf_min=la[:, 4], leaf_max=la[:, 5])
    return full, cand


def _fused_epi_kernel(binsT_ref, leaf_ref, stats_ref, chan_ref, parent_ref,
                      la_ref, fm_ref, pv_ref, qs_ref, der_ref,
                      plane_ref, cand_ref, acc_ref, *,
                      f, b, c, s, mode, p, nblk, with_monotone):
    """Full-pass fused kernel WITH the split epilogue: accumulation runs
    in a VMEM scratch; the last grid step derives siblings, scans, and
    writes both outputs once."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        plane_ref[...] = jnp.zeros_like(plane_ref)
        cand_ref[...] = jnp.zeros_like(cand_ref)

    _accumulate(binsT_ref[...], leaf_ref[0, :], stats_ref[...],
                chan_ref[0, :], None, acc_ref, f=f, b=b, c=c, s=s, mode=mode)

    @pl.when(i == nblk - 1)
    def _epi():
        full, cand = _epilogue_compute(
            acc_ref[...], parent_ref[...], der_ref[...], qs_ref[...],
            la_ref[...], fm_ref[...], pv_ref[0, :], f=f, b=b, p=p, s=s,
            mode=mode, with_monotone=with_monotone)
        plane_ref[...] = full
        cand_ref[...] = cand


def _gather_epi_kernel(idx_ref, binsT_hbm, leaf_hbm, stats_hbm, idxv_ref,
                       chan_ref, parent_ref, la_ref, fm_ref, pv_ref,
                       qs_ref, der_ref, plane_ref, cand_ref,
                       bins_s, leaf_s, stats_s, sem_b, sem_l, sem_s,
                       acc_ref, *, f, b, c, s, mode, n, p, nblk,
                       with_monotone):
    """Compacted-pass fused kernel WITH the split epilogue (in-kernel DMA
    row gather + scratch accumulation + last-step scan)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        plane_ref[...] = jnp.zeros_like(plane_ref)
        cand_ref[...] = jnp.zeros_like(cand_ref)

    _dma_gather_rows(idx_ref, binsT_hbm, leaf_hbm, stats_hbm, bins_s,
                     leaf_s, stats_s, sem_b, sem_l, sem_s, i=i, c=c, n=n)

    vmask = idxv_ref[0, :] < n
    _accumulate(bins_s[...], leaf_s[0, :], stats_s[...], chan_ref[0, :],
                vmask, acc_ref, f=f, b=b, c=c, s=s, mode=mode)

    @pl.when(i == nblk - 1)
    def _epi():
        full, cand = _epilogue_compute(
            acc_ref[...], parent_ref[...], der_ref[...], qs_ref[...],
            la_ref[...], fm_ref[...], pv_ref[0, :], f=f, b=b, p=p, s=s,
            mode=mode, with_monotone=with_monotone)
        plane_ref[...] = full
        cand_ref[...] = cand


def _epi_out_specs(f, num_bins, p):
    from .split import CAND_CHANNELS
    return (jax.ShapeDtypeStruct((f * num_bins, _PAD), jnp.float32),
            jax.ShapeDtypeStruct((p, f, CAND_CHANNELS), jnp.float32))


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block", "mode", "interpret",
                                    "with_monotone"))
def _fused_epi_call(binsT, leaf2d, stats, chan, parent, la, fm, pv2d, qs,
                    der, *, num_bins, block, mode, interpret=False,
                    with_monotone=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    f, n = binsT.shape
    s = stats.shape[1]
    p = la.shape[0]
    c = block
    nblk = n // c
    acc_dtype = jnp.int32 if mode == "q8" else jnp.float32
    kernel = functools.partial(_fused_epi_kernel, f=f, b=num_bins, c=c, s=s,
                               mode=mode, p=p, nblk=nblk,
                               with_monotone=with_monotone)
    kw = ({"interpret": True} if interpret
          else {"compiler_params": _compiler_params()})
    const = pl.BlockSpec
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            const((f, c), lambda i: (0, i)),
            const((1, c), lambda i: (0, i)),
            const((c, s), lambda i: (i, 0)),
            const((1, _PAD), lambda i: (0, 0)),
            const((f * num_bins, _PAD), lambda i: (0, 0)),   # parent
            const(la.shape, lambda i: (0, 0)),
            const(fm.shape, lambda i: (0, 0)),
            const((1, 8), lambda i: (0, 0)),
            const((1, _PAD), lambda i: (0, 0)),
            const((1, _PAD), lambda i: (0, 0)),
        ],
        out_specs=(const((f * num_bins, _PAD), lambda i: (0, 0)),
                   const(_epi_out_specs(f, num_bins, p)[1].shape,
                         lambda i: (0, 0, 0))),
        out_shape=_epi_out_specs(f, num_bins, p),
        scratch_shapes=[pltpu.VMEM((f * num_bins, _PAD), acc_dtype)],
        **kw,
    )(binsT, leaf2d, stats, chan, parent, la, fm, pv2d, qs, der)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block", "mode", "interpret",
                                    "with_monotone"))
def _fused_gather_epi_call(idx, binsT, leaf2d, stats, idx2d, chan, parent,
                           la, fm, pv2d, qs, der, *, num_bins, block, mode,
                           interpret=False, with_monotone=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    f, n = binsT.shape
    s = stats.shape[1]
    p = la.shape[0]
    m = idx.shape[0]
    c = block
    nblk = m // c
    acc_dtype = jnp.int32 if mode == "q8" else jnp.float32
    kernel = functools.partial(_gather_epi_kernel, f=f, b=num_bins, c=c,
                               s=s, mode=mode, n=n, p=p, nblk=nblk,
                               with_monotone=with_monotone)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),            # binsT
            pl.BlockSpec(memory_space=pltpu.ANY),            # leaf
            pl.BlockSpec(memory_space=pltpu.ANY),            # stats
            pl.BlockSpec((1, c), lambda i, idx_ref: (0, i)),  # idx2d
            pl.BlockSpec((1, _PAD), lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((f * num_bins, _PAD),
                         lambda i, idx_ref: (0, 0)),         # parent
            pl.BlockSpec(la.shape, lambda i, idx_ref: (0, 0)),
            pl.BlockSpec(fm.shape, lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((1, 8), lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((1, _PAD), lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((1, _PAD), lambda i, idx_ref: (0, 0)),
        ],
        out_specs=(pl.BlockSpec((f * num_bins, _PAD),
                                lambda i, idx_ref: (0, 0)),
                   pl.BlockSpec(_epi_out_specs(f, num_bins, p)[1].shape,
                                lambda i, idx_ref: (0, 0, 0))),
        scratch_shapes=[
            pltpu.VMEM((f, c), binsT.dtype),
            pltpu.VMEM((1, c), jnp.int32),
            pltpu.VMEM((c, s), stats.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((f * num_bins, _PAD), acc_dtype),
        ],
    )
    kw = ({"interpret": True} if interpret
          else {"compiler_params": _compiler_params()})
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_epi_out_specs(f, num_bins, p),
        **kw,
    )(idx, binsT, leaf2d, stats, idx2d, chan, parent, la, fm, pv2d, qs, der)


def pack_leaf_aux(sum_g, sum_h, cnt, output, leaf_min=None, leaf_max=None):
    """[P, 8] f32 per-slot leaf aggregates for the epilogue kernel
    (columns: sum_g, sum_h, cnt, output, min, max, 0, 0)."""
    p = sum_g.shape[0]
    big = np.float32(np.finfo(np.float32).max)
    lmin = (jnp.full((p,), -big) if leaf_min is None
            else leaf_min.astype(jnp.float32))
    lmax = (jnp.full((p,), big) if leaf_max is None
            else leaf_max.astype(jnp.float32))
    cols = [sum_g, sum_h, cnt, output, lmin, lmax,
            jnp.zeros((p,)), jnp.zeros((p,))]
    return jnp.stack([a.astype(jnp.float32) for a in cols], axis=1)


def pack_feature_meta(num_bins_f, missing_type_f, default_bin_f, monotone_f):
    """[F, 8] f32 per-feature scan metadata for the epilogue kernel
    (columns: num_bins, missing_type, default_bin, monotone, 0...)."""
    f = num_bins_f.shape[0]
    cols = [num_bins_f, missing_type_f, default_bin_f, monotone_f]
    cols = [a.astype(jnp.float32) for a in cols] + [jnp.zeros((f,))] * 4
    return jnp.stack(cols, axis=1)


def pack_scan_params(p) -> jax.Array:
    """[7] f32 packed numerical-scan SplitParams for the epilogue kernel
    (inverse of _epilogue_params)."""
    return jnp.stack([
        p.lambda_l1, p.lambda_l2, p.max_delta_step, p.path_smooth,
        p.min_data_in_leaf, p.min_sum_hessian_in_leaf,
        p.min_gain_to_split]).astype(jnp.float32)


def histogram_tiles_pallas_epilogue(binsT, stats, leaf_ids, sel, derive,
                                    parent_planes, leaf_aux, fmeta, pvec,
                                    num_bins, block=2048, mode="hilo",
                                    idx=None, interpret=False,
                                    with_monotone=False, q_scale=None):
    """Fused histogram pass + in-kernel split epilogue.

    Args beyond histogram_tiles_pallas_mode:
      sel: [P] leaf per slot; sibling pairs occupy ADJACENT slot pairs —
        computed (smaller) sibling at even slots, derived at odd slots.
        Derived slots accumulate no rows (their chan lanes are dead) and
        get their plane as parent - computed-sibling in the epilogue.
      derive: [P] bool marking the derived slots.
      parent_planes: [P, F, B, S] f32 parent histograms for the derived
        slots (zeros elsewhere; XLA-gathered from the grower's resident
        state, the one plane-sized read the subtraction needs).
      leaf_aux: [P, 8] from pack_leaf_aux.
      fmeta: [F, 8] from pack_feature_meta.
      pvec: [7] from pack_scan_params.
      q_scale: [S] dequant scale for mode="q8" (the grower's per-tree
        scales; the kernel dequantizes before deriving, so subtraction
        runs in f32 exactly like the classic XLA flow).

    Returns (tile [P, F, B, S] f32 — derived planes included, resident
    for the next level's subtraction — and cand [P, F, CAND_CHANNELS]).
    """
    f, n = binsT.shape
    p = sel.shape[0]
    s = stats.shape[1]
    assert s == 3, "the split epilogue expects (grad, hess, count) stats"
    assert p * s <= _PAD, (p, s)
    sel_compute = jnp.where(derive, -1, sel)
    chan = chan_leaf_table(sel_compute, s)
    der, qs = _epilogue_lanes(sel, derive, s,
                              q_scale if mode == "q8" else None)
    parent = jnp.zeros((f * num_bins, _PAD), jnp.float32)
    parent = parent.at[:, :p * s].set(
        parent_planes.astype(jnp.float32).transpose(1, 2, 0, 3)
        .reshape(f * num_bins, p * s))
    la = leaf_aux.astype(jnp.float32)
    fm = fmeta.astype(jnp.float32)
    pv2d = jnp.pad(pvec.astype(jnp.float32), (0, 1))[None, :]
    leaf2d = leaf_ids[None, :].astype(jnp.int32)
    if mode != "q8":
        stats = stats.astype(jnp.float32)
    if idx is not None:
        c = min(block, max(128, _round_up(idx.shape[0], 128)))
        mpad = _round_up(idx.shape[0], c)
        idx = idx.astype(jnp.int32)
        if mpad != idx.shape[0]:
            idx = jnp.pad(idx, (0, mpad - idx.shape[0]), constant_values=n)
        plane, cand = _fused_gather_epi_call(
            idx, binsT, leaf2d, stats, idx[None, :], chan, parent, la, fm,
            pv2d, qs, der, num_bins=num_bins, block=c, mode=mode,
            interpret=interpret, with_monotone=with_monotone)
    else:
        c = min(block, max(512, _round_up(n, 512)))
        pad = _round_up(n, c) - n
        if pad:
            binsT = jnp.pad(binsT, ((0, 0), (0, pad)))
            stats = jnp.pad(stats, ((0, pad), (0, 0)))
            leaf2d = jnp.pad(leaf2d, ((0, 0), (0, pad)),
                             constant_values=-2)
        plane, cand = _fused_epi_call(
            binsT, leaf2d, stats, chan, parent, la, fm, pv2d, qs, der,
            num_bins=num_bins, block=c, mode=mode, interpret=interpret,
            with_monotone=with_monotone)
    tile = (plane[:, :p * s].reshape(f, num_bins, p, s)
            .transpose(2, 0, 1, 3))
    return tile, cand


# ---------------------------------------------------------------- roofline

# MXU input-rate multiplier per mode: passes over the same one-hot x rhs
# contraction (hilo = 2 bf16 passes, highest = 6, q8 = 1 int8 pass)
MXU_PASSES = {"hilo": 2, "highest": 6, "q8": 1}


def traffic_model(n, f, b, p, s, mode="hilo", gathered_rows=None):
    """Modeled HBM bytes per histogram tile pass: the fused kernel vs the
    XLA one-hot formulation of the same contraction (which must
    materialize its one-hot and leaf-channel RHS through HBM — each
    counted write+read) vs the pre-fusion kernel (XLA-side [N, 128] RHS +
    compacted-copy gather). Used by the acceptance/traffic tests and
    scripts/kernel_bench.py; all quantities are static byte counts.

    ``gathered_rows``: rows the compaction ladder selected (the gather
    kernel's M); None = full pass over n rows.
    """
    stat_b = 1 if mode == "q8" else 4
    out_b = 4
    rhs_b = 1 if mode == "q8" else (2 * 2 if mode == "hilo" else 4)
    oh_b = 1 if mode == "q8" else (2 if mode == "hilo" else 4)
    m = n if gathered_rows is None else gathered_rows
    out_bytes = f * b * _PAD * out_b
    common = m * f + m * s * stat_b + m * 4          # bins + stats + leaf
    fused = common + out_bytes + (m * 4 if gathered_rows is not None else 0)
    # pre-fusion kernel: [N(=m), 128] RHS written by XLA then re-read by
    # the kernel, plus (when compacted) the [F, M] gathered copy written
    # then re-read
    prefusion = (common + out_bytes + 2 * m * _PAD * rhs_b
                 + (2 * m * f if gathered_rows is not None else 0))
    # XLA one-hot contraction: the [M, F*B] one-hot and the RHS both
    # round-trip HBM (XLA cannot keep either resident across the scan)
    xla_onehot = (common + out_bytes + 2 * m * f * b * oh_b
                  + 2 * m * _PAD * rhs_b)
    # split-search consumer bytes per LEAF (ISSUE 12): the classic split
    # phase streams each leaf's [F, B, S=3] f32 histogram plane through
    # the gain scan's temporaries; the fused epilogue returns only the
    # [F, CAND_CHANNELS] candidate row — a >= B/4x reduction in bytes
    # the search reads back from HBM (3*B*4 / (12*4) = exactly B/4 at
    # the 12-channel layout; kernel_bench asserts the floor from the
    # REAL returned buffers, not from this model)
    from .split import CAND_CHANNELS
    search_in_planes = f * b * s * 4
    search_in_cand = f * CAND_CHANNELS * 4
    return {"fused": fused, "prefusion": prefusion,
            "xla_onehot": xla_onehot, "output": out_bytes,
            "search_in_planes": search_in_planes,
            "search_in_cand": search_in_cand}


# ------------------------------------------------------------- autotuning

# measured (block, tile_leaves) per shape bucket — keyed like the predict
# engine's compile cache: (F, B, log2-row-bucket, mode)
_tuned: dict = {}

BLOCK_CANDIDATES = (1024, 2048, 4096, 8192)


def oom_shrink_block(block: int) -> int:
    """Rung 1 of the OOM degradation ladder: a histogram row block a
    quarter the current size (floor 256 — below that the per-pass
    overheads dominate and rung 2's formulation change is the right
    lever). ``block=0`` (the per-method auto default) shrinks from the
    kernel's 2048 default."""
    return max(256, (block or 2048) // 4)


def structural_tile_leaves(stats_channels: int = 3) -> int:
    """The leaf batch the kernel wants, by construction: the widest tile
    whose (leaf x stat) channels fit one 128-lane group. No measurement
    needed — kernel cost is flat in the tile width (channels occupy the
    full lane group either way)."""
    return max(1, _PAD // max(stats_channels, 1))


def autotune_hist(binsT, num_bins: int, mode: str = "hilo",
                  stats_channels: int = 3, sample_rows: int = 262144,
                  block_candidates=BLOCK_CANDIDATES,
                  force_measure: bool = False,
                  epilogue: bool = False) -> dict:
    """Measured kernel-shape tuning, keyed like the predict engine's shape
    buckets: TIME the fused kernel at each candidate row-block size on a
    sampled prefix and cache the winner per (F, B, log2-row-bucket, mode).

    The leaf batch (``tile_leaves``) is chosen structurally: the kernel's
    cost is flat in the tile width (channels occupy fixed 128 lanes), so
    the widest tile that fits the lane group — ``128 // S`` — always wins;
    it is returned alongside so the grower issues the fewest passes.

    Non-TPU backends return the static defaults without measuring
    (``force_measure`` overrides for tests, running in interpret mode).
    ``epilogue`` keys the sweep on the kernel FORM — the fused split
    epilogue changes the block-shape economics (scratch accumulation +
    the in-kernel scan), so a block tuned for the plane-returning kernel
    must never ride into the epilogue kernel (ISSUE 12's trainer-state
    contract; models/gbdt.py _hist_tuning enforces the same rule on
    checkpoint-ridden dicts). Returns ``{"block": int, "tile_leaves":
    int, "epilogue": bool}`` (0 = keep defaults).
    """
    import time

    tile = structural_tile_leaves(stats_channels)
    if jax.default_backend() != "tpu" and not force_measure:
        return {"block": 0, "tile_leaves": 0, "epilogue": epilogue}
    f, n = binsT.shape
    key = (f, int(num_bins), max(n, 1).bit_length(), mode, epilogue)
    hit = _tuned.get(key)
    if hit is not None:
        return hit
    interpret = jax.default_backend() != "tpu"
    k = min(n, sample_rows)
    subT = binsT[:, :k]
    st_dtype = jnp.int8 if mode == "q8" else jnp.float32
    stats = jnp.ones((k, stats_channels), st_dtype)
    lid = jnp.zeros((k,), jnp.int32)
    sel = jnp.zeros((tile,), jnp.int32).at[1:].set(-1)
    if epilogue:
        derive = jnp.zeros((tile,), bool)
        parent = jnp.zeros((tile, f, num_bins, stats_channels), jnp.float32)
        la = pack_leaf_aux(*(jnp.zeros((tile,)) for _ in range(4)))
        fmeta = pack_feature_meta(
            jnp.full((f,), num_bins, jnp.int32),
            jnp.zeros((f,), jnp.int32), jnp.zeros((f,), jnp.int32),
            jnp.zeros((f,), jnp.int32))
        pvec = jnp.zeros((7,), jnp.float32)
        qsc = (jnp.ones((stats_channels,), jnp.float32)
               if mode == "q8" else None)

        def run_fn(blk):
            t, c = histogram_tiles_pallas_epilogue(
                subT, stats, lid, sel, derive, parent, la, fmeta, pvec,
                num_bins, block=blk, mode=mode, interpret=interpret,
                q_scale=qsc)
            return jnp.sum(t) + jnp.sum(c)
    else:
        def run_fn(blk):
            return jnp.sum(histogram_tiles_pallas_mode(
                subT, stats, lid, sel, num_bins, block=blk, mode=mode,
                interpret=interpret))
    times = {}
    for blk in block_candidates:
        if blk > _round_up(k, 512):
            continue
        try:
            r = run_fn(blk)
            r.block_until_ready()                # compile + first run
            t0 = time.time()
            float(run_fn(blk))                   # sync via scalar fetch
            times[blk] = time.time() - t0
        except Exception as e:                   # candidate unsupported
            from ..utils import faults
            if faults.is_resource_exhausted(e):
                # a candidate block that exhausts VMEM/HBM is not an
                # error — it is exactly what the sweep exists to avoid;
                # name it so an operator can see the shape is memory-bound
                from ..utils import log
                log.info(f"pallas hist autotune: block {blk} skipped "
                         f"(RESOURCE_EXHAUSTED at this shape)")
            continue
    if not times:
        out = {"block": 0, "tile_leaves": tile, "epilogue": epilogue}
    else:
        best = min(times, key=times.get)
        from ..utils import log
        log.info("pallas hist autotune: "
                 + ", ".join(f"blk{b_}={t * 1e3:.1f}ms"
                             for b_, t in sorted(times.items()))
                 + f" -> block={best} tile_leaves={tile} "
                 f"(at {k} sampled rows, mode={mode}, "
                 f"epilogue={epilogue})")
        out = {"block": best, "tile_leaves": tile, "epilogue": epilogue}
    _tuned[key] = out
    return out
