"""Pallas TPU kernel for the histogram tile pass.

The fused re-design of the CUDA histogram kernels (reference:
src/treelearner/kernels/histogram_16_64_256.cu:16-120 — per-workgroup
shared-memory sub-histograms with atomic adds). On TPU there are no atomics;
instead each grid step builds the per-feature bin one-hot IN VMEM and
contracts it with the (leaf-slot x stat) channel matrix on the MXU,
accumulating into a VMEM-resident [F*B, P*S] output that is flushed once.

Why a kernel at all: the XLA formulation (histogram.py "onehot") must
materialize the ``[C, F*B]`` one-hot in HBM — ~300 GB of traffic per full
pass at Higgs scale, which bounds the pass at ~370-450 ms. Fused, the
one-hot never leaves VMEM and the pass is bounded by the bin-compare VPU
work (~75 G ops) plus the f32 matmuls.

The leaf-channel RHS ``[N, PAD]`` (leaf one-hot x stats, PS columns padded
to the 128-lane boundary) is prepared by XLA — it is small (~2% of the
one-hot's traffic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_PAD = 128          # lane width; P*S channels are padded up to this


def _hist_kernel(binsT_ref, rhs_ref, out_ref, *, f, b, c):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rhs = rhs_ref[...]                                   # [C, PAD] f32
    binsT = binsT_ref[...]                               # [F, C] int8
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (c, b), 1)
    for j in range(f):                                   # static unroll
        col = binsT[j, :].astype(jnp.int32)              # [C]
        oh = (col[:, None] == iota_b).astype(jnp.float32)   # [C, B] in VMEM
        acc = jax.lax.dot_general(
            oh, rhs, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)          # [B, PAD]
        out_ref[j * b:(j + 1) * b, :] += acc


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block"))
def _hist_pallas_call(binsT, rhs, *, num_bins, block):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    f, n = binsT.shape
    c = block
    nblk = n // c
    kernel = functools.partial(_hist_kernel, f=f, b=num_bins, c=c)
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((f, c), lambda i: (0, i)),
            pl.BlockSpec((c, _PAD), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f * num_bins, _PAD), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((f * num_bins, _PAD), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(binsT, rhs)


def _hist_kernel_hilo(binsT_ref, rhs_ref, out_ref, *, f, b, c):
    """hi/lo bf16 variant: rhs carries [hi || lo] bf16 halves whose products
    accumulate in f32 on the MXU — 2 bf16 passes instead of the 6 that
    Precision.HIGHEST costs on f32 inputs, at ~2^-17 relative input
    rounding (~16-17 mantissa bits)."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rhs = rhs_ref[...]                                   # [C, 2*PAD] bf16
    binsT = binsT_ref[...]                               # [F, C] int8
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (c, b), 1)
    for j in range(f):                                   # static unroll
        col = binsT[j, :].astype(jnp.int32)              # [C]
        oh = (col[:, None] == iota_b).astype(jnp.bfloat16)  # [C, B] in VMEM
        acc = jax.lax.dot_general(
            oh, rhs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [B, 2*PAD]
        out_ref[j * b:(j + 1) * b, :] += acc[:, :_PAD] + acc[:, _PAD:]


@functools.partial(jax.jit, static_argnames=("num_bins", "block"))
def _hist_pallas_call_hilo(binsT, rhs, *, num_bins, block):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    f, n = binsT.shape
    c = block
    nblk = n // c
    kernel = functools.partial(_hist_kernel_hilo, f=f, b=num_bins, c=c)
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((f, c), lambda i: (0, i)),
            pl.BlockSpec((c, 2 * _PAD), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f * num_bins, _PAD), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((f * num_bins, _PAD), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(binsT, rhs)


def histogram_tiles_pallas_hilo(binsT: jax.Array, stats: jax.Array,
                                leaf_ids: jax.Array, sel: jax.Array,
                                num_bins: int, block: int = 2048) -> jax.Array:
    """[P, F, B, S] histogram tile via the fused kernel, hi/lo bf16 matmuls.

    Numerically: each bf16 product against the exact 0/1 one-hot is the bf16
    input value itself, accumulated in f32; the recombined hi+lo sum carries
    ~16-17 mantissa bits of input precision (~2^-17 relative rounding) with
    exact counts — the fast-path precision model, comparable to (slightly
    coarser than) the reference GPU's float32 histograms (gpu_use_dp=false).
    The HIGHEST-precision kernel below is the precise alternative.
    """
    f, n = binsT.shape
    p = sel.shape[0]
    s = stats.shape[1]
    binsT, rhs, c = _prep_rhs(binsT, stats, leaf_ids, sel, block)
    rhs_hi = rhs.astype(jnp.bfloat16)
    rhs_lo = (rhs - rhs_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    rhs2 = jnp.concatenate([rhs_hi, rhs_lo], axis=1)     # [N, 2*PAD]
    out = _hist_pallas_call_hilo(binsT, rhs2, num_bins=num_bins, block=c)
    return out[:, :p * s].reshape(f, num_bins, p, s).transpose(2, 0, 1, 3)


def histogram_tiles_pallas(binsT: jax.Array, stats: jax.Array,
                           leaf_ids: jax.Array, sel: jax.Array,
                           num_bins: int, block: int = 2048) -> jax.Array:
    """[P, F, B, S] histogram tile via the fused kernel.

    Args mirror histogram.py histogram_tiles but take the FEATURE-MAJOR bin
    matrix [F, N] (contiguous per-feature rows for the kernel's block
    loads).
    """
    f, n = binsT.shape
    p = sel.shape[0]
    s = stats.shape[1]
    binsT, rhs, c = _prep_rhs(binsT, stats, leaf_ids, sel, block)
    out = _hist_pallas_call(binsT, rhs, num_bins=num_bins, block=c)
    return out[:, :p * s].reshape(f, num_bins, p, s).transpose(2, 0, 1, 3)


def _prep_rhs(binsT, stats, leaf_ids, sel, block):
    """Shared prep for both kernels: pad rows to the block size and build
    the f32 leaf-onehot x stat channel matrix [N, _PAD]."""
    f, n = binsT.shape
    p = sel.shape[0]
    s = stats.shape[1]
    assert p * s <= _PAD, (p, s)
    c = min(block, max(512, -(-n // 512) * 512))
    pad = -n % c
    if pad:
        binsT = jnp.pad(binsT, ((0, 0), (0, pad)))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))
        leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
    lo = (leaf_ids[:, None] == sel[None, :]).astype(jnp.float32)   # [N, P]
    rhs = (lo[:, :, None] * stats.astype(jnp.float32)[:, None, :]
           ).reshape(-1, p * s)
    rhs = jnp.pad(rhs, ((0, 0), (0, _PAD - p * s)))
    return binsT, rhs, c
