"""Per-leaf gradient-statistics histograms on device.

The TPU analog of the reference's histogram construction hot loop
(reference: src/io/dense_bin.hpp:98-141 ``ConstructHistogramInner`` on CPU and
src/treelearner/kernels/histogram_16_64_256.cu on CUDA). The data lives as a
dense binned matrix ``bins[N, F]`` and histograms are built for a TILE of
pending leaves in a single data pass keyed by ``(tile slot, feature, bin)``.

Backends (selected by ``method``):

- ``"onehot"`` (TPU default): scan over fixed-size row blocks; each block
  builds a transient bin one-hot ``[C, F*B]`` and a leaf-slot one-hot x stats
  ``[C, P*S]`` and contracts them on the MXU. No scatter at all — measured on
  v5e, XLA's scatter-add runs at ~0.06 G updates/s (sequential lowering)
  while this pass is memory/pipeline-bound at ~4 G elem/s nearly independent
  of the tile width P (the one-hot materialization dominates), which is why
  a tile of ~42 leaves costs the same as one. This is the TPU re-design of
  the CUDA sub-histogram kernels (histogram_16_64_256.cu:16-120): their
  shared-memory atomics become a dense one-hot contraction.
- ``"scatter"``: one flat scatter-add — the right backend on CPU hosts
  (tests, small data), pathological on TPU.
- ``"binloop"``: loop over bin values with masked einsum reductions; kept for
  small problems and cross-checks.

Accumulation is float32 (the reference CPU path uses float64 ``hist_t``
(bin.h:32); its GPU path defaults to float32 ``gpu_use_dp=false`` with
documented AUC parity (docs/GPU-Performance.rst:133-140) — we follow the GPU
precision model). Counts are accumulated exactly as a third channel rather
than re-derived from the hessian like the reference's
``RoundInt(hess * cnt_factor)`` (feature_histogram.hpp:869).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def histogram_scatter(bins: jax.Array, stats: jax.Array, leaf_ids: jax.Array,
                      num_leaves: int, num_bins: int) -> jax.Array:
    """Flat scatter-add histogram.

    Args:
      bins: [N, F] integer bin matrix.
      stats: [N, S] per-row statistics (grad, hess, count-weight); rows that
        must not contribute (inactive leaves, bagged-out) carry zeros.
      leaf_ids: [N] leaf slot of each row.
      num_leaves: number of leaf slots L (static).
      num_bins: bins per feature B (static).

    Returns:
      [L, F, B, S] float32 histogram.
    """
    n, f = bins.shape
    s = stats.shape[1]
    flat_idx = (leaf_ids[:, None].astype(jnp.int32) * f
                + jnp.arange(f, dtype=jnp.int32)[None, :]) * num_bins + bins.astype(jnp.int32)
    contrib = jnp.broadcast_to(stats.astype(jnp.float32)[:, None, :], (n, f, s))
    hist = jnp.zeros((num_leaves * f * num_bins, s), dtype=jnp.float32)
    hist = hist.at[flat_idx.reshape(-1)].add(contrib.reshape(-1, s))
    return hist.reshape(num_leaves, f, num_bins, s)


def histogram_binloop(bins: jax.Array, stats: jax.Array, leaf_onehot: jax.Array,
                      num_bins: int) -> jax.Array:
    """Histogram via a fori_loop over bin values (no scatter).

    ``leaf_onehot``: [N, L] float32 0/1 row-to-leaf assignment (already masked
    for inactive rows). For each bin value the row mask is a dense compare and
    the (leaf x stat) reduction is a matmul — the design swaps the CUDA
    kernel's shared-memory atomics (histogram_16_64_256.cu:16-120) for
    compare+matmul, which is how a TPU VPU/MXU wants this computation.

    Returns [L, F, B, S].
    """
    n, f = bins.shape
    l = leaf_onehot.shape[1]
    s = stats.shape[1]
    bins = bins.astype(jnp.int32)

    acc_dtype = jnp.result_type(stats.dtype, leaf_onehot.dtype, jnp.float32)

    def body(b, acc):
        mask = (bins == b).astype(acc_dtype)             # [N, F]
        out = jnp.einsum("nl,nf,ns->lfs", leaf_onehot, mask, stats,
                         preferred_element_type=acc_dtype)
        return acc.at[:, :, b, :].set(out)

    acc = jnp.zeros((l, f, num_bins, s), dtype=acc_dtype)
    return jax.lax.fori_loop(0, num_bins, body, acc)


@functools.partial(jax.jit, static_argnames=("num_leaves", "num_bins", "method"))
def build_histograms(bins: jax.Array, stats: jax.Array, leaf_ids: jax.Array,
                     num_leaves: int, num_bins: int,
                     method: str = "scatter") -> jax.Array:
    """Build [L, F, B, S] histograms for all leaf slots in one data pass."""
    if method == "scatter":
        return histogram_scatter(bins, stats, leaf_ids, num_leaves, num_bins)
    elif method == "binloop":
        onehot = jax.nn.one_hot(leaf_ids, num_leaves, dtype=jnp.float32)
        return histogram_binloop(bins, stats, onehot, num_bins)
    raise ValueError(f"unknown histogram method: {method}")


def oom_fallback_method(method: str) -> str:
    """Rung 2 of the OOM degradation ladder (models/gbdt.py
    _maybe_degrade_oom): the minimum-footprint formulation of the same
    histogram contraction. The Pallas kernels pin VMEM tiles and the
    onehot formulations materialize a transient [C, F*B] one-hot per row
    block; ``scatter`` allocates only the [L, F, B, S] output and updates
    it in place — slow on TPU (sequential lowering) but the smallest
    possible working set, which is the point of a degraded-but-alive run.
    Quantized methods keep their exact-integer accumulation via
    ``onehot_q8`` (scatter has no integer form — resolve_method's rule)."""
    if method.endswith("_q8"):
        return "onehot_q8"
    return "scatter"


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Histogram subtraction trick: sibling = parent - child
    (reference: serial_tree_learner.cpp:311-320, feature_histogram.hpp:79)."""
    return parent - child


def compact_indices(keep: jax.Array, size: int) -> jax.Array:
    """[size] int32 prefix-sum compaction of the ``keep`` rows' indices
    (original row order — jnp.nonzero is stable); padding slots carry N.
    This is the compaction ladder's row-index buffer: the Pallas gather
    kernel consumes it directly (pallas_hist fusion 2 — rows are gathered
    IN KERNEL and no compacted copy touches HBM), while the XLA backends
    expand it through compact_rows."""
    n = keep.shape[0]
    return jnp.nonzero(keep, size=size, fill_value=n)[0].astype(jnp.int32)


def compact_rows(bins: jax.Array | None, binsT: jax.Array | None,
                 stats: jax.Array, leaf_ids: jax.Array, keep: jax.Array,
                 size: int):
    """Prefix-sum compaction of the ``keep`` rows into statically-shaped
    padded buffers of ``size`` rows — the shape-static analog of the
    reference's permuted per-leaf row partition (data_partition.hpp:21-60):
    a tile pass over the compacted buffer costs O(size) instead of O(N).

    The kept rows land in ORIGINAL row order (jnp.nonzero is a stable
    prefix-sum compaction), so a scatter-add histogram over the buffer
    accumulates each cell's contributions in exactly the order of the
    full-N pass — bit-identical sums there; the matmul backends regroup
    partial sums (see the onehot scan) and match to accumulation-order
    tolerance like every other pass-shape change.

    Padded slots carry zero stats and leaf id -2, which matches no tile
    ``sel`` entry (active slots are >= 0, inactive -1), so every backend
    drops them. The caller guarantees ``sum(keep) <= size`` (the grower's
    ladder dispatch conditions on the pending row count).

    Args:
      bins: [N, F] row-major bin matrix or None (sparse-only datasets).
      binsT: [F, N] feature-major copy or None.
      stats: [N, S] per-row statistics (any accumulation dtype).
      leaf_ids: [N] int32 leaf slot per row.
      keep: [N] bool: row belongs to the tile's pending leaves.
      size: static output row count.

    Returns:
      (bins_c, binsT_c, stats_c, leaf_ids_c) with ``size`` rows each
      (None stays None).
    """
    n = leaf_ids.shape[0]
    idx = compact_indices(keep, size)
    ok = idx < n
    idxc = jnp.minimum(idx, n - 1)
    stats_c = jnp.where(ok[:, None], jnp.take(stats, idxc, axis=0),
                        jnp.zeros((), stats.dtype))
    leaf_ids_c = jnp.where(ok, jnp.take(leaf_ids, idxc), jnp.int32(-2))
    bins_c = None if bins is None else jnp.take(bins, idxc, axis=0)
    binsT_c = None if binsT is None else jnp.take(binsT, idxc, axis=1)
    return bins_c, binsT_c, stats_c, leaf_ids_c


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# (method, reasons) combinations already warned about — one warning per
# distinct degradation, not one per trace
_pallas_fallback_warned: set = set()


def resolve_method(method: str, deterministic: bool = False,
                   quantized: bool = False, interpret: bool = False) -> str:
    """Map ``histogram_method="auto"`` to the platform's fast backend
    (the analog of the reference's col-wise/row-wise auto benchmark,
    dataset.cpp:591-689 TestMultiThreadingMethod — here the choice is
    platform-structural: scatter-add is fast on CPU hosts and pathologically
    serialized on TPU, where the fused Pallas kernel is the primary path;
    measured on v5e at Higgs shape the ladder is
    pallas_q8 < pallas_hilo < pallas ~ onehot << scatter).

    ``pallas_hilo`` rounds grad/hess inputs to a hi+lo bf16 pair (~2^-17
    relative, vs f32's 2^-24) before the MXU contraction; near-tied split
    gains can therefore differ from a full-f32 run. ``deterministic=True``
    (the reference's reproducibility flag, config.h:166) keeps ``auto`` on
    the HIGHEST-precision kernel so results are stable across
    histogram-method choices at ~1.7x the pass cost.

    ``quantized=True`` (Config.quantized_grad, the end-to-end int8
    quantized-gradient training mode) maps the resolved method onto its
    q8 twin: the Pallas kernel on TPU, the XLA int8 contraction elsewhere
    (scatter/binloop have no integer-accumulation form — they resolve to
    onehot_q8 with a one-time note).

    ``interpret=True`` (Config.hist_pallas_interpret) keeps ``auto`` on the
    Pallas kernels OFF-TPU too, running them through the Pallas
    interpreter — the CPU test path for the production TPU pipeline.

    ``histogram_tiles`` falls back from a pallas method to the equivalent
    XLA onehot contraction when the kernel's preconditions don't hold
    (non-TPU backend without interpret, no feature-major bins, f64
    accumulation, or tile_leaves*stats exceeding the 128-lane group) and
    warns once per precondition."""
    on_kernel = jax.default_backend() == "tpu" or interpret
    if quantized:
        if method in ("auto", "pallas", "pallas_hilo", "pallas_q8"):
            return "pallas_q8" if on_kernel else "onehot_q8"
        if method in ("scatter", "binloop"):
            key = ("quantized_grad", method)
            if key not in _pallas_fallback_warned:
                _pallas_fallback_warned.add(key)
                from ..utils import log
                log.info(f"quantized_grad: histogram_method={method!r} has "
                         "no integer-accumulation form; using onehot_q8")
        return "onehot_q8"
    if method == "auto":
        if not on_kernel:
            return "scatter"
        return "pallas" if deterministic else "pallas_hilo"
    return method


# measured auto-selection cache: (F, B, log2-rows-bucket, has_binsT) -> method
_measured_method: dict = {}


def measured_auto_method(bins, binsT, num_bins: int, tile_leaves: int = 42,
                         hist_block: int = 0, sample_rows: int = 262144,
                         force_measure: bool = False) -> str:
    """TIME the candidate histogram backends on a sampled row block and
    return the fastest — the analog of the reference's col-wise/row-wise
    auto benchmark (dataset.cpp:591-689 TestMultiThreadingMethod), which
    measures rather than guesses because the ranking is shape-dependent.

    Candidates are the two production TPU formulations of the same
    contraction, ``pallas_hilo`` (fused VMEM kernel) and ``onehot_hilo``
    (XLA one-hot matmul); quantized/HIGHEST modes change numerics and are
    never auto-chosen. The winner is cached per (features, bins,
    log2-row bucket, binsT availability) so repeated Boosters on similar
    shapes skip the probe. Non-TPU backends return "scatter" without
    measuring (structurally fastest there); ``force_measure`` overrides
    for tests.
    """
    import time

    if jax.default_backend() != "tpu" and not force_measure:
        return "scatter"
    n, f = bins.shape
    key = (f, int(num_bins), max(n, 1).bit_length(), binsT is not None)
    hit = _measured_method.get(key)
    if hit is not None:
        return hit
    k = min(n, sample_rows)
    sub = bins[:k]
    subT = binsT[:, :k] if binsT is not None else None
    stats = jnp.ones((k, 3), jnp.float32)
    lid = jnp.zeros((k,), jnp.int32)
    p = max(1, min(tile_leaves, 42))
    sel = jnp.zeros((p,), jnp.int32).at[1:].set(-1)
    candidates = ["onehot_hilo"]
    if subT is not None:
        candidates.insert(0, "pallas_hilo")
    times = {}
    for m in candidates:
        fn = jax.jit(functools.partial(
            histogram_tiles, num_bins=num_bins, method=m,
            block=hist_block))
        try:
            r = fn(sub, stats, lid, sel, binsT=subT)
            float(jnp.sum(r))                  # compile + first run
            t0 = time.time()
            r = fn(sub, stats, lid, sel, binsT=subT)
            float(jnp.sum(r))                  # sync via scalar fetch
            times[m] = time.time() - t0
        except Exception:                      # kernel unsupported here
            continue
    if not times:
        return "onehot_hilo"
    winner = min(times, key=times.get)
    from ..utils import log
    log.info("histogram auto-selection: "
             + ", ".join(f"{m}={t * 1e3:.1f}ms" for m, t in times.items())
             + f" -> {winner} (at {k} sampled rows)")
    _measured_method[key] = winner
    return winner


def histogram_tiles(bins: jax.Array, stats: jax.Array, leaf_ids: jax.Array,
                    sel: jax.Array, num_bins: int, method: str = "onehot",
                    block: int = 0, dtype=jnp.float32,
                    binsT: jax.Array | None = None,
                    gather_idx: jax.Array | None = None,
                    interpret: bool = False) -> jax.Array:
    """Histograms for a TILE of leaves.

    Slot ``p`` of the output accumulates the rows whose ``leaf_ids`` equals
    ``sel[p]``; ``sel`` entries < 0 are inactive slots (zero output). This is
    the unit the grower calls once per tile round — on TPU its cost is nearly
    independent of the tile width, so one call covers up to ~42 pending
    leaves.

    Args:
      bins: [N, F] integer bin matrix.
      stats: [N, S] per-row statistics (grad, hess, count-weight), already
        masked for bagging.
      leaf_ids: [N] leaf slot of each row.
      sel: [P] int32 leaf ids selected into this tile (-1 = inactive slot).
      num_bins: bins per feature B (static).
      gather_idx: optional [M] int32 compacted row-index buffer
        (compact_indices output; entries >= N are padding). The Pallas
        kernels consume it directly — rows are gathered IN KERNEL from the
        HBM-resident arrays (pallas_hist fusion 2) and the pass covers M
        instead of N rows. Non-Pallas backends (and Pallas fallbacks)
        expand it into compacted copies first, which is what the ladder
        did before the fusion.
      interpret: run Pallas kernels through the interpreter (CPU test
        path, Config.hist_pallas_interpret); ignored by XLA backends.

    Returns:
      [P, F, B, S] float32 histogram.
    """
    n, f = bins.shape if bins is not None else binsT.shape[::-1]
    p = sel.shape[0]
    s = stats.shape[1]

    if method in ("pallas", "pallas_hilo", "pallas_q8"):
        # the fused kernel needs: real TPU lowering (or the interpreter),
        # the feature-major bin matrix, f32 accumulation, and the tile x
        # stat channels within one 128-lane group; otherwise run the XLA
        # onehot formulation of the same contraction. ``reasons`` IS the
        # gate: empty means every precondition holds, so the warning can
        # never disagree with it.
        reasons = []
        if jax.default_backend() != "tpu" and not interpret:
            reasons.append(f"backend is {jax.default_backend()!r}, not tpu "
                           "(set hist_pallas_interpret=true to emulate)")
        if binsT is None:
            reasons.append("feature-major bin matrix (binsT) unavailable")
        if not (dtype == jnp.float32 or method == "pallas_q8"):
            reasons.append(f"accumulation dtype {jnp.dtype(dtype).name} "
                           "(kernel is f32-only)")
        if p * s > 128:
            reasons.append(f"tile_leaves*stats = {p}*{s} = {p * s} > 128 "
                           "lanes (lower tile_leaves)")
        if not reasons:
            from . import pallas_hist
            kmode = {"pallas": "highest", "pallas_hilo": "hilo",
                     "pallas_q8": "q8"}[method]
            return pallas_hist.histogram_tiles_pallas_mode(
                binsT, stats, leaf_ids, sel, num_bins,
                block=block or 2048, mode=kmode, idx=gather_idx,
                interpret=interpret and jax.default_backend() != "tpu")
        # an explicitly requested kernel silently degrading to the XLA
        # formulation is a large perf cliff — name the violated
        # precondition once so the user can tell why
        key = (method, tuple(reasons))
        if key not in _pallas_fallback_warned:
            _pallas_fallback_warned.add(key)
            from ..utils import log
            log.warning(
                f"histogram_method={method!r} fell back to the XLA onehot "
                f"formulation: {'; '.join(reasons)}")
        method = {"pallas": "onehot", "pallas_hilo": "onehot_hilo",
                  "pallas_q8": "onehot_q8"}[method]

    if gather_idx is not None:
        # XLA backends can't gather in kernel: expand the index buffer into
        # compacted copies (exactly what the pre-fusion ladder did) and run
        # the pass over those
        ok = gather_idx < n
        idxc = jnp.minimum(gather_idx, n - 1)
        stats = jnp.where(ok[:, None], jnp.take(stats, idxc, axis=0),
                          jnp.zeros((), stats.dtype))
        leaf_ids = jnp.where(ok, jnp.take(leaf_ids, idxc), jnp.int32(-2))
        bins = None if bins is None else jnp.take(bins, idxc, axis=0)
        binsT = None if binsT is None else jnp.take(binsT, idxc, axis=1)
        n = gather_idx.shape[0]

    if method in ("onehot", "onehot_hilo", "onehot_q8"):
        # "onehot_q8": int8 MXU contraction for QUANTIZED stats (the
        # opt-in quantized-gradient mode, see grower.py): stats arrive as
        # int8 channels, the one-hot is exact in int8, products accumulate
        # in int32 — exact integer histograms the caller dequantizes
        q8 = method == "onehot_q8"
        hilo = method == "onehot_hilo" and dtype == jnp.float32
        c = min(block or 16384, _round_up(max(n, 1), 512))
        pad = _round_up(n, c) - n
        if pad:
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            stats = jnp.pad(stats, ((0, pad), (0, 0)))
            leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
        nblk = (n + pad) // c
        iota_b = jnp.arange(num_bins, dtype=jnp.int32)

        def body(acc, xs):
            b, st, lid = xs
            oh_bool = (b.astype(jnp.int32)[:, :, None] == iota_b[None, None, :])
            if q8:
                oh = oh_bool.astype(jnp.int8).reshape(c, f * num_bins)
                rhs = jnp.where((lid[:, None] == sel[None, :])[:, :, None],
                                st[:, None, :], jnp.int8(0)).reshape(c, p * s)
                h = jax.lax.dot_general(oh, rhs, (((0,), (0,)), ((), ())),
                                        preferred_element_type=jnp.int32)
                return acc + h, None
            lo = (lid[:, None] == sel[None, :]).astype(dtype)  # [C, P]
            rhs = (lo[:, :, None] * st.astype(dtype)[:, None, :]
                   ).reshape(c, p * s)
            if hilo:
                # hi/lo bf16 decomposition: the one-hot side is exact in
                # bf16 (0/1) and the stat side is split into two bf16 parts
                # whose matmul contributions accumulate in f32 on the MXU —
                # 2 bf16 passes instead of the 6 that Precision.HIGHEST
                # costs on f32 inputs. Inputs round at ~2^-17 relative
                # (hi+lo carries ~16-17 mantissa bits vs f32's 24); sums
                # accumulate in f32 either way. Comparable precision model
                # to the reference GPU's float32 histograms
                # (gpu_use_dp=false, docs/GPU-Performance.rst:133-140),
                # with slightly coarser input rounding; counts are exact
                # (0/1 in bf16).
                from .pallas_hist import split_hilo
                oh = oh_bool.astype(jnp.bfloat16).reshape(c, f * num_bins)
                h2 = jax.lax.dot_general(oh, split_hilo(rhs),
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
                h = h2[:, :p * s] + h2[:, p * s:]
            else:
                oh = oh_bool.astype(dtype).reshape(c, f * num_bins)
                # HIGHEST precision: TPU matmuls otherwise truncate inputs to
                # bf16, corrupting grad/hess sums ~0.5% (the one-hot side is
                # exact either way; counts accumulate exactly in f32
                # regardless)
                h = jax.lax.dot_general(oh, rhs, (((0,), (0,)), ((), ())),
                                        precision=jax.lax.Precision.HIGHEST,
                                        preferred_element_type=dtype)
            return acc + h, None

        acc_dtype = jnp.int32 if q8 else dtype
        h, _ = jax.lax.scan(
            body, jnp.zeros((f * num_bins, p * s), acc_dtype),
            (bins.reshape(nblk, c, f), stats.reshape(nblk, c, s),
             leaf_ids.reshape(nblk, c)))
        return h.reshape(f, num_bins, p, s).transpose(2, 0, 1, 3)

    # slot index per row: position of its leaf in sel, or P (dropped)
    eq = leaf_ids[:, None] == sel[None, :]                        # [N, P]
    if method == "scatter":
        slot = jnp.where(jnp.any(eq, axis=1),
                         jnp.argmax(eq, axis=1).astype(jnp.int32),
                         jnp.int32(p))
        flat_idx = (slot[:, None] * f
                    + jnp.arange(f, dtype=jnp.int32)[None, :]) * num_bins \
            + bins.astype(jnp.int32)
        contrib = jnp.broadcast_to(stats.astype(dtype)[:, None, :],
                                   (n, f, s))
        hist = jnp.zeros(((p + 1) * f * num_bins, s), dtype=dtype)
        hist = hist.at[flat_idx.reshape(-1)].add(contrib.reshape(-1, s))
        return hist.reshape(p + 1, f, num_bins, s)[:p]
    elif method == "binloop":
        onehot = eq.astype(dtype)
        return histogram_binloop(bins, stats.astype(dtype), onehot, num_bins)
    raise ValueError(f"unknown histogram method: {method}")


def epilogue_supported(method: str, binsT, p: int, s: int, dtype,
                       interpret: bool = False) -> bool:
    """Whether the IN-KERNEL form of the split epilogue can run (same
    preconditions as the plain pallas kernels). When False,
    histogram_tiles_with_candidates runs the XLA twin of the identical
    epilogue math instead — the fused-search path works on every backend,
    only the kernel fusion degrades."""
    if method not in ("pallas", "pallas_hilo", "pallas_q8"):
        return False
    if jax.default_backend() != "tpu" and not interpret:
        return False
    if binsT is None or p * s > 128 or s != 3:
        return False
    return dtype == jnp.float32 or method == "pallas_q8"


def histogram_tiles_with_candidates(bins, stats, leaf_ids, sel, derive,
                                    parent_planes, leaf_aux, fmeta, pvec,
                                    num_bins, method: str = "onehot",
                                    block: int = 0, dtype=jnp.float32,
                                    binsT=None, gather_idx=None,
                                    interpret: bool = False,
                                    with_monotone: bool = False,
                                    q_scale=None):
    """Histogram tile pass + fused split-finding epilogue.

    The frontier-batched unit of the ``split_fusion`` grower path: one
    launch histograms the tile's COMPUTED leaves (even slots), derives
    each derived sibling's plane as parent - computed (odd slots, static
    lane shift in kernel / slot roll in XLA), and reduces every
    (leaf, feature) to its best numerical split candidate
    (ops/split.py numerical_candidates). On the Pallas methods the whole
    epilogue runs IN KERNEL (pallas_hist.histogram_tiles_pallas_epilogue)
    and only the candidate table + the parent-needed planes leave VMEM;
    every other backend runs the SAME jnp ops on the tile it built —
    bit-identical tables by construction (the parity suite pins it).

    Args mirror histogram_tiles plus the epilogue pack (see
    histogram_tiles_pallas_epilogue). Returns (tile [P, F, B, S] f32
    with derived planes filled in, cand [P, F, CAND_CHANNELS]).
    """
    from . import pallas_hist

    p = sel.shape[0]
    s = stats.shape[1]
    if epilogue_supported(method, binsT, p, s, dtype, interpret):
        kmode = {"pallas": "highest", "pallas_hilo": "hilo",
                 "pallas_q8": "q8"}[method]
        return pallas_hist.histogram_tiles_pallas_epilogue(
            binsT, stats, leaf_ids, sel, derive, parent_planes, leaf_aux,
            fmeta, pvec, num_bins, block=block or 2048, mode=kmode,
            idx=gather_idx,
            interpret=interpret and jax.default_backend() != "tpu",
            with_monotone=with_monotone, q_scale=q_scale)

    # XLA twin: build the computed slots' planes with the requested
    # backend, then the identical derive + scan at plane level
    sel_compute = jnp.where(derive, -1, sel)
    tile = histogram_tiles(bins, stats, leaf_ids, sel_compute, num_bins,
                           method=method, block=block, dtype=dtype,
                           binsT=binsT, gather_idx=gather_idx,
                           interpret=interpret)
    return derive_and_scan(tile, derive, parent_planes, leaf_aux, fmeta,
                           pvec, q8=method.endswith("_q8"),
                           q_scale=q_scale, with_monotone=with_monotone)


def derive_and_scan(tile, derive, parent_planes, leaf_aux, fmeta, pvec, *,
                    q8: bool = False, q_scale=None,
                    with_monotone: bool = False):
    """The XLA twin of the in-kernel split epilogue, at plane level:
    dequantize (q8, fenced), derive the odd slots' planes as
    parent - computed-sibling (slot roll == the kernel's static lane
    shift), scan each slot to its best per-feature candidates. The
    grower calls this ONCE per tile pass, OUTSIDE the compaction-rung
    lax.cond — the rung branches return only the tile, so the scan
    compiles once per grower instead of once per rung."""
    from . import pallas_hist
    from .split import _round_fence, numerical_candidates

    params = pallas_hist._epilogue_params(pvec.astype(jnp.float32))
    if q8:
        # fence the dequant product before the sibling subtraction —
        # same reason as the kernel epilogue (see _epilogue_compute):
        # an FMA-contracted multiply-sub would break ladder invariance
        tile = _round_fence(
            tile.astype(jnp.float32) * q_scale[None, None, None, :],
            params)
    else:
        tile = tile.astype(jnp.float32)
    shifted = jnp.concatenate([jnp.zeros_like(tile[:1]), tile[:-1]], axis=0)
    full = jnp.where(derive[:, None, None, None],
                     parent_planes.astype(jnp.float32) - shifted, tile)
    la = leaf_aux.astype(jnp.float32)
    fm = fmeta.astype(jnp.float32)
    cand = numerical_candidates(
        full, la[:, 0], la[:, 1], la[:, 2], la[:, 3],
        fm[:, 0].astype(jnp.int32), fm[:, 1].astype(jnp.int32),
        fm[:, 2].astype(jnp.int32), fm[:, 3].astype(jnp.int32),
        params, with_monotone=with_monotone,
        leaf_min=la[:, 4], leaf_max=la[:, 5])
    return full, cand
