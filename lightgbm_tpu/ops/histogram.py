"""Per-leaf gradient-statistics histograms on device.

The TPU analog of the reference's histogram construction hot loop
(reference: src/io/dense_bin.hpp:98-141 ``ConstructHistogramInner`` on CPU and
src/treelearner/kernels/histogram_16_64_256.cu on CUDA). Instead of
scatter-adds with atomics, the data lives as a dense binned matrix
``bins[N, F]`` and histograms are built for ALL pending leaves in a single
pass keyed by ``(leaf, feature, bin)``.

Backends (selected by ``method``):

- ``"scatter"``: one flat XLA scatter-add. Exact, portable; XLA lowers it to
  sort+segment-sum on TPU. Reference semantics but no atomics.
- ``"binloop"``: loop over bin values with masked einsum reductions — turns
  the scatter into ``B`` dense compare+matmul steps (VPU/MXU friendly, no
  scatter at all).

Accumulation is float32 (the reference CPU path uses float64 ``hist_t``
(bin.h:32); its GPU path defaults to float32 ``gpu_use_dp=false`` with
documented AUC parity (docs/GPU-Performance.rst:133-140) — we follow the GPU
precision model). Counts are accumulated exactly as a third channel rather
than re-derived from the hessian like the reference's
``RoundInt(hess * cnt_factor)`` (feature_histogram.hpp:869).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def histogram_scatter(bins: jax.Array, stats: jax.Array, leaf_ids: jax.Array,
                      num_leaves: int, num_bins: int) -> jax.Array:
    """Flat scatter-add histogram.

    Args:
      bins: [N, F] integer bin matrix.
      stats: [N, S] per-row statistics (grad, hess, count-weight); rows that
        must not contribute (inactive leaves, bagged-out) carry zeros.
      leaf_ids: [N] leaf slot of each row.
      num_leaves: number of leaf slots L (static).
      num_bins: bins per feature B (static).

    Returns:
      [L, F, B, S] float32 histogram.
    """
    n, f = bins.shape
    s = stats.shape[1]
    flat_idx = (leaf_ids[:, None].astype(jnp.int32) * f
                + jnp.arange(f, dtype=jnp.int32)[None, :]) * num_bins + bins.astype(jnp.int32)
    contrib = jnp.broadcast_to(stats.astype(jnp.float32)[:, None, :], (n, f, s))
    hist = jnp.zeros((num_leaves * f * num_bins, s), dtype=jnp.float32)
    hist = hist.at[flat_idx.reshape(-1)].add(contrib.reshape(-1, s))
    return hist.reshape(num_leaves, f, num_bins, s)


def histogram_binloop(bins: jax.Array, stats: jax.Array, leaf_onehot: jax.Array,
                      num_bins: int) -> jax.Array:
    """Histogram via a fori_loop over bin values (no scatter).

    ``leaf_onehot``: [N, L] float32 0/1 row-to-leaf assignment (already masked
    for inactive rows). For each bin value the row mask is a dense compare and
    the (leaf x stat) reduction is a matmul — the design swaps the CUDA
    kernel's shared-memory atomics (histogram_16_64_256.cu:16-120) for
    compare+matmul, which is how a TPU VPU/MXU wants this computation.

    Returns [L, F, B, S].
    """
    n, f = bins.shape
    l = leaf_onehot.shape[1]
    s = stats.shape[1]
    bins = bins.astype(jnp.int32)

    def body(b, acc):
        mask = (bins == b).astype(jnp.float32)           # [N, F]
        out = jnp.einsum("nl,nf,ns->lfs", leaf_onehot, mask, stats,
                         preferred_element_type=jnp.float32)
        return acc.at[:, :, b, :].set(out)

    acc = jnp.zeros((l, f, num_bins, s), dtype=jnp.float32)
    return jax.lax.fori_loop(0, num_bins, body, acc)


@functools.partial(jax.jit, static_argnames=("num_leaves", "num_bins", "method"))
def build_histograms(bins: jax.Array, stats: jax.Array, leaf_ids: jax.Array,
                     num_leaves: int, num_bins: int,
                     method: str = "scatter") -> jax.Array:
    """Build [L, F, B, S] histograms for all leaf slots in one data pass."""
    if method == "scatter":
        return histogram_scatter(bins, stats, leaf_ids, num_leaves, num_bins)
    elif method == "binloop":
        onehot = jax.nn.one_hot(leaf_ids, num_leaves, dtype=jnp.float32)
        return histogram_binloop(bins, stats, onehot, num_bins)
    raise ValueError(f"unknown histogram method: {method}")


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Histogram subtraction trick: sibling = parent - child
    (reference: serial_tree_learner.cpp:311-320, feature_histogram.hpp:79)."""
    return parent - child
