"""Vectorized best-split search over histograms.

TPU-native re-design of the reference's per-feature threshold scan
(reference: src/treelearner/feature_histogram.hpp:858-1050
``FindBestThresholdSequentially`` and the gain/output formulas at
feature_histogram.hpp:737-856). Where the reference runs a sequential
two-direction scan per feature inside OpenMP, here cumulative sums over the
bin axis evaluate EVERY (leaf, feature, direction, threshold) candidate at
once, then a masked lexicographic argmax reproduces the reference's
first-better-wins tie ordering.

Semantics carried over exactly:

- gain  = GetLeafGain(left) + GetLeafGain(right) compared against
  ``min_gain_shift = GetLeafGain(parent) + min_gain_to_split`` (strict ``>``),
  with stored gain = best_gain - min_gain_shift
  (feature_histogram.hpp:103-112, 934-944).
- leaf output = -ThresholdL1(sum_g, l1) / (sum_h + l2), clipped to
  ±max_delta_step, then path-smoothed toward the parent output
  (feature_histogram.hpp:737-764 CalculateSplittedLeafOutput).
- missing handling (feature_histogram.hpp:166-213 FuncForNumricalL3 dispatch):
  * num_bin > 2 and MissingType::Zero  -> two scans, default bin skipped from
    both accumulations and from the threshold candidates (SKIP_DEFAULT_BIN).
  * num_bin > 2 and MissingType::NaN   -> two scans, NaN bin (last bin)
    excluded from directional accumulation so its mass rides with the default
    direction (NA_AS_MISSING).
  * otherwise -> single reverse scan; default_left=False forced for NaN
    (feature_histogram.hpp:199-210).
  Reverse scan => missing goes left (default_left=True); forward scan =>
  missing goes right.
- the accumulated direction's hessian starts at kEpsilon
  (feature_histogram.hpp:882 ``sum_right_hessian = kEpsilon``).
- min_data_in_leaf / min_sum_hessian_in_leaf validity masks
  (feature_histogram.hpp:904-917).

Deviation from the reference: counts come from an exactly-accumulated count
channel instead of ``RoundInt(hess * num_data / sum_hessian)``
(feature_histogram.hpp:869, 898) — exact counts, same constraint semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_EPSILON = 1e-15          # reference: include/LightGBM/meta.h kEpsilon
K_MIN_SCORE = -jnp.inf     # reference: kMinScore


class FeatureMeta(NamedTuple):
    """Per-feature static metadata arrays, all shape [F]."""
    num_bins: jax.Array        # int32, total bins incl. NaN bin
    missing_type: jax.Array    # int32, MISSING_{NONE,ZERO,NAN}
    default_bin: jax.Array     # int32, bin of value 0.0
    is_categorical: jax.Array  # bool
    monotone: jax.Array        # int8, -1/0/+1 (0 = unconstrained)
    penalty: jax.Array         # float32 feature_contri gain multiplier


class SplitParams(NamedTuple):
    """Split hyperparameters (dynamic scalars so param changes don't recompile)."""
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    max_delta_step: jax.Array
    path_smooth: jax.Array
    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    min_gain_to_split: jax.Array
    cat_l2: jax.Array
    cat_smooth: jax.Array
    max_cat_threshold: jax.Array
    min_data_per_group: jax.Array
    max_cat_to_onehot: jax.Array
    monotone_penalty: jax.Array
    cegb_tradeoff: jax.Array
    cegb_penalty_split: jax.Array

    @classmethod
    def from_config(cls, config) -> "SplitParams":
        f32 = jnp.float32
        return cls(
            lambda_l1=f32(config.lambda_l1),
            lambda_l2=f32(config.lambda_l2),
            max_delta_step=f32(config.max_delta_step),
            path_smooth=f32(config.path_smooth),
            min_data_in_leaf=f32(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=f32(config.min_sum_hessian_in_leaf),
            min_gain_to_split=f32(config.min_gain_to_split),
            cat_l2=f32(config.cat_l2),
            cat_smooth=f32(config.cat_smooth),
            max_cat_threshold=jnp.int32(config.max_cat_threshold),
            min_data_per_group=f32(config.min_data_per_group),
            max_cat_to_onehot=jnp.int32(config.max_cat_to_onehot),
            monotone_penalty=f32(config.monotone_penalty),
            cegb_tradeoff=f32(config.cegb_tradeoff),
            cegb_penalty_split=f32(config.cegb_penalty_split),
        )


class BundleMeta(NamedTuple):
    """Per-(column, bin) EFB segment structure (bundling.py layout). For a
    bundle column, bin ``b`` inside member ``f``'s range has ``seg_lo/seg_hi``
    = that range's first/last bin; bins outside any member range (bundle bin
    0) carry lo = hi = 0. Regular columns: lo = 0, hi = num_bin - 1 (which
    makes the generalized directional sums reduce to the plain ones).
    ``fwd_ok/rev_ok`` restrict threshold candidates per scan direction so
    the bundle scan evaluates exactly the member feature's unbundled
    candidate set (each original threshold once, with the member's
    most-frequent mass — reconstructed from the leaf totals — on the side
    its bin order dictates); built host-side in
    basic.py _build_feature_meta_bundled.

    ``pref_fwd/pref_rev`` are the per-(column, bin, direction) TIE-BREAK
    keys (higher wins among equal-gain candidates), built so the bundled
    argmax reproduces the UNBUNDLED lexicographic order exactly: ordered
    by the candidate's ORIGINAL owner feature (lowest index wins — a
    bundle column interleaves several features' bins, so the plain
    column-major preference would resolve a within-bundle tie to the
    highest-offset member instead of the lowest feature, silently growing
    a different tree than the unbundled run), then by the owner's own scan
    direction and threshold order."""
    seg_lo: jax.Array        # int32 [F, B]
    seg_hi: jax.Array        # int32 [F, B]
    is_bundle: jax.Array     # bool [F]
    fwd_ok: jax.Array        # bool [F, B]
    rev_ok: jax.Array        # bool [F, B]
    pref_fwd: jax.Array      # int32 [F, B]
    pref_rev: jax.Array      # int32 [F, B]


class SplitInfo(NamedTuple):
    """Per-leaf best split, struct-of-arrays of shape [L]
    (reference: src/treelearner/split_info.hpp:22-90)."""
    gain: jax.Array          # f32; -inf when unsplittable
    feature: jax.Array       # int32 inner feature index
    threshold: jax.Array     # int32 bin threshold (left: bin <= threshold)
    default_left: jax.Array  # bool, direction for missing values
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    left_count: jax.Array    # f32 (weighted count channel)
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    is_cat: jax.Array        # bool, categorical (bitset) split
    cat_bitset: jax.Array    # uint32[L, CAT_WORDS] categorical membership (0 when numerical)
    seg_lo: jax.Array        # int32 [L]; EFB bundle segment start (-1 regular)
    seg_hi: jax.Array        # int32 [L]; EFB bundle segment end (inclusive)


CAT_BITSET_WORDS = 8  # default width (256 bins); widened when max_bin > 256


def threshold_l1(s: jax.Array, l1: jax.Array) -> jax.Array:
    """reference: feature_histogram.hpp:737-741 ThresholdL1."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def _round_fence(x: jax.Array, p: "SplitParams") -> jax.Array:
    """Value-preserving rounding fence for the gain math (the
    models/gbdt.py _fma_guard idiom): bitcast to the matching integer
    width, XOR with a runtime-zero salt the compiler cannot fold, bitcast
    back. XLA contracts a multiply feeding an add into an FMA whose
    single rounding drifts 1 ulp — and WHICH adds it contracts depends on
    the surrounding program, so the same gain expression compiled in two
    places (the classic split phase vs the fused tile epilogue, or either
    side of a compaction-rung lax.cond) can disagree in the last bit.
    Fencing each product before it enters an add pins the two-rounding
    sequence everywhere, which is what makes the split_fusion bit-parity
    contract (and the classic path's own cross-context stability) hold.
    The salt ``l2 != l2`` is zero unless lambda_l2 is NaN — runtime data
    the simplifier cannot prove constant."""
    itype = jnp.uint64 if x.dtype == jnp.float64 else jnp.uint32
    salt = (p.lambda_l2 != p.lambda_l2).astype(itype)
    xi = jax.lax.bitcast_convert_type(x, itype)
    return jax.lax.bitcast_convert_type(jnp.bitwise_xor(xi, salt), x.dtype)


def calculate_leaf_output(sum_g, sum_h, p: SplitParams, num_data, parent_output,
                          lambda_l2=None):
    """reference: feature_histogram.hpp:743-764 CalculateSplittedLeafOutput."""
    l2 = p.lambda_l2 if lambda_l2 is None else lambda_l2
    ret = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + l2)
    ret = jnp.where((p.max_delta_step > 0) & (jnp.abs(ret) > p.max_delta_step),
                    jnp.sign(ret) * p.max_delta_step, ret)
    use_smooth = p.path_smooth > K_EPSILON
    n_over_s = num_data / jnp.where(use_smooth, p.path_smooth, 1.0)
    # the product rounds concretely before the add (_round_fence): the
    # smoothing multiply-add is FMA-contraction-prone and must compute
    # the same bits in every compilation context (classic phase, fused
    # epilogue, compaction-rung branches); the division term cannot
    # contract and needs no fence
    smoothed = (_round_fence(ret * (n_over_s / (n_over_s + 1.0)), p)
                + parent_output / (n_over_s + 1.0))
    return jnp.where(use_smooth, smoothed, ret)


def leaf_gain_given_output(sum_g, sum_h, output, p: SplitParams, lambda_l2=None):
    """reference: feature_histogram.hpp:846-856 GetLeafGainGivenOutput.

    Both products pass the rounding fence before the add — see
    _round_fence: the gain must compute the same bits wherever this
    expression is compiled (classic split phase, fused tile epilogue,
    either side of a compaction-rung cond)."""
    l2 = p.lambda_l2 if lambda_l2 is None else lambda_l2
    sg = threshold_l1(sum_g, p.lambda_l1)
    return -(_round_fence(2.0 * sg * output, p)
             + _round_fence((sum_h + l2) * output * output, p))


def leaf_gain(sum_g, sum_h, p: SplitParams, num_data, parent_output, lambda_l2=None):
    """reference: feature_histogram.hpp:826-843 GetLeafGain. Always routed
    through the output (identical to the closed form when no clipping/smoothing)."""
    out = calculate_leaf_output(sum_g, sum_h, p, num_data, parent_output, lambda_l2)
    return leaf_gain_given_output(sum_g, sum_h, out, p, lambda_l2)


def _directional_sums(hist_excl, leaf_sum_g, leaf_sum_h, leaf_cnt,
                      bundle: BundleMeta | None = None):
    """Cumulative left/right sums for every threshold, both directions.

    hist_excl: [L, F, B, 3] histogram with excluded bins zeroed.
    Returns dict with fwd/rev (accumulated-side eps added like the reference).
    Threshold t means: left = bins <= t (accumulated side fwd), right = bins > t.

    With ``bundle``, the accumulated side is SEGMENT-relative: an EFB bundle
    column interleaves many features' bin ranges, so the left mass at
    threshold t inside member f's range is csum[t] - csum[seg_lo-1] and the
    reverse-scan right mass is csum[seg_hi] - csum[t]. The complement side
    comes from the leaf totals, which automatically assigns every
    out-of-segment row (the member's most-frequent/default mass and the
    other members' rows) to the scan's default direction — the same
    total-minus-accumulated reconstruction as the reference's FixHistogram
    (dataset.cpp) + SKIP_DEFAULT_BIN scans.
    """
    csum = jnp.cumsum(hist_excl, axis=2)                       # [L, F, B, 3]
    total_excl = csum[:, :, -1:, :]
    if bundle is None:
        # forward: left accumulates bins 0..t
        fwd_left = csum
        # reverse: right accumulates bins t+1..B-1 (of the non-excluded mass)
        rev_right = total_excl - csum
    else:
        lo = bundle.seg_lo[None, :, :, None]                   # [1, F, B, 1]
        hi = bundle.seg_hi[None, :, :, None]
        lo_b = jnp.broadcast_to(jnp.maximum(lo - 1, 0), csum.shape)
        hi_b = jnp.broadcast_to(hi, csum.shape)
        csum_lo = jnp.where(lo > 0,
                            jnp.take_along_axis(csum, lo_b, axis=2), 0.0)
        csum_hi = jnp.take_along_axis(csum, hi_b, axis=2)
        fwd_left = csum - csum_lo
        rev_right = csum_hi - csum
    lt = dict(
        fwd_left_g=fwd_left[..., 0], fwd_left_h=fwd_left[..., 1] + K_EPSILON,
        fwd_left_c=fwd_left[..., 2],
        rev_right_g=rev_right[..., 0], rev_right_h=rev_right[..., 1] + K_EPSILON,
        rev_right_c=rev_right[..., 2],
    )
    # complement side from the leaf's TRUE totals (includes missing mass):
    b = (leaf_sum_g[:, None, None], leaf_sum_h[:, None, None], leaf_cnt[:, None, None])
    lt["fwd_right_g"] = b[0] - lt["fwd_left_g"]
    lt["fwd_right_h"] = b[1] - lt["fwd_left_h"]
    lt["fwd_right_c"] = b[2] - lt["fwd_left_c"]
    lt["rev_left_g"] = b[0] - lt["rev_right_g"]
    lt["rev_left_h"] = b[1] - lt["rev_right_h"]
    lt["rev_left_c"] = b[2] - lt["rev_right_c"]
    return lt


def _leaf_gain_nosmooth(sum_g, sum_h, p: SplitParams, lambda_l2):
    """Leaf gain with NO path smoothing (the reference's categorical
    min_gain_shift when path_smooth is off, feature_histogram.hpp:296-302:
    GetLeafGain with parent_output=0)."""
    sg = threshold_l1(sum_g, p.lambda_l1)
    out = -sg / (sum_h + lambda_l2)
    out = jnp.where((p.max_delta_step > 0) & (jnp.abs(out) > p.max_delta_step),
                    jnp.sign(out) * p.max_delta_step, out)
    return -(_round_fence(2.0 * sg * out, p)
             + _round_fence((sum_h + lambda_l2) * out * out, p))


def find_best_cat_splits(hist: jax.Array, leaf_sum_g, leaf_sum_h, leaf_cnt,
                         leaf_output, leaf_depth, meta: FeatureMeta,
                         p: SplitParams, feature_mask: jax.Array,
                         max_depth: int = -1,
                         cat_words: int = CAT_BITSET_WORDS,
                         gain_adjust=None):
    """Best categorical split per leaf over all categorical features.

    Vectorized re-design of the reference's categorical threshold search
    (reference: feature_histogram.hpp:277-515
    FindBestThresholdCategoricalInner). Two modes, chosen per feature:

    - one-hot (num_bin <= max_cat_to_onehot): every bin t in [1, nb) is a
      one-vs-rest candidate, gain with plain lambda_l2.
    - sorted many-vs-many: bins with count >= cat_smooth are sorted by
      grad/(hess + cat_smooth); candidates take the first i+1 sorted bins
      from either end (two directions), with l2 += cat_l2, the
      min_data_per_group group counter, and max_cat_threshold cap.

    Candidate axes are evaluated all at once as [L, F, 3, B] gains
    (mode-slots: one-hot / dir+1 / dir-1); a lexicographic argmax reproduces
    the reference's first-better-wins evaluation order.

    Returns (gain[L], feature[L], left sums..., bitset[L, CAT_WORDS]).
    """
    L, F, B, _ = hist.shape
    g = hist[..., 0]
    h = hist[..., 1]
    c = hist[..., 2]
    nb = meta.num_bins[None, :]                                 # [1, F]
    bins = jnp.arange(B, dtype=jnp.int32)[None, None, :]        # [1, 1, B]
    in_range = (bins >= 1) & (bins < nb[:, :, None])            # bin 0 = other/NaN
    G = leaf_sum_g[:, None]
    H = leaf_sum_h[:, None]
    C = leaf_cnt[:, None]
    parent_out = leaf_output[:, None, None]

    use_onehot = (meta.num_bins <= p.max_cat_to_onehot)[None, :]   # [1, F]
    l2_sorted = p.lambda_l2 + p.cat_l2

    # min_gain_shift (feature_histogram.hpp:291-305): smoothing uses the
    # parent's actual output; otherwise plain-l2 leaf gain with no smoothing
    use_smooth = p.path_smooth > K_EPSILON
    shift_smooth = leaf_gain_given_output(leaf_sum_g, leaf_sum_h, leaf_output, p)
    shift_plain = _leaf_gain_nosmooth(leaf_sum_g, leaf_sum_h, p, p.lambda_l2)
    min_gain_shift = (jnp.where(use_smooth, shift_smooth, shift_plain)
                      + p.min_gain_to_split)[:, None, None]       # [L, 1, 1]

    def split_gain(lg, lh, lc, l2):
        rg, rh, rc = G[:, :, None] - lg, H[:, :, None] - lh, C[:, :, None] - lc
        lo = calculate_leaf_output(lg, lh, p, lc, parent_out, l2)
        ro = calculate_leaf_output(rg, rh, p, rc, parent_out, l2)
        return (leaf_gain_given_output(lg, lh, lo, p, l2)
                + leaf_gain_given_output(rg, rh, ro, p, l2))

    # ---- one-hot candidates: left = single bin t (hess + eps)
    oh_lg, oh_lh, oh_lc = g, h + K_EPSILON, c
    oh_gain = split_gain(oh_lg, oh_lh, oh_lc, p.lambda_l2)
    oh_ok = (in_range
             & (c >= p.min_data_in_leaf) & (h >= p.min_sum_hessian_in_leaf)
             & (C[:, :, None] - c >= p.min_data_in_leaf)
             & (H[:, :, None] - h - K_EPSILON >= p.min_sum_hessian_in_leaf))

    # ---- sorted candidates
    valid = in_range & (c >= p.cat_smooth)                       # count filter
    ratio = jnp.where(valid, g / (h + p.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=2)                           # stable; invalid last
    sg = jnp.take_along_axis(jnp.where(valid, g, 0.0), order, axis=2)
    sh = jnp.take_along_axis(jnp.where(valid, h, 0.0), order, axis=2)
    sc = jnp.take_along_axis(jnp.where(valid, c, 0.0), order, axis=2)
    csum_g = jnp.cumsum(sg, axis=2)
    csum_h = jnp.cumsum(sh, axis=2)
    csum_c = jnp.cumsum(sc, axis=2)
    used_bin = valid.sum(axis=2).astype(jnp.int32)               # [L, F]
    max_num_cat = jnp.minimum(p.max_cat_threshold, (used_bin + 1) // 2)

    idx = jnp.arange(B, dtype=jnp.int32)[None, None, :]
    # dir +1: left = first i+1 sorted bins
    fw_lg, fw_lh, fw_lc = csum_g, csum_h + K_EPSILON, csum_c
    # dir -1: left = last i+1 valid sorted bins = total_valid - csum[ub-2-i]
    j = used_bin[:, :, None] - 2 - idx
    jc = jnp.clip(j, 0, B - 1)
    tot_g, tot_h, tot_c = csum_g[:, :, -1:], csum_h[:, :, -1:], csum_c[:, :, -1:]
    bw_lg = tot_g - jnp.where(j >= 0, jnp.take_along_axis(csum_g, jc, axis=2), 0.0)
    bw_lh = tot_h - jnp.where(j >= 0, jnp.take_along_axis(csum_h, jc, axis=2), 0.0) + K_EPSILON
    bw_lc = tot_c - jnp.where(j >= 0, jnp.take_along_axis(csum_c, jc, axis=2), 0.0)

    cand_ok_base = (idx < used_bin[:, :, None]) & (idx < max_num_cat[:, :, None])

    def sorted_guards(lh_, lc_):
        rc = C[:, :, None] - lc_
        rh = H[:, :, None] - lh_
        return ((lc_ >= p.min_data_in_leaf) & (lh_ >= p.min_sum_hessian_in_leaf)
                & (rc >= p.min_data_in_leaf) & (rc >= p.min_data_per_group)
                & (rh >= p.min_sum_hessian_in_leaf))

    # group counter (feature_histogram.hpp:443-447): cnt accumulates along the
    # scan and resets when a candidate is emitted — a sequential recurrence,
    # run as a lax.scan over the (small) bin axis with [L, F] lanes
    def group_scan(per_bin_cnt, eligible):
        def step(carry, xs):
            cnt_i, elig_i = xs
            acc = carry + cnt_i
            emit = elig_i & (acc >= p.min_data_per_group)
            return jnp.where(emit, 0.0, acc), emit
        xs = (jnp.moveaxis(per_bin_cnt, 2, 0), jnp.moveaxis(eligible, 2, 0))
        _, emits = jax.lax.scan(step, jnp.zeros(per_bin_cnt.shape[:2]), xs)
        return jnp.moveaxis(emits, 0, 2)

    fw_elig = cand_ok_base & sorted_guards(fw_lh, fw_lc)
    bw_elig = cand_ok_base & sorted_guards(bw_lh, bw_lc)
    # per-candidate cnt along each direction's scan order
    bw_cnt = jnp.where(j + 1 >= 0,
                       jnp.take_along_axis(sc, jnp.clip(j + 1, 0, B - 1), axis=2),
                       0.0)
    fw_ok = group_scan(sc, fw_elig)
    bw_ok = group_scan(bw_cnt, bw_elig)

    fw_gain = split_gain(fw_lg, fw_lh, fw_lc, l2_sorted)
    bw_gain = split_gain(bw_lg, bw_lh, bw_lc, l2_sorted)

    # ---- assemble [L, F, 3, B]: slot 0 one-hot, 1 dir+1, 2 dir-1
    fmask = feature_mask
    if fmask.ndim == 1:
        fmask = fmask[None, :]
    base_ok = (fmask.astype(bool) & meta.is_categorical)[:, :, None]  # [L|1, F, 1]
    if max_depth > 0:
        base_ok = base_ok & (leaf_depth < max_depth)[:, None, None]

    oh_val = oh_ok & base_ok & use_onehot[:, :, None]
    so_val = base_ok & ~use_onehot[:, :, None]

    # adjusted "key" gains: stored gain x feature contri - CEGB delta
    # (matches the numerical path; monotone never applies to categoricals)
    contri = meta.penalty[None, :, None]

    def keyed(gain, valid):
        key = (gain - min_gain_shift) * contri
        if gain_adjust is not None:
            key = key - gain_adjust[:, :, None]
        return jnp.where(valid, key, K_MIN_SCORE)

    gains = jnp.stack([
        keyed(oh_gain, oh_val & (oh_gain > min_gain_shift)),
        keyed(fw_gain, so_val & fw_ok & (fw_gain > min_gain_shift)),
        keyed(bw_gain, so_val & bw_ok & (bw_gain > min_gain_shift)),
    ], axis=2)                                                   # [L, F, 3, B]

    # lexicographic argmax: features in index order, then evaluation order
    # (one-hot t asc | dir+1 i asc | dir-1 i asc), strict-greater-wins
    farange = jnp.arange(F, dtype=jnp.int32)[None, :, None, None]
    slot_pref = jnp.asarray([3 * B, 2 * B, B], jnp.int32)[None, None, :, None]
    pref = ((F - 1) - farange) * (8 * B) + slot_pref - idx[:, :, None, :]
    flat_gains = gains.reshape(L, -1)
    best_gain = jnp.max(flat_gains, axis=1)
    is_best = flat_gains == best_gain[:, None]
    best_idx = jnp.argmax(jnp.where(
        is_best, jnp.broadcast_to(pref, gains.shape).reshape(L, -1), -1), axis=1)

    bf = (best_idx // (3 * B)).astype(jnp.int32)
    rem = best_idx % (3 * B)
    bmode = (rem // B).astype(jnp.int32)                         # 0/1/2
    bi = (rem % B).astype(jnp.int32)

    li = jnp.arange(L)

    def pick3(a0, a1, a2):
        v0 = a0[li, bf, bi]
        v1 = a1[li, bf, bi]
        v2 = a2[li, bf, bi]
        return jnp.where(bmode == 0, v0, jnp.where(bmode == 1, v1, v2))

    left_g = pick3(oh_lg, fw_lg, bw_lg)
    left_h = pick3(oh_lh, fw_lh, bw_lh)
    left_c = pick3(oh_lc, fw_lc, bw_lc)

    # ---- membership bitset over bins for the chosen candidate
    order_rows = order[li, bf]                                   # [L, B]
    rank = jnp.argsort(order_rows, axis=1).astype(jnp.int32)     # bin -> sort pos
    ub_rows = used_bin[li, bf][:, None]
    bins_row = jnp.arange(B, dtype=jnp.int32)[None, :]
    member_oh = bins_row == bi[:, None]
    member_fw = rank <= bi[:, None]
    member_bw = (rank >= ub_rows - 1 - bi[:, None]) & (rank < ub_rows)
    member = jnp.where((bmode == 0)[:, None], member_oh,
                       jnp.where((bmode == 1)[:, None], member_fw, member_bw))
    # restrict to in-range bins of the chosen feature
    nb_rows = meta.num_bins[bf][:, None]
    member = member & (bins_row >= 1) & (bins_row < nb_rows)
    pad = (-B) % 32
    if pad:
        member = jnp.pad(member, ((0, 0), (0, pad)))
    mw = member.reshape(L, -1, 32).astype(jnp.uint32)
    words = (mw << jnp.arange(32, dtype=jnp.uint32)[None, None, :]).sum(
        axis=2, dtype=jnp.uint32)
    nwords = words.shape[1]
    if nwords < cat_words:
        words = jnp.pad(words, ((0, 0), (0, cat_words - nwords)))
    else:
        assert nwords == cat_words, (
            f"bitset width {nwords} exceeds cat_words={cat_words}")

    l2_out = jnp.where(use_onehot[0, bf], p.lambda_l2, l2_sorted)
    return (best_gain.astype(jnp.float32), bf, left_g, left_h, left_c,
            words, l2_out)


# ------------------------------------------------- fused split epilogue
#
# The split-finding epilogue of the fused Pallas histogram pipeline
# (ops/pallas_hist.py): after the kernel's last grid step accumulates a
# tile's histogram planes in VMEM, the NUMERICAL threshold scan below runs
# in-kernel and reduces each (leaf, feature) to one best candidate — only
# the tiny [P, F, CAND_CHANNELS] table returns to the grower's split
# phase, never the [F, B, S] planes. The same function is the XLA twin
# for the non-Pallas backends (models/grower.py tile_pass under
# ``split_fusion``), so the two paths are the SAME jnp ops on the same
# plane values — bit-identical tables by construction.
#
# Division of labor with find_best_splits (which stays the one place for
# categorical / EFB-bundle / forced-split / CEGB / extra_trees semantics;
# the grower only enables the fused path when none of those apply):
#   in the scan (per-bin, must precede the per-feature reduction):
#     missing-type bin exclusion, both-direction cumulative sums, gains
#     with l1/l2/max_delta_step/path_smooth, basic-monotone clip +
#     violation zeroing, min_data/min_hessian masks, threshold-range
#     masks, strict gain > min_gain_shift, NaN rejection, and the
#     reference's within-feature tie order (reverse scan first, highest
#     threshold; forward strictly-greater, lowest threshold).
#   deferred to candidates_to_splitinfo (whole-feature/whole-leaf
#     multiplicative or masking transforms that cannot change the
#     within-feature argmax): feature_contri, the monotone depth penalty,
#     feature_mask/interaction masks, the max_depth gate, and the
#     cross-feature lowest-index-wins argmax — applied in exactly the
#     order find_best_splits applies them, so a fused and a classic run
#     pick the same candidate with the same stored gain bits.

# candidate-table channel layout ([..., CAND_CHANNELS] float32): gain is
# the SHIFTED raw gain (gain - min_gain_shift; K_MIN_SCORE = invalid),
# threshold/is_rev stored as exact small-integer floats. 12 channels (10
# used + 2 pad) keep the per-leaf table at exactly 1/(B/4) of the
# [F, B, 3] plane bytes the classic search streams — the ISSUE 12
# acceptance floor, asserted from the REAL returned buffers in
# kernel_bench and the fusion tests
CAND_CHANNELS = 12
CAND_GAIN, CAND_THR, CAND_REV = 0, 1, 2
CAND_LG, CAND_LH, CAND_LC = 3, 4, 5
CAND_RG, CAND_RH, CAND_RC = 6, 7, 8


def numerical_candidates(hist, leaf_sum_g, leaf_sum_h, leaf_cnt, leaf_output,
                         num_bins_f, missing_type_f, default_bin_f,
                         monotone_f, p: SplitParams, *,
                         with_monotone: bool = False,
                         leaf_min=None, leaf_max=None) -> jax.Array:
    """Per-(leaf, feature) best numerical split candidate.

    The kernel-callable core of find_best_splits' numerical scan (same
    ops in the same order — the fused-vs-classic bit-parity suite pins
    the agreement): evaluates every (direction, threshold) with the full
    validity mask set and reduces each feature to its best candidate
    under the reference's within-feature tie order.

    Args:
      hist: [P, F, B, 3] float32 histogram planes (excluded bins NOT yet
        zeroed — done here, like find_best_splits).
      leaf_sum_g/h/cnt/output: [P] leaf aggregates for the tile's slots.
      num_bins_f/missing_type_f/default_bin_f/monotone_f: [F] int32 (the
        FeatureMeta columns, passed as bare arrays so the Pallas kernel
        can load them from a packed f32 input).
      p: SplitParams (only the 7 numerical-scan fields are read, so the
        kernel can rebuild it from a scalar vector).
      with_monotone: static; basic-mode [P] output bounds.

    Returns:
      [P, F, CAND_CHANNELS] float32 candidate table (see CAND_*).
    """
    P, F, B, _ = hist.shape
    nb = num_bins_f[None, :, None]
    bins = jnp.arange(B, dtype=jnp.int32)[None, None, :]

    mode_a = (num_bins_f > 2) & (missing_type_f != MISSING_NONE)
    is_nan = missing_type_f == MISSING_NAN
    is_zero = missing_type_f == MISSING_ZERO

    excl = jnp.zeros((1, F, B), dtype=bool)
    excl = excl | (mode_a & is_nan)[None, :, None] & (bins == nb - 1)
    excl = excl | ((mode_a & is_zero)[None, :, None]
                   & (bins == default_bin_f[None, :, None]))
    hist_excl = jnp.where(excl[:, :, :, None], 0.0, hist)

    s = _directional_sums(hist_excl, leaf_sum_g, leaf_sum_h, leaf_cnt)
    parent_out = leaf_output[:, None, None]

    def clip_out(out):
        if not with_monotone:
            return out
        return jnp.clip(out, leaf_min[:, None, None], leaf_max[:, None, None])

    def split_gain_dir(prefix):
        lg, lh, lc = (s[f"{prefix}_left_g"], s[f"{prefix}_left_h"],
                      s[f"{prefix}_left_c"])
        rg, rh, rc = (s[f"{prefix}_right_g"], s[f"{prefix}_right_h"],
                      s[f"{prefix}_right_c"])
        lo = clip_out(calculate_leaf_output(lg, lh, p, lc, parent_out))
        ro = clip_out(calculate_leaf_output(rg, rh, p, rc, parent_out))
        gain = (leaf_gain_given_output(lg, lh, lo, p)
                + leaf_gain_given_output(rg, rh, ro, p))
        if with_monotone:
            mono = monotone_f[None, :, None]
            viol = (((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro)))
            gain = jnp.where(viol, 0.0, gain)
        return gain

    gain_fwd = split_gain_dir("fwd")
    gain_rev = split_gain_dir("rev")

    min_gain_shift = (leaf_gain(leaf_sum_g, leaf_sum_h, p, leaf_cnt,
                                leaf_output)
                      + p.min_gain_to_split)[:, None, None]

    def constraint_mask(prefix):
        lh, lc = s[f"{prefix}_left_h"], s[f"{prefix}_left_c"]
        rh, rc = s[f"{prefix}_right_h"], s[f"{prefix}_right_c"]
        return ((lc >= p.min_data_in_leaf) & (rc >= p.min_data_in_leaf)
                & (lh >= p.min_sum_hessian_in_leaf)
                & (rh >= p.min_sum_hessian_in_leaf))

    thr_ok_common = bins <= nb - 2
    fwd_ok = mode_a[None, :, None] & thr_ok_common
    rev_upper = nb - 2 - (mode_a & is_nan)[None, :, None].astype(jnp.int32)
    rev_ok = bins <= rev_upper
    zero_thr_skip = ((mode_a & is_zero)[None, :, None]
                     & (bins == default_bin_f[None, :, None]))
    fwd_ok = fwd_ok & ~zero_thr_skip
    rev_ok = rev_ok & ~zero_thr_skip

    valid_fwd = (constraint_mask("fwd") & fwd_ok
                 & (gain_fwd > min_gain_shift) & ~jnp.isnan(gain_fwd))
    valid_rev = (constraint_mask("rev") & rev_ok
                 & (gain_rev > min_gain_shift) & ~jnp.isnan(gain_rev))

    key_fwd = jnp.where(valid_fwd, gain_fwd - min_gain_shift, K_MIN_SCORE)
    key_rev = jnp.where(valid_rev, gain_rev - min_gain_shift, K_MIN_SCORE)

    # within-feature lexicographic reduction (the reference's scan order:
    # reverse runs first and keeps the highest-threshold maximum, forward
    # replaces only on strictly greater gain, lowest threshold first) —
    # the [2, B] preference values match find_best_splits' tpref exactly
    gains = jnp.stack([key_rev, key_fwd], axis=2)            # [P, F, 2, B]
    pref = jnp.stack([2 * B + bins, (B - 1) - bins],
                     axis=2)                                  # [1, 1, 2, B]
    flat = gains.reshape(P, F, 2 * B)
    best = jnp.max(flat, axis=2)
    is_best = flat == best[..., None]
    pref_b = jnp.broadcast_to(pref, gains.shape).reshape(P, F, 2 * B)
    bidx = jnp.argmax(jnp.where(is_best, pref_b, -1), axis=2)
    bdir = (bidx // B).astype(jnp.int32)                     # 0=rev, 1=fwd
    bt = (bidx % B).astype(jnp.int32)

    def pick(rev_name, fwd_name):
        rv = jnp.take_along_axis(s[rev_name], bt[:, :, None], axis=2)[..., 0]
        fv = jnp.take_along_axis(s[fwd_name], bt[:, :, None], axis=2)[..., 0]
        return jnp.where(bdir == 0, rv, fv)

    out = jnp.zeros((P, F, CAND_CHANNELS), jnp.float32)
    out = out.at[:, :, CAND_GAIN].set(best.astype(jnp.float32))
    out = out.at[:, :, CAND_THR].set(bt.astype(jnp.float32))
    out = out.at[:, :, CAND_REV].set((bdir == 0).astype(jnp.float32))
    out = out.at[:, :, CAND_LG].set(pick("rev_left_g", "fwd_left_g"))
    out = out.at[:, :, CAND_LH].set(pick("rev_left_h", "fwd_left_h"))
    out = out.at[:, :, CAND_LC].set(pick("rev_left_c", "fwd_left_c"))
    out = out.at[:, :, CAND_RG].set(pick("rev_right_g", "fwd_right_g"))
    out = out.at[:, :, CAND_RH].set(pick("rev_right_h", "fwd_right_h"))
    out = out.at[:, :, CAND_RC].set(pick("rev_right_c", "fwd_right_c"))
    return out


def candidates_to_splitinfo(cand, leaf_sum_g, leaf_sum_h, leaf_cnt,
                            leaf_output, leaf_depth, meta: FeatureMeta,
                            p: SplitParams, feature_mask, max_depth: int = -1,
                            cat_words: int = CAT_BITSET_WORDS,
                            with_monotone: bool = False,
                            leaf_min=None, leaf_max=None) -> SplitInfo:
    """Cross-feature argmax over a candidate table -> per-leaf SplitInfo.

    Applies the transforms find_best_splits folds into its keyed gains —
    feature_contri, the monotone depth penalty, feature/depth masking —
    in the same order, then the cross-feature lowest-index-wins argmax
    (the reference's in-order feature loop with strict operator>). The
    candidates' within-feature selection already happened in the scan, so
    only whole-feature transforms that COMMUTE with it are legal here:
    the contri multiplier commutes only when positive (the reference
    itself applies penalty post-scan, feature_histogram.hpp:94, but
    find_best_splits applies it per bin — the gbdt resolver keeps
    non-positive feature_contri on the classic phase), and the monotone
    depth penalty is floored at K_EPSILON > 0. The fused-vs-classic
    bit-parity suite pins the equivalence.

    Args:
      cand: [P, F, CAND_CHANNELS] from numerical_candidates.
      feature_mask: [P, F] bool/float validity.
    """
    P, F, _ = cand.shape
    raw = cand[:, :, CAND_GAIN]
    valid = jnp.isfinite(raw)
    contri = meta.penalty[None, :]
    mono_pen = monotone_split_penalty(leaf_depth, p)[:, None]
    is_mono = (meta.monotone != 0)[None, :]
    key = raw * contri
    key = jnp.where(is_mono, key * mono_pen, key)

    fmask = feature_mask.astype(bool) & ~meta.is_categorical[None, :]
    depth_ok = (jnp.ones((P,), bool) if max_depth <= 0
                else (leaf_depth < max_depth))
    key = jnp.where(valid & fmask & depth_ok[:, None], key, K_MIN_SCORE)

    best_gain = jnp.max(key, axis=1)
    is_best = key == best_gain[:, None]
    fpref = (F - 1) - jnp.arange(F, dtype=jnp.int32)[None, :]
    bf = jnp.argmax(jnp.where(is_best, fpref, -1), axis=1).astype(jnp.int32)

    li = jnp.arange(P)
    row = cand[li, bf]                                       # [P, CAND]
    bt = row[:, CAND_THR].astype(jnp.int32)
    bdir_rev = row[:, CAND_REV] > 0.5
    left_g, left_h, left_c = row[:, CAND_LG], row[:, CAND_LH], row[:, CAND_LC]
    right_g, right_h, right_c = (row[:, CAND_RG], row[:, CAND_RH],
                                 row[:, CAND_RC])

    left_out = calculate_leaf_output(left_g, left_h, p, left_c, leaf_output)
    right_out = calculate_leaf_output(right_g, right_h, p, right_c,
                                      leaf_output)
    if with_monotone:
        left_out = jnp.clip(left_out, leaf_min, leaf_max)
        right_out = jnp.clip(right_out, leaf_min, leaf_max)

    mode_a = (meta.num_bins > 2) & (meta.missing_type != MISSING_NONE)
    nan_single = ((meta.missing_type == MISSING_NAN) & ~mode_a)[bf]
    default_left = bdir_rev & ~nan_single

    return SplitInfo(
        gain=best_gain.astype(jnp.float32),
        feature=bf,
        threshold=bt,
        default_left=default_left,
        left_sum_g=left_g, left_sum_h=left_h, left_count=left_c,
        right_sum_g=right_g, right_sum_h=right_h, right_count=right_c,
        left_output=left_out, right_output=right_out,
        is_cat=jnp.zeros((P,), dtype=bool),
        cat_bitset=jnp.zeros((P, cat_words), dtype=jnp.uint32),
        seg_lo=jnp.full((P,), -1, jnp.int32),
        seg_hi=jnp.full((P,), -1, jnp.int32),
    )


def monotone_split_penalty(leaf_depth, p: SplitParams):
    """Depth-decaying gain multiplier for splits on monotone features
    (reference: monotone_constraints.hpp:355-364)."""
    d = leaf_depth.astype(jnp.float32)
    pen = p.monotone_penalty
    small = 1.0 - pen / jnp.exp2(d) + K_EPSILON
    large = 1.0 - jnp.exp2(pen - 1.0 - d) + K_EPSILON
    out = jnp.where(pen <= 1.0, small, large)
    out = jnp.where(pen >= d + 1.0, K_EPSILON, out)
    return jnp.where(pen > 0.0, out, 1.0)


def sync_best_splits(info: SplitInfo, axis_name: str) -> SplitInfo:
    """Allreduce-argmax of per-leaf best splits across a mesh axis — the SPMD
    analog of the reference's SyncUpGlobalBestSplit allreduce over serialized
    SplitInfo blobs (reference: parallel_tree_learner.h:191-214; reducer
    keeps the destination on ties, i.e. the lower rank wins). Used by the
    feature-parallel learner where each device searched its own feature
    slice."""
    gathered = jax.tree.map(
        lambda x: jax.lax.all_gather(x, axis_name), info)   # [D, L, ...]
    gains = gathered.gain                                   # [D, L]
    ndev = gains.shape[0]
    # winner = max gain; ties -> lowest device rank (strict-greater reducer)
    order = jnp.arange(ndev, dtype=jnp.int32)[:, None]
    best_gain = jnp.max(gains, axis=0)
    is_best = gains == best_gain[None, :]
    win = jnp.argmax(jnp.where(is_best, ndev - order, 0), axis=0)  # [L]
    li = jnp.arange(gains.shape[1])
    return jax.tree.map(lambda x: x[win, li], gathered)


def per_feature_best_gain_key(gains_rev: jax.Array, gains_fwd: jax.Array
                              ) -> jax.Array:
    """Best adjusted gain per (leaf, feature) over all numerical candidates
    — the quantity the voting-parallel learner votes on (reference:
    voting_parallel_tree_learner.cpp:137-150 local gains for GlobalVoting)."""
    return jnp.maximum(jnp.max(gains_rev, axis=2), jnp.max(gains_fwd, axis=2))


def find_best_splits(hist: jax.Array, leaf_sum_g, leaf_sum_h, leaf_cnt,
                     leaf_output, leaf_depth, meta: FeatureMeta, p: SplitParams,
                     feature_mask: jax.Array, max_depth: int = -1,
                     with_categorical: bool = False,
                     cat_words: int = CAT_BITSET_WORDS,
                     leaf_min=None, leaf_max=None,
                     adv_bounds=None,
                     gain_adjust=None, rand_bin=None,
                     bundle: BundleMeta | None = None,
                     return_feature_gains: bool = False):
    """Best split per leaf over all numerical features.

    Args:
      hist: [L, F, B, 3] (grad, hess, count).
      leaf_sum_g/h/cnt/output/depth: [L] current leaf aggregates.
      feature_mask: [F] or [L, F] float/bool validity (col sampling,
        per-node sampling, interaction constraints).
      max_depth: static; leaves at max_depth get gain -inf (reference:
        serial_tree_learner.cpp BeforeFindBestSplit depth guard).
      leaf_min/leaf_max: [L] monotone output bounds; when set (static),
        candidate outputs are clipped and monotone-violating candidates
        rejected (reference: feature_histogram.hpp:766-824 GetSplitGains
        with USE_MC + BasicConstraint clip).
      adv_bounds: optional (lmin, lmax, rmin, rmax) [L, F, B] per-threshold
        child output bounds for the ADVANCED monotone mode (reference:
        CumulativeFeatureConstraint Get{Left,Right}{Min,Max} per threshold,
        monotone_constraints.hpp:144-259); overrides the [L] clip for the
        numerical search.
      gain_adjust: [L, F] additive penalty subtracted from the stored gain
        (the CEGB delta, cost_effective_gradient_boosting.hpp:66-84).
      rand_bin: [L, F] int32 forced random threshold for extra_trees
      (feature_histogram.hpp USE_RAND): only that bin is a candidate.
    Returns SplitInfo with arrays of shape [L].
    """
    L, F, B, _ = hist.shape
    nb = meta.num_bins[None, :, None]                      # [1, F, 1]
    bins = jnp.arange(B, dtype=jnp.int32)[None, None, :]   # [1, 1, B]

    mode_a = (meta.num_bins > 2) & (meta.missing_type != MISSING_NONE)   # [F]
    is_nan = meta.missing_type == MISSING_NAN
    is_zero = meta.missing_type == MISSING_ZERO

    excl = jnp.zeros((1, F, B), dtype=bool)
    excl = excl | (mode_a & is_nan)[None, :, None] & (bins == nb - 1)
    excl = excl | (mode_a & is_zero)[None, :, None] & (bins == meta.default_bin[None, :, None])
    hist_excl = jnp.where(excl[:, :, :, None], 0.0, hist)

    s = _directional_sums(hist_excl, leaf_sum_g, leaf_sum_h, leaf_cnt, bundle)

    parent_out = leaf_output[:, None, None]

    use_mc = leaf_min is not None or adv_bounds is not None

    def clip_out(out, side):
        if adv_bounds is not None:
            lmin_a, lmax_a, rmin_a, rmax_a = adv_bounds
            mn, mx = ((lmin_a, lmax_a) if side == "left"
                      else (rmin_a, rmax_a))
            return jnp.clip(out, mn, mx)
        if leaf_min is None:
            return out
        return jnp.clip(out, leaf_min[:, None, None], leaf_max[:, None, None])

    def split_gain_dir(prefix):
        lg, lh, lc = s[f"{prefix}_left_g"], s[f"{prefix}_left_h"], s[f"{prefix}_left_c"]
        rg, rh, rc = s[f"{prefix}_right_g"], s[f"{prefix}_right_h"], s[f"{prefix}_right_c"]
        lo = clip_out(calculate_leaf_output(lg, lh, p, lc, parent_out), "left")
        ro = clip_out(calculate_leaf_output(rg, rh, p, rc, parent_out), "right")
        gain = (leaf_gain_given_output(lg, lh, lo, p)
                + leaf_gain_given_output(rg, rh, ro, p))
        if use_mc:
            mono = meta.monotone[None, :, None].astype(jnp.int32)
            viol = (((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro)))
            gain = jnp.where(viol, 0.0, gain)   # GetSplitGains returns 0
        return gain

    gain_fwd = split_gain_dir("fwd")
    gain_rev = split_gain_dir("rev")

    min_gain_shift = (leaf_gain(leaf_sum_g, leaf_sum_h, p, leaf_cnt, leaf_output)
                      + p.min_gain_to_split)[:, None, None]

    def constraint_mask(prefix):
        lh, lc = s[f"{prefix}_left_h"], s[f"{prefix}_left_c"]
        rh, rc = s[f"{prefix}_right_h"], s[f"{prefix}_right_c"]
        return ((lc >= p.min_data_in_leaf) & (rc >= p.min_data_in_leaf)
                & (lh >= p.min_sum_hessian_in_leaf) & (rh >= p.min_sum_hessian_in_leaf))

    valid_fwd = constraint_mask("fwd")
    valid_rev = constraint_mask("rev")

    # threshold-range masks (see module docstring for the scan ranges)
    thr_ok_common = bins <= nb - 2
    fwd_ok = mode_a[None, :, None] & thr_ok_common
    rev_upper = nb - 2 - (mode_a & is_nan)[None, :, None].astype(jnp.int32)
    rev_ok = bins <= rev_upper
    zero_thr_skip = (mode_a & is_zero)[None, :, None] & (bins == meta.default_bin[None, :, None])
    fwd_ok = fwd_ok & ~zero_thr_skip
    rev_ok = rev_ok & ~zero_thr_skip
    if bundle is not None:
        # bundle columns: per-bin direction masks reproduce each member's
        # unbundled candidate set exactly (see BundleMeta docstring)
        isb = bundle.is_bundle[None, :, None]
        fwd_ok = jnp.where(isb, bundle.fwd_ok[None, :, :], fwd_ok)
        rev_ok = jnp.where(isb, bundle.rev_ok[None, :, :], rev_ok)
    if rand_bin is not None:   # extra_trees: only the random threshold
        rb = rand_bin[:, :, None]
        fwd_ok = fwd_ok & (bins == rb)
        rev_ok = rev_ok & (bins == rb)

    fmask = feature_mask
    if fmask.ndim == 1:
        fmask = fmask[None, :]
    fmask = (fmask.astype(bool) & ~meta.is_categorical)[..., None]   # [L|1, F, 1]

    depth_ok = jnp.ones((L,), dtype=bool) if max_depth <= 0 else (leaf_depth < max_depth)
    base_ok = fmask & depth_ok[:, None, None]

    valid_fwd = valid_fwd & fwd_ok & base_ok & (gain_fwd > min_gain_shift) & ~jnp.isnan(gain_fwd)
    valid_rev = valid_rev & rev_ok & base_ok & (gain_rev > min_gain_shift) & ~jnp.isnan(gain_rev)

    # ---- adjusted "key" gains: the stored gain after per-feature contri
    # multiplier (feature_histogram.hpp:94 output->gain *= meta->penalty),
    # minus the CEGB delta (serial_tree_learner.cpp:740-744), times the
    # monotone penalty (serial_tree_learner.cpp:745-749). Cross-feature and
    # cross-leaf comparisons all happen on these adjusted gains.
    contri = meta.penalty[None, :, None]
    mono_pen = monotone_split_penalty(leaf_depth, p)[:, None, None]
    is_mono = (meta.monotone != 0)[None, :, None]

    def keyed(gain, valid):
        key = (gain - min_gain_shift) * contri
        if gain_adjust is not None:
            key = key - gain_adjust[:, :, None]
        key = jnp.where(is_mono, key * mono_pen, key)
        return jnp.where(valid, key, K_MIN_SCORE)

    gain_fwd = keyed(gain_fwd, valid_fwd)
    gain_rev = keyed(gain_rev, valid_rev)

    # ---- lexicographic argmax reproducing the reference's scan tie order:
    # reverse scan runs first and keeps the first (=highest-threshold) maximum;
    # forward replaces only on strictly greater gain (lowest threshold first).
    # Across features: lowest feature index wins ties
    # (serial_tree_learner.cpp:374-448 feature loop with strict operator>).
    gains = jnp.stack([gain_rev, gain_fwd], axis=2)          # [L, F, 2, B]
    if bundle is not None:
        # bundled datasets: host-built preference tables ordered by each
        # candidate's ORIGINAL owner feature + its unbundled scan order,
        # so gain ties resolve exactly as the unbundled run's would (see
        # BundleMeta docstring)
        pref = jnp.stack([bundle.pref_rev, bundle.pref_fwd],
                         axis=1)[None]                       # [1, F, 2, B]
    else:
        farange = jnp.arange(F, dtype=jnp.int32)[None, :, None, None]
        tpref = jnp.stack([bins, (B - 1) - bins], axis=2)    # rev: high t; fwd: low t
        pref = ((F - 1) - farange) * (4 * B) + jnp.stack(
            [jnp.full_like(bins, 2 * B), jnp.zeros_like(bins)], axis=2) + tpref

    flat_gains = gains.reshape(L, -1)
    best_gain = jnp.max(flat_gains, axis=1)
    is_best = flat_gains == best_gain[:, None]
    flat_pref = jnp.broadcast_to(pref, gains.shape).reshape(L, -1)
    best_idx = jnp.argmax(jnp.where(is_best, flat_pref, -1), axis=1)

    bf = (best_idx // (2 * B)).astype(jnp.int32)             # feature
    rem = best_idx % (2 * B)
    bdir = (rem // B).astype(jnp.int32)                      # 0=rev, 1=fwd
    bt = (rem % B).astype(jnp.int32)                         # threshold bin

    li = jnp.arange(L)

    def pick(rev_name, fwd_name):
        rev_v = s[rev_name][li, bf, bt]
        fwd_v = s[fwd_name][li, bf, bt]
        return jnp.where(bdir == 0, rev_v, fwd_v)

    left_g = pick("rev_left_g", "fwd_left_g")
    left_h = pick("rev_left_h", "fwd_left_h")
    left_c = pick("rev_left_c", "fwd_left_c")
    right_g = pick("rev_right_g", "fwd_right_g")
    right_h = pick("rev_right_h", "fwd_right_h")
    right_c = pick("rev_right_c", "fwd_right_c")

    left_out = calculate_leaf_output(left_g, left_h, p, left_c, leaf_output)
    right_out = calculate_leaf_output(right_g, right_h, p, right_c, leaf_output)
    if adv_bounds is not None:
        lmin_a, lmax_a, rmin_a, rmax_a = adv_bounds
        left_out = jnp.clip(left_out, lmin_a[li, bf, bt], lmax_a[li, bf, bt])
        right_out = jnp.clip(right_out, rmin_a[li, bf, bt],
                             rmax_a[li, bf, bt])
    elif use_mc:
        left_out = jnp.clip(left_out, leaf_min, leaf_max)
        right_out = jnp.clip(right_out, leaf_min, leaf_max)

    # default_left: reverse scan => True; forced False for NaN single-scan mode
    # (feature_histogram.hpp:199-210)
    nan_single = (is_nan & ~mode_a)[bf]
    default_left = (bdir == 0) & ~nan_single

    if bundle is not None:
        chose_bundle = bundle.is_bundle[bf]
        seg_lo_out = jnp.where(chose_bundle, bundle.seg_lo[bf, bt], -1)
        seg_hi_out = jnp.where(chose_bundle, bundle.seg_hi[bf, bt], -1)
    else:
        seg_lo_out = jnp.full((L,), -1, jnp.int32)
        seg_hi_out = jnp.full((L,), -1, jnp.int32)

    num_info = SplitInfo(
        gain=best_gain.astype(jnp.float32),
        feature=bf,
        threshold=bt,
        default_left=default_left,
        left_sum_g=left_g, left_sum_h=left_h, left_count=left_c,
        right_sum_g=right_g, right_sum_h=right_h, right_count=right_c,
        left_output=left_out, right_output=right_out,
        is_cat=jnp.zeros((L,), dtype=bool),
        cat_bitset=jnp.zeros((L, cat_words), dtype=jnp.uint32),
        seg_lo=seg_lo_out.astype(jnp.int32),
        seg_hi=seg_hi_out.astype(jnp.int32),
    )
    if not with_categorical:
        if return_feature_gains:
            return num_info, per_feature_best_gain_key(gain_rev, gain_fwd)
        return num_info

    (cgain, cfeat, clg, clh, clc, cbits, cl2) = find_best_cat_splits(
        hist, leaf_sum_g, leaf_sum_h, leaf_cnt, leaf_output, leaf_depth,
        meta, p, feature_mask, max_depth, cat_words,
        gain_adjust=gain_adjust)
    crg = leaf_sum_g - clg
    crh = leaf_sum_h - clh
    crc = leaf_cnt - clc
    clo = calculate_leaf_output(clg, clh, p, clc, leaf_output, cl2)
    cro = calculate_leaf_output(crg, crh, p, crc, leaf_output, cl2)
    if use_mc:
        clo = jnp.clip(clo, leaf_min, leaf_max)
        cro = jnp.clip(cro, leaf_min, leaf_max)
    # per-leaf choice between numerical and categorical bests; ties resolve
    # to the lower feature index (the reference's in-order feature loop with
    # strict operator>, serial_tree_learner.cpp:374-448)
    take_cat = (cgain > num_info.gain) | (
        (cgain == num_info.gain) & jnp.isfinite(cgain) & (cfeat < num_info.feature))

    def sel(cv, nv):
        cond = take_cat
        while cond.ndim < cv.ndim:
            cond = cond[..., None]
        return jnp.where(cond, cv, nv)

    merged = SplitInfo(
        gain=sel(cgain, num_info.gain),
        feature=sel(cfeat, num_info.feature),
        threshold=sel(jnp.zeros((L,), jnp.int32), num_info.threshold),
        default_left=sel(jnp.zeros((L,), bool), num_info.default_left),
        left_sum_g=sel(clg, num_info.left_sum_g),
        left_sum_h=sel(clh, num_info.left_sum_h),
        left_count=sel(clc, num_info.left_count),
        right_sum_g=sel(crg, num_info.right_sum_g),
        right_sum_h=sel(crh, num_info.right_sum_h),
        right_count=sel(crc, num_info.right_count),
        left_output=sel(clo, num_info.left_output),
        right_output=sel(cro, num_info.right_output),
        is_cat=take_cat,
        cat_bitset=sel(cbits, num_info.cat_bitset),
        seg_lo=sel(jnp.full((L,), -1, jnp.int32), num_info.seg_lo),
        seg_hi=sel(jnp.full((L,), -1, jnp.int32), num_info.seg_hi),
    )
    if return_feature_gains:
        return merged, per_feature_best_gain_key(gain_rev, gain_fwd)
    return merged
