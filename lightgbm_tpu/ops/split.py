"""Vectorized best-split search over histograms.

TPU-native re-design of the reference's per-feature threshold scan
(reference: src/treelearner/feature_histogram.hpp:858-1050
``FindBestThresholdSequentially`` and the gain/output formulas at
feature_histogram.hpp:737-856). Where the reference runs a sequential
two-direction scan per feature inside OpenMP, here cumulative sums over the
bin axis evaluate EVERY (leaf, feature, direction, threshold) candidate at
once, then a masked lexicographic argmax reproduces the reference's
first-better-wins tie ordering.

Semantics carried over exactly:

- gain  = GetLeafGain(left) + GetLeafGain(right) compared against
  ``min_gain_shift = GetLeafGain(parent) + min_gain_to_split`` (strict ``>``),
  with stored gain = best_gain - min_gain_shift
  (feature_histogram.hpp:103-112, 934-944).
- leaf output = -ThresholdL1(sum_g, l1) / (sum_h + l2), clipped to
  ±max_delta_step, then path-smoothed toward the parent output
  (feature_histogram.hpp:737-764 CalculateSplittedLeafOutput).
- missing handling (feature_histogram.hpp:166-213 FuncForNumricalL3 dispatch):
  * num_bin > 2 and MissingType::Zero  -> two scans, default bin skipped from
    both accumulations and from the threshold candidates (SKIP_DEFAULT_BIN).
  * num_bin > 2 and MissingType::NaN   -> two scans, NaN bin (last bin)
    excluded from directional accumulation so its mass rides with the default
    direction (NA_AS_MISSING).
  * otherwise -> single reverse scan; default_left=False forced for NaN
    (feature_histogram.hpp:199-210).
  Reverse scan => missing goes left (default_left=True); forward scan =>
  missing goes right.
- the accumulated direction's hessian starts at kEpsilon
  (feature_histogram.hpp:882 ``sum_right_hessian = kEpsilon``).
- min_data_in_leaf / min_sum_hessian_in_leaf validity masks
  (feature_histogram.hpp:904-917).

Deviation from the reference: counts come from an exactly-accumulated count
channel instead of ``RoundInt(hess * num_data / sum_hessian)``
(feature_histogram.hpp:869, 898) — exact counts, same constraint semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_EPSILON = 1e-15          # reference: include/LightGBM/meta.h kEpsilon
K_MIN_SCORE = -jnp.inf     # reference: kMinScore


class FeatureMeta(NamedTuple):
    """Per-feature static metadata arrays, all shape [F]."""
    num_bins: jax.Array        # int32, total bins incl. NaN bin
    missing_type: jax.Array    # int32, MISSING_{NONE,ZERO,NAN}
    default_bin: jax.Array     # int32, bin of value 0.0
    is_categorical: jax.Array  # bool
    monotone: jax.Array        # int8, -1/0/+1 (0 = unconstrained)
    penalty: jax.Array         # float32 feature_contri gain multiplier


class SplitParams(NamedTuple):
    """Split hyperparameters (dynamic scalars so param changes don't recompile)."""
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    max_delta_step: jax.Array
    path_smooth: jax.Array
    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    min_gain_to_split: jax.Array
    cat_l2: jax.Array
    cat_smooth: jax.Array
    max_cat_threshold: jax.Array
    min_data_per_group: jax.Array
    max_cat_to_onehot: jax.Array

    @classmethod
    def from_config(cls, config) -> "SplitParams":
        f32 = jnp.float32
        return cls(
            lambda_l1=f32(config.lambda_l1),
            lambda_l2=f32(config.lambda_l2),
            max_delta_step=f32(config.max_delta_step),
            path_smooth=f32(config.path_smooth),
            min_data_in_leaf=f32(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=f32(config.min_sum_hessian_in_leaf),
            min_gain_to_split=f32(config.min_gain_to_split),
            cat_l2=f32(config.cat_l2),
            cat_smooth=f32(config.cat_smooth),
            max_cat_threshold=jnp.int32(config.max_cat_threshold),
            min_data_per_group=f32(config.min_data_per_group),
            max_cat_to_onehot=jnp.int32(config.max_cat_to_onehot),
        )


class SplitInfo(NamedTuple):
    """Per-leaf best split, struct-of-arrays of shape [L]
    (reference: src/treelearner/split_info.hpp:22-90)."""
    gain: jax.Array          # f32; -inf when unsplittable
    feature: jax.Array       # int32 inner feature index
    threshold: jax.Array     # int32 bin threshold (left: bin <= threshold)
    default_left: jax.Array  # bool, direction for missing values
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    left_count: jax.Array    # f32 (weighted count channel)
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    cat_bitset: jax.Array    # uint32[L, CAT_WORDS] categorical membership (0 when numerical)


CAT_BITSET_WORDS = 8  # supports categorical splits over up to 256 bins


def threshold_l1(s: jax.Array, l1: jax.Array) -> jax.Array:
    """reference: feature_histogram.hpp:737-741 ThresholdL1."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_g, sum_h, p: SplitParams, num_data, parent_output,
                          lambda_l2=None):
    """reference: feature_histogram.hpp:743-764 CalculateSplittedLeafOutput."""
    l2 = p.lambda_l2 if lambda_l2 is None else lambda_l2
    ret = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + l2)
    ret = jnp.where((p.max_delta_step > 0) & (jnp.abs(ret) > p.max_delta_step),
                    jnp.sign(ret) * p.max_delta_step, ret)
    use_smooth = p.path_smooth > K_EPSILON
    n_over_s = num_data / jnp.where(use_smooth, p.path_smooth, 1.0)
    smoothed = ret * (n_over_s / (n_over_s + 1.0)) + parent_output / (n_over_s + 1.0)
    return jnp.where(use_smooth, smoothed, ret)


def leaf_gain_given_output(sum_g, sum_h, output, p: SplitParams, lambda_l2=None):
    """reference: feature_histogram.hpp:846-856 GetLeafGainGivenOutput."""
    l2 = p.lambda_l2 if lambda_l2 is None else lambda_l2
    sg = threshold_l1(sum_g, p.lambda_l1)
    return -(2.0 * sg * output + (sum_h + l2) * output * output)


def leaf_gain(sum_g, sum_h, p: SplitParams, num_data, parent_output, lambda_l2=None):
    """reference: feature_histogram.hpp:826-843 GetLeafGain. Always routed
    through the output (identical to the closed form when no clipping/smoothing)."""
    out = calculate_leaf_output(sum_g, sum_h, p, num_data, parent_output, lambda_l2)
    return leaf_gain_given_output(sum_g, sum_h, out, p, lambda_l2)


def _directional_sums(hist_excl, leaf_sum_g, leaf_sum_h, leaf_cnt):
    """Cumulative left/right sums for every threshold, both directions.

    hist_excl: [L, F, B, 3] histogram with excluded bins zeroed.
    Returns dict with fwd/rev (accumulated-side eps added like the reference).
    Threshold t means: left = bins <= t (accumulated side fwd), right = bins > t.
    """
    csum = jnp.cumsum(hist_excl, axis=2)                       # [L, F, B, 3]
    total_excl = csum[:, :, -1:, :]
    # forward: left accumulates bins 0..t
    fwd_left = csum
    # reverse: right accumulates bins t+1..B-1 (of the non-excluded mass)
    rev_right = total_excl - csum
    lt = dict(
        fwd_left_g=fwd_left[..., 0], fwd_left_h=fwd_left[..., 1] + K_EPSILON,
        fwd_left_c=fwd_left[..., 2],
        rev_right_g=rev_right[..., 0], rev_right_h=rev_right[..., 1] + K_EPSILON,
        rev_right_c=rev_right[..., 2],
    )
    # complement side from the leaf's TRUE totals (includes missing mass):
    b = (leaf_sum_g[:, None, None], leaf_sum_h[:, None, None], leaf_cnt[:, None, None])
    lt["fwd_right_g"] = b[0] - lt["fwd_left_g"]
    lt["fwd_right_h"] = b[1] - lt["fwd_left_h"]
    lt["fwd_right_c"] = b[2] - lt["fwd_left_c"]
    lt["rev_left_g"] = b[0] - lt["rev_right_g"]
    lt["rev_left_h"] = b[1] - lt["rev_right_h"]
    lt["rev_left_c"] = b[2] - lt["rev_right_c"]
    return lt


def find_best_splits(hist: jax.Array, leaf_sum_g, leaf_sum_h, leaf_cnt,
                     leaf_output, leaf_depth, meta: FeatureMeta, p: SplitParams,
                     feature_mask: jax.Array, max_depth: int = -1) -> SplitInfo:
    """Best split per leaf over all numerical features.

    Args:
      hist: [L, F, B, 3] (grad, hess, count).
      leaf_sum_g/h/cnt/output/depth: [L] current leaf aggregates.
      feature_mask: [F] or [L, F] float/bool validity (col sampling,
        interaction constraints).
      max_depth: static; leaves at max_depth get gain -inf (reference:
        serial_tree_learner.cpp BeforeFindBestSplit depth guard).
    Returns SplitInfo with arrays of shape [L].
    """
    L, F, B, _ = hist.shape
    nb = meta.num_bins[None, :, None]                      # [1, F, 1]
    bins = jnp.arange(B, dtype=jnp.int32)[None, None, :]   # [1, 1, B]

    mode_a = (meta.num_bins > 2) & (meta.missing_type != MISSING_NONE)   # [F]
    is_nan = meta.missing_type == MISSING_NAN
    is_zero = meta.missing_type == MISSING_ZERO

    excl = jnp.zeros((1, F, B), dtype=bool)
    excl = excl | (mode_a & is_nan)[None, :, None] & (bins == nb - 1)
    excl = excl | (mode_a & is_zero)[None, :, None] & (bins == meta.default_bin[None, :, None])
    hist_excl = jnp.where(excl[:, :, :, None], 0.0, hist)

    s = _directional_sums(hist_excl, leaf_sum_g, leaf_sum_h, leaf_cnt)

    parent_out = leaf_output[:, None, None]
    num_data = leaf_cnt[:, None, None]

    def side_gain(g, h, c):
        return leaf_gain(g, h, p, c, parent_out)

    gain_fwd = side_gain(s["fwd_left_g"], s["fwd_left_h"], s["fwd_left_c"]) + \
        side_gain(s["fwd_right_g"], s["fwd_right_h"], s["fwd_right_c"])
    gain_rev = side_gain(s["rev_left_g"], s["rev_left_h"], s["rev_left_c"]) + \
        side_gain(s["rev_right_g"], s["rev_right_h"], s["rev_right_c"])

    min_gain_shift = (leaf_gain(leaf_sum_g, leaf_sum_h, p, leaf_cnt, leaf_output)
                      + p.min_gain_to_split)[:, None, None]

    def constraint_mask(lg, lh, lc, rg, rh, rc):
        return ((lc >= p.min_data_in_leaf) & (rc >= p.min_data_in_leaf)
                & (lh >= p.min_sum_hessian_in_leaf) & (rh >= p.min_sum_hessian_in_leaf))

    valid_fwd = constraint_mask(s["fwd_left_g"], s["fwd_left_h"], s["fwd_left_c"],
                                s["fwd_right_g"], s["fwd_right_h"], s["fwd_right_c"])
    valid_rev = constraint_mask(s["rev_left_g"], s["rev_left_h"], s["rev_left_c"],
                                s["rev_right_g"], s["rev_right_h"], s["rev_right_c"])

    # threshold-range masks (see module docstring for the scan ranges)
    thr_ok_common = bins <= nb - 2
    fwd_ok = mode_a[None, :, None] & thr_ok_common
    rev_upper = nb - 2 - (mode_a & is_nan)[None, :, None].astype(jnp.int32)
    rev_ok = bins <= rev_upper
    zero_thr_skip = (mode_a & is_zero)[None, :, None] & (bins == meta.default_bin[None, :, None])
    fwd_ok = fwd_ok & ~zero_thr_skip
    rev_ok = rev_ok & ~zero_thr_skip

    fmask = feature_mask
    if fmask.ndim == 1:
        fmask = fmask[None, :]
    fmask = (fmask.astype(bool) & ~meta.is_categorical)[..., None]   # [L|1, F, 1]

    depth_ok = jnp.ones((L,), dtype=bool) if max_depth <= 0 else (leaf_depth < max_depth)
    base_ok = fmask & depth_ok[:, None, None]

    valid_fwd = valid_fwd & fwd_ok & base_ok & (gain_fwd > min_gain_shift) & ~jnp.isnan(gain_fwd)
    valid_rev = valid_rev & rev_ok & base_ok & (gain_rev > min_gain_shift) & ~jnp.isnan(gain_rev)

    gain_fwd = jnp.where(valid_fwd, gain_fwd, K_MIN_SCORE)
    gain_rev = jnp.where(valid_rev, gain_rev, K_MIN_SCORE)

    # ---- lexicographic argmax reproducing the reference's scan tie order:
    # reverse scan runs first and keeps the first (=highest-threshold) maximum;
    # forward replaces only on strictly greater gain (lowest threshold first).
    # Across features: lowest feature index wins ties
    # (serial_tree_learner.cpp:374-448 feature loop with strict operator>).
    gains = jnp.stack([gain_rev, gain_fwd], axis=2)          # [L, F, 2, B]
    farange = jnp.arange(F, dtype=jnp.int32)[None, :, None, None]
    tpref = jnp.stack([bins, (B - 1) - bins], axis=2)        # rev: high t; fwd: low t
    pref = ((F - 1) - farange) * (4 * B) + jnp.stack(
        [jnp.full_like(bins, 2 * B), jnp.zeros_like(bins)], axis=2) + tpref

    flat_gains = gains.reshape(L, -1)
    best_gain = jnp.max(flat_gains, axis=1)
    is_best = flat_gains == best_gain[:, None]
    flat_pref = jnp.broadcast_to(pref, gains.shape).reshape(L, -1)
    best_idx = jnp.argmax(jnp.where(is_best, flat_pref, -1), axis=1)

    bf = (best_idx // (2 * B)).astype(jnp.int32)             # feature
    rem = best_idx % (2 * B)
    bdir = (rem // B).astype(jnp.int32)                      # 0=rev, 1=fwd
    bt = (rem % B).astype(jnp.int32)                         # threshold bin

    li = jnp.arange(L)

    def pick(rev_name, fwd_name):
        rev_v = s[rev_name][li, bf, bt]
        fwd_v = s[fwd_name][li, bf, bt]
        return jnp.where(bdir == 0, rev_v, fwd_v)

    left_g = pick("rev_left_g", "fwd_left_g")
    left_h = pick("rev_left_h", "fwd_left_h")
    left_c = pick("rev_left_c", "fwd_left_c")
    right_g = pick("rev_right_g", "fwd_right_g")
    right_h = pick("rev_right_h", "fwd_right_h")
    right_c = pick("rev_right_c", "fwd_right_c")

    left_out = calculate_leaf_output(left_g, left_h, p, left_c, leaf_output)
    right_out = calculate_leaf_output(right_g, right_h, p, right_c, leaf_output)

    # default_left: reverse scan => True; forced False for NaN single-scan mode
    # (feature_histogram.hpp:199-210)
    nan_single = (is_nan & ~mode_a)[bf]
    default_left = (bdir == 0) & ~nan_single

    shift = min_gain_shift[:, 0, 0]
    stored_gain = jnp.where(jnp.isfinite(best_gain), best_gain - shift, K_MIN_SCORE)

    return SplitInfo(
        gain=stored_gain.astype(jnp.float32),
        feature=bf,
        threshold=bt,
        default_left=default_left,
        left_sum_g=left_g, left_sum_h=left_h, left_count=left_c,
        right_sum_g=right_g, right_sum_h=right_h, right_count=right_c,
        left_output=left_out, right_output=right_out,
        cat_bitset=jnp.zeros((L, CAT_BITSET_WORDS), dtype=jnp.uint32),
    )
