"""Atomic checkpoint/resume for fault-tolerant training.

The reference's only mid-training persistence is ``snapshot_freq`` model
dumps (gbdt.cpp:277-281): non-atomic in-place writes that lose all trainer
state — DART's drop RNG, the feature-fraction RNG, bagging phase, eval
history, early-stopping counters — so a "resume" from one silently trains
a DIFFERENT model. This module makes resumable boosting a design point
(the TF Boosted Trees stance, arXiv:1710.11555): a checkpoint captures
the model text PLUS a trainer-state sidecar, every file lands via
``utils/atomic_write`` (tmp + fsync + rename), and a manifest written
LAST records byte lengths + sha256 checksums so a kill at any point
leaves either a fully valid checkpoint or one that validation rejects.

Layout under the checkpoint directory::

    ckpt_00000007/
        model.txt       v3 model text (interop: loads as a normal model)
        state.pkl       pickled trainer state (trees, scores, RNGs, ...)
        MANIFEST.json   iteration, params hash, dataset fingerprint,
                        per-file {bytes, sha256}; its presence marks the
                        checkpoint complete

``load_latest_valid`` walks checkpoints newest-first and falls back past
any truncated/corrupt one with a warning. Resume is BIT-IDENTICAL: the
sidecar restores the exact float32 score caches, device tree arrays and
RNG states, so kill-at-k + resume reproduces the uninterrupted run's
model text byte for byte (tests/test_fault_tolerance.py asserts this for
gbdt/dart/goss with bagging).

Multi-process runs write from rank 0 only, with a cross-process barrier
after the save so no rank races ahead of a checkpoint that may later be
resumed from.

Note: ``state.pkl`` is a pickle — load checkpoints only from directories
you trust, like any model artifact.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .utils import log
from .utils import faults
from .utils.atomic_write import atomic_write_bytes, atomic_write_text

MANIFEST_NAME = "MANIFEST.json"
MODEL_NAME = "model.txt"
STATE_NAME = "state.pkl"
_CKPT_RE = re.compile(r"^ckpt_(\d{8})$")
MANIFEST_FORMAT = 1

# params that steer IO/logging/injection but not the trained model — they
# may differ between the checkpointing run and the resuming run
_NON_TRAINING_PARAMS = frozenset({
    "task", "data", "valid", "input_model", "output_model", "output_result",
    "convert_model", "convert_model_language", "verbosity", "snapshot_freq",
    "metric_freq", "num_threads", "machine_list_filename",
    "checkpoint_path", "checkpoint_keep", "check_numerics",
    "heartbeat_interval", "collective_deadline", "max_restarts",
    "fault_kill_at_iter", "fault_hang_at_iter", "fault_kill_in_ckpt_write",
    "fault_nan_grad_at_iter", "fault_corrupt_checkpoint",
})


def params_hash(config) -> str:
    """Stable hash of the training-relevant parameters: resuming under a
    different configuration must be detected, not silently train a
    different model. Walks the full Config field set directly —
    ``to_params()`` omits list-typed fields (default_factory), which would
    blind the check to monotone/interaction constraints, per-feature bins,
    metric lists etc."""
    import dataclasses
    items = sorted(
        (f.name, repr(getattr(config, f.name)))
        for f in dataclasses.fields(type(config))
        if f.name not in _NON_TRAINING_PARAMS)
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def dataset_fingerprint(train_set) -> str:
    """Cheap identity check for the training data: shape plus label/weight
    bytes (not a full data hash — the point is catching 'resumed on a
    different dataset', not bit-auditing features)."""
    import numpy as np
    h = hashlib.sha256()
    n = int(getattr(train_set, "num_data", 0) or 0)
    f = int(getattr(train_set, "num_total_features", 0) or 0)
    h.update(f"{n}x{f}".encode())
    label = train_set.get_label() if hasattr(train_set, "get_label") else None
    if label is not None:
        h.update(np.ascontiguousarray(np.asarray(label, np.float64)).tobytes())
    weight = train_set.get_weight() if hasattr(train_set, "get_weight") else None
    if weight is not None:
        h.update(np.ascontiguousarray(np.asarray(weight, np.float64)).tobytes())
    return h.hexdigest()[:16]


def capture_state(booster) -> Dict[str, Any]:
    """Full trainer state of a training booster: the boosting layer's state
    (trees, score caches, RNGs — see GBDT.get_trainer_state) plus
    booster-level fields and the states of any stateful callbacks the
    engine registered on the booster."""
    state: Dict[str, Any] = {
        "format": MANIFEST_FORMAT,
        "boosting": booster._boosting.get_trainer_state(),
        "booster": {
            "best_iteration": booster.best_iteration,
            "best_score": dict(booster.best_score),
            "attr": dict(getattr(booster, "_attr", {}) or {}),
        },
        "callbacks": {},
    }
    for cb in getattr(booster, "_callbacks", []) or []:
        key = getattr(cb, "ckpt_key", None)
        if key and hasattr(cb, "get_state"):
            state["callbacks"][key] = cb.get_state()
    return state


@dataclass
class LoadedCheckpoint:
    path: str
    iteration: int
    manifest: Dict[str, Any]
    model_text: str
    state: Dict[str, Any]


class CheckpointManager:
    """Writes, validates, prunes and loads checkpoints in one directory."""

    def __init__(self, directory: str, keep: int = 2, config=None):
        self.directory = os.fspath(directory)
        self.keep = max(1, int(keep))
        self._fault_plan = faults.plan_from(config)
        self._dataset_fp: Optional[str] = None

    # ------------------------------------------------------------- write
    def save(self, booster, iteration: int) -> Optional[str]:
        """Checkpoint ``booster`` after ``iteration`` completed boosting
        iterations. Rank 0 writes; every rank barriers after, so no
        process races past a checkpoint another may resume from."""
        import jax
        from . import distributed
        path = None
        if jax.process_count() <= 1 or jax.process_index() == 0:
            path = self._write(booster, iteration)
        distributed.barrier(f"lgbm_tpu_checkpoint_{iteration}")
        return path

    def _write(self, booster, iteration: int) -> str:
        """Stage the whole checkpoint in ``ckpt_N.tmp`` and publish it with
        one directory rename. A writer killed at ANY point leaves either no
        ``ckpt_N`` at all (a stale ``.tmp`` the name filter ignores and the
        next write cleans) or a complete one — and within the stage the
        manifest still lands last, so even a non-staged legacy directory
        can only be complete-or-rejected."""
        name = f"ckpt_{iteration:08d}"
        path = os.path.join(self.directory, name)
        stage = path + ".tmp"
        os.makedirs(self.directory, exist_ok=True)
        self._clean_stale_tmp()
        if os.path.isdir(path):
            if self._quick_valid(path):
                # a resumed incarnation re-reaches an already-checkpointed
                # iteration: resume is bit-identical, so the existing
                # VALID checkpoint already holds these bytes — keeping it
                # (instead of delete-then-republish) means a kill can
                # never destroy a published valid checkpoint
                self._prune()
                return path
            shutil.rmtree(path, ignore_errors=True)
        os.makedirs(stage, exist_ok=True)
        model_bytes = booster.model_to_string(num_iteration=-1).encode()
        state_bytes = pickle.dumps(capture_state(booster), protocol=4)
        atomic_write_bytes(os.path.join(stage, MODEL_NAME), model_bytes)
        atomic_write_bytes(os.path.join(stage, STATE_NAME), state_bytes)
        faults.maybe_kill_in_ckpt_write(self._fault_plan, iteration)
        if self._dataset_fp is None:
            self._dataset_fp = dataset_fingerprint(
                booster._boosting.train_set)
        phash = getattr(booster, "_initial_params_hash", None) \
            or params_hash(booster.config)
        from . import distributed
        manifest = {
            "format": MANIFEST_FORMAT,
            "iteration": int(iteration),
            "params_hash": phash,
            "dataset_fingerprint": self._dataset_fp,
            "files": {
                MODEL_NAME: {"bytes": len(model_bytes),
                             "sha256": hashlib.sha256(model_bytes).hexdigest()},
                STATE_NAME: {"bytes": len(state_bytes),
                             "sha256": hashlib.sha256(state_bytes).hexdigest()},
            },
            # supervision telemetry: which incarnation wrote this, and the
            # gang's liveness view at write time (postmortem breadcrumbs)
            "health": distributed.health_snapshot(),
        }
        # the manifest lands LAST within the stage; the rename publishes
        # the complete checkpoint atomically (the target cannot exist:
        # valid ones short-circuited above, invalid ones were removed)
        atomic_write_text(os.path.join(stage, MANIFEST_NAME),
                          json.dumps(manifest, indent=1, sort_keys=True))
        os.replace(stage, path)
        faults.maybe_corrupt_checkpoint(self._fault_plan,
                                        os.path.join(path, MODEL_NAME))
        self._prune()
        return path

    def _clean_stale_tmp(self) -> None:
        """Remove ``ckpt_*.tmp`` staging directories a killed writer left
        behind (they never match ``_CKPT_RE`` so readers already ignore
        them; this reclaims the disk)."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for entry in entries:
            if entry.startswith("ckpt_") and entry.endswith(".tmp"):
                stale = os.path.join(self.directory, entry)
                log.warning(f"removing stale checkpoint staging dir "
                            f"{entry} (writer was killed mid-write)")
                shutil.rmtree(stale, ignore_errors=True)

    def _quick_valid(self, path: str) -> bool:
        """Cheap structural validation for PRUNING decisions: manifest
        parses and every listed file exists with the recorded byte length.
        (Checksums are deliberately skipped — pruning runs on every save;
        ``validate`` does the full sha256 pass on the read side.)"""
        mpath = os.path.join(path, MANIFEST_NAME)
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
            if manifest.get("format") != MANIFEST_FORMAT:
                return False
            files = manifest.get("files", {})
            if not files:
                return False
            for fname, meta in files.items():
                if os.path.getsize(os.path.join(path, fname)) \
                        != int(meta["bytes"]):
                    return False
        except (OSError, ValueError, KeyError, TypeError):
            return False
        return True

    def _prune(self) -> None:
        """Retention by VALIDITY, not by name: keep the newest ``keep``
        structurally valid checkpoints; checkpoints that fail validation
        are deleted (they can never be resumed from) and never count
        toward ``keep`` — so a run of damaged newer checkpoints can't
        evict the newest checkpoint that actually works."""
        valid, invalid = [], []
        for it, path in self.checkpoints():
            (valid if self._quick_valid(path) else invalid).append(
                (it, path))
        for it, path in valid[:-self.keep]:
            shutil.rmtree(path, ignore_errors=True)
        for it, path in invalid:
            log.warning(f"pruning invalid checkpoint "
                        f"{os.path.basename(path)} (failed structural "
                        f"validation; it could never be resumed from)")
            shutil.rmtree(path, ignore_errors=True)

    # -------------------------------------------------------------- read
    def checkpoints(self) -> List[Tuple[int, str]]:
        """(iteration, path) pairs sorted ascending by iteration."""
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        for entry in entries:
            m = _CKPT_RE.match(entry)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, entry)))
        return sorted(out)

    def validate(self, path: str) -> Dict[str, Any]:
        """Parse + integrity-check one checkpoint's manifest; raises
        ValueError naming what failed."""
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.exists(mpath):
            raise ValueError("no manifest (checkpoint write did not complete)")
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            raise ValueError(f"unreadable manifest: {e}")
        if manifest.get("format") != MANIFEST_FORMAT:
            raise ValueError(f"unknown manifest format "
                             f"{manifest.get('format')!r}")
        for fname, meta in manifest.get("files", {}).items():
            fpath = os.path.join(path, fname)
            if not os.path.exists(fpath):
                raise ValueError(f"missing file {fname}")
            size = os.path.getsize(fpath)
            if size != int(meta["bytes"]):
                raise ValueError(f"{fname} is {size} bytes, manifest says "
                                 f"{meta['bytes']} (truncated?)")
            with open(fpath, "rb") as fh:
                digest = hashlib.sha256(fh.read()).hexdigest()
            if digest != meta["sha256"]:
                raise ValueError(f"{fname} checksum mismatch (corrupt)")
        return manifest

    def load_latest_valid(self) -> Optional[LoadedCheckpoint]:
        """Newest checkpoint that passes integrity validation, falling back
        past truncated/corrupt ones with a warning; None when the
        directory holds no valid checkpoint."""
        for iteration, path in reversed(self.checkpoints()):
            try:
                manifest = self.validate(path)
                with open(os.path.join(path, MODEL_NAME), encoding="utf-8") as fh:
                    model_text = fh.read()
                with open(os.path.join(path, STATE_NAME), "rb") as fh:
                    state = pickle.load(fh)
            except (ValueError, OSError, pickle.UnpicklingError, EOFError,
                    TypeError) as e:
                # TypeError covers structurally-incompatible pickles: a
                # namedtuple in the state (e.g. GrowAux) that gained a
                # field since the checkpoint was written unpickles via
                # cls(*old_fields) and raises TypeError — treat it like
                # corruption and fall back rather than crash the resume
                log.warning(f"checkpoint {os.path.basename(path)} is corrupt "
                            f"or truncated ({e}); falling back to the "
                            f"previous checkpoint")
                continue
            return LoadedCheckpoint(path=path, iteration=iteration,
                                    manifest=manifest, model_text=model_text,
                                    state=state)
        return None


def restore_booster(booster, ckpt: LoadedCheckpoint) -> Dict[str, Any]:
    """Restore a freshly constructed training booster to the checkpointed
    state after validating that params and dataset match what the
    checkpoint was written with. Returns the saved callback states (keyed
    by ``ckpt_key``) for the engine to hand to its callbacks."""
    phash = getattr(booster, "_initial_params_hash", None) \
        or params_hash(booster.config)
    want = ckpt.manifest.get("params_hash")
    if want and want != phash:
        log.fatal(
            f"cannot resume from {ckpt.path}: it was written with different "
            f"training parameters (params_hash {want} != {phash}) — "
            f"resuming would silently train a different model. Use the "
            f"original parameters, or delete the checkpoint directory to "
            f"start fresh.")
    fp = dataset_fingerprint(booster._boosting.train_set)
    want_fp = ckpt.manifest.get("dataset_fingerprint")
    if want_fp and want_fp != fp:
        log.fatal(
            f"cannot resume from {ckpt.path}: it was written against a "
            f"different training dataset (fingerprint {want_fp} != {fp}).")
    booster._boosting.set_trainer_state(ckpt.state["boosting"])
    b = ckpt.state.get("booster", {})
    booster.best_iteration = b.get("best_iteration", -1)
    booster.best_score = dict(b.get("best_score", {}))
    if b.get("attr"):
        booster._attr = dict(b["attr"])
    return dict(ckpt.state.get("callbacks", {}))
