"""Atomic checkpoint/resume for fault-tolerant training.

The reference's only mid-training persistence is ``snapshot_freq`` model
dumps (gbdt.cpp:277-281): non-atomic in-place writes that lose all trainer
state — DART's drop RNG, the feature-fraction RNG, bagging phase, eval
history, early-stopping counters — so a "resume" from one silently trains
a DIFFERENT model. This module makes resumable boosting a design point
(the TF Boosted Trees stance, arXiv:1710.11555): a checkpoint captures
the model text PLUS a trainer-state sidecar, every file lands via
``utils/atomic_write`` (tmp + fsync + rename), and a manifest written
LAST records byte lengths + sha256 checksums so a kill at any point
leaves either a fully valid checkpoint or one that validation rejects.

Layout under the checkpoint directory::

    ckpt_00000007/
        model.txt       v3 model text (interop: loads as a normal model)
        state.pkl       pickled trainer state (trees, scores, RNGs, ...)
        MANIFEST.json   iteration, params hash, dataset fingerprint,
                        per-file {bytes, sha256}; its presence marks the
                        checkpoint complete

``load_latest_valid`` walks checkpoints newest-first and falls back past
any truncated/corrupt one with a warning. Resume is BIT-IDENTICAL: the
sidecar restores the exact float32 score caches, device tree arrays and
RNG states, so kill-at-k + resume reproduces the uninterrupted run's
model text byte for byte (tests/test_fault_tolerance.py asserts this for
gbdt/dart/goss with bagging).

Multi-process runs write from rank 0 only, with a cross-process barrier
after the save so no rank races ahead of a checkpoint that may later be
resumed from.

Note: ``state.pkl`` is a pickle — load checkpoints only from directories
you trust, like any model artifact.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .utils import log
from .utils import faults
from .utils.atomic_write import atomic_write_bytes, atomic_write_text

MANIFEST_NAME = "MANIFEST.json"
MODEL_NAME = "model.txt"
STATE_NAME = "state.pkl"
PARTITION_NAME = "PARTITION.json"
_CKPT_RE = re.compile(r"^ckpt_(\d{8})$")
MANIFEST_FORMAT = 1


def shard_name(rank: int) -> str:
    """Per-rank score-cache shard file inside a sharded checkpoint."""
    return f"shard_rank{int(rank)}.pkl"

# params that steer IO/logging/injection but not the trained model — they
# may differ between the checkpointing run and the resuming run
_NON_TRAINING_PARAMS = frozenset({
    "task", "data", "valid", "input_model", "output_model", "output_result",
    "convert_model", "convert_model_language", "verbosity", "snapshot_freq",
    "metric_freq", "num_threads", "machine_list_filename",
    "checkpoint_path", "checkpoint_keep", "checkpoint_shards",
    "check_numerics",
    # kernel-shape tuning: an execution-strategy knob (block-size choice
    # regroups partial sums at the same f32 tolerance every pass-shape
    # change does). hist_pallas_interpret is NOT here: off-TPU it changes
    # which algorithm "auto" resolves to (scatter vs the hilo kernel),
    # i.e. the histogram rounding model — the same class of drift as
    # histogram_method itself, which is hashed. quantized_grad is NOT
    # here — it changes the trained model.
    "hist_autotune",
    # split_fusion is bit-identical to the classic split phase by
    # contract (tests/test_split_fusion.py pins model-text parity), so
    # toggling it between incarnations is execution strategy, not model
    # drift; the kernel-shape ride it DOES affect is handled by the
    # epilogue-keyed autotune cache (gbdt._hist_tuning)
    "split_fusion",
    "heartbeat_interval", "collective_deadline", "max_restarts",
    "rank_restart_budget", "min_world_size",
    # training-integrity knobs: the divergence-check cadence and the OOM
    # fallback GATE steer supervision, not the trained model (a degrade
    # EVENT does change numerics — which is why the degraded configuration
    # itself rides the trainer state, see GBDT.get_trainer_state
    # "oom_degrade" — but toggling the gate between runs must not reject
    # an otherwise-valid resume)
    "integrity_check_period", "hist_oom_fallback",
    # serving-front-end knobs: batching/deadline/admission policy for the
    # ServeFrontend — pure request-routing, never touches training
    "serve_flush_ms", "serve_max_batch_rows", "serve_max_queue_rows",
    "serve_deadline_ms", "serve_metrics", "serve_metrics_port",
    "serve_metrics_host",
    # telemetry knobs (lightgbm_tpu/telemetry.py): the flight recorder
    # observes training from already-fetched host values — ring size,
    # flush cadence and destination can all differ between the
    # checkpointing run and the resuming run without touching the model
    "telemetry_flight_recorder", "telemetry_ring_size", "telemetry_dir",
    "telemetry_flush_period", "telemetry_memory",
    "fault_kill_at_iter", "fault_hang_at_iter", "fault_kill_in_ckpt_write",
    "fault_nan_grad_at_iter", "fault_corrupt_checkpoint",
    "fault_kill_rank_at_iter", "fault_hang_rank_at_iter",
    "fault_kill_in_shard_write", "fault_corrupt_shard",
    "fault_flip_score_rank", "fault_nan_hist_at_iter",
    "fault_oom_at_iter", "fault_oom_count",
    "fault_slow_predict_ms", "fault_oom_at_predict",
})


def params_hash(config) -> str:
    """Stable hash of the training-relevant parameters: resuming under a
    different configuration must be detected, not silently train a
    different model. Walks the full Config field set directly —
    ``to_params()`` omits list-typed fields (default_factory), which would
    blind the check to monotone/interaction constraints, per-feature bins,
    metric lists etc."""
    import dataclasses
    items = sorted(
        (f.name, repr(getattr(config, f.name)))
        for f in dataclasses.fields(type(config))
        if f.name not in _NON_TRAINING_PARAMS)
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def dataset_fingerprint(train_set, local: bool = False) -> str:
    """Cheap identity check for the training data: shape plus label/weight
    bytes (not a full data hash — the point is catching 'resumed on a
    different dataset', not bit-auditing features). With ``local`` the
    shape part uses the PROCESS-LOCAL row count (labels/weights are
    already process-local on pre-partitioned datasets), giving the
    per-rank fingerprint sharded manifests record."""
    import numpy as np
    h = hashlib.sha256()
    n_local = getattr(train_set, "num_local_data", None) if local else None
    n = int(n_local if n_local is not None
            else (getattr(train_set, "num_data", 0) or 0))
    f = int(getattr(train_set, "num_total_features", 0) or 0)
    h.update(f"{n}x{f}".encode())
    label = train_set.get_label() if hasattr(train_set, "get_label") else None
    if label is not None:
        h.update(np.ascontiguousarray(np.asarray(label, np.float64)).tobytes())
    weight = train_set.get_weight() if hasattr(train_set, "get_weight") else None
    if weight is not None:
        h.update(np.ascontiguousarray(np.asarray(weight, np.float64)).tobytes())
    return h.hexdigest()[:16]


def label_range_sha256(label, lo: int, hi: int) -> str:
    """sha256 of LOCAL label rows [lo, hi) as float64 bytes — the per-rank
    row-content hash PARTITION.json records, recomputable by any later
    rank whose local range CONTAINS [lo, hi)."""
    import numpy as np
    a = np.ascontiguousarray(np.asarray(label, np.float64)[lo:hi])
    return hashlib.sha256(a.tobytes()).hexdigest()


def split_local_state(state: Dict[str, Any]) -> Tuple[Dict[str, Any],
                                                      Dict[str, Any]]:
    """Split a captured trainer state into (global, local) halves for the
    sharded layout: the score caches are the process-LOCAL rows of a
    pre-partitioned run and go into the rank's shard; everything else
    (trees, RNGs, counters) is rank-symmetric and lives in rank 0's
    state.pkl. The inverse is a plain dict merge before
    ``set_trainer_state``."""
    state = dict(state)
    boosting = dict(state["boosting"])
    local = {
        "train_score": boosting.pop("train_score"),
        "valid_scores": boosting.pop("valid_scores"),
    }
    state["boosting"] = boosting
    return state, local


def capture_state(booster) -> Dict[str, Any]:
    """Full trainer state of a training booster: the boosting layer's state
    (trees, score caches, RNGs — see GBDT.get_trainer_state) plus
    booster-level fields and the states of any stateful callbacks the
    engine registered on the booster."""
    state: Dict[str, Any] = {
        "format": MANIFEST_FORMAT,
        "boosting": booster._boosting.get_trainer_state(),
        "booster": {
            "best_iteration": booster.best_iteration,
            "best_score": dict(booster.best_score),
            "attr": dict(getattr(booster, "_attr", {}) or {}),
        },
        "callbacks": {},
    }
    for cb in getattr(booster, "_callbacks", []) or []:
        key = getattr(cb, "ckpt_key", None)
        if key and hasattr(cb, "get_state"):
            state["callbacks"][key] = cb.get_state()
    return state


@dataclass
class LoadedCheckpoint:
    path: str
    iteration: int
    manifest: Dict[str, Any]
    model_text: str
    state: Dict[str, Any]
    # sharded checkpoints only: the PARTITION.json row-partition manifest
    # ({"world_size", "global_rows", "ranks": [{"rank", "row_start",
    # "row_count", "label_sha256", "valid_counts"}, ...]}); the state above
    # is then the GLOBAL half (score caches live in the shards)
    partition: Optional[Dict[str, Any]] = None


class CheckpointManager:
    """Writes, validates, prunes and loads checkpoints in one directory."""

    def __init__(self, directory: str, keep: int = 2, config=None):
        self.directory = os.fspath(directory)
        self.keep = max(1, int(keep))
        self._fault_plan = faults.plan_from(config)
        self._dataset_fp: Optional[str] = None
        self._label_sha: Optional[str] = None

    # ------------------------------------------------------------- write
    def save(self, booster, iteration: int) -> Optional[str]:
        """Checkpoint ``booster`` after ``iteration`` completed boosting
        iterations. Replicated-data runs: rank 0 writes. Pre-partitioned
        runs (``checkpoint_shards``): EVERY rank writes its process-local
        score-cache shard and rank 0 publishes the manifests. Every rank
        barriers after, so no process races past a checkpoint another may
        resume from."""
        import jax
        from . import distributed
        path = None
        boosting = getattr(booster, "_boosting", None)
        sharded = bool(getattr(boosting, "_pre_part", False)) and \
            bool(getattr(booster.config, "checkpoint_shards", True))
        if sharded:
            path = self._write_sharded_booster(booster, iteration)
        elif jax.process_count() <= 1 or jax.process_index() == 0:
            path = self._write(booster, iteration)
        distributed.barrier(f"lgbm_tpu_checkpoint_{iteration}")
        return path

    def _write_sharded_booster(self, booster, iteration: int) -> Optional[str]:
        """Assemble the sharded-write inputs from a live pre-partitioned
        booster and run the rank-symmetric protocol (``write_sharded``)."""
        import jax
        import numpy as np
        boosting = booster._boosting
        ts = boosting.train_set
        if jax.process_index() == 0:
            state = capture_state(booster)
            global_state, local_state = split_local_state(state)
        else:
            # non-zero ranks contribute ONLY their score-cache shard:
            # capture_state would device_get the whole tree ensemble just
            # to be discarded (the global half is rank-symmetric and
            # written by rank 0 alone)
            global_state = {}
            local_state = {
                "train_score": np.asarray(boosting.train_score),
                "valid_scores": [np.asarray(s)
                                 for s in boosting._valid_scores],
            }
        row_start = int(getattr(ts, "local_row_start", 0) or 0)
        n_local = getattr(ts, "num_local_data", None)
        row_count = int(n_local if n_local is not None else ts.num_data)
        if self._dataset_fp is None:
            self._dataset_fp = dataset_fingerprint(ts, local=True)
        if self._label_sha is None:
            # labels are immutable after construction: hash once per
            # manager, not per checkpoint (O(n_local) f64 bytes)
            label = ts.get_label() if hasattr(ts, "get_label") else None
            self._label_sha = (label_range_sha256(label, 0, row_count)
                               if label is not None else "")
        label_sha = self._label_sha or None
        phash = getattr(booster, "_initial_params_hash", None) \
            or params_hash(booster.config)
        return self.write_sharded(
            iteration,
            # only rank 0 ever writes the model/global payloads — the
            # other ranks must not pay a full-ensemble serialization per
            # checkpoint
            model_text=(booster.model_to_string(num_iteration=-1)
                        if jax.process_index() == 0 else ""),
            global_state=global_state,
            local_state=local_state,
            row_start=row_start, row_count=row_count,
            global_rows=int(ts.num_data),
            fingerprint=self._dataset_fp,
            label_sha256=label_sha,
            valid_counts=[int(s.shape[0])
                          for s in local_state["valid_scores"]],
            phash=phash)

    def write_sharded(self, iteration: int, *, model_text: str,
                      global_state: Dict[str, Any],
                      local_state: Dict[str, Any],
                      row_start: int, row_count: int, global_rows: int,
                      fingerprint: str, label_sha256: Optional[str],
                      valid_counts: List[int],
                      phash: str = "") -> Optional[str]:
        """The rank-symmetric sharded checkpoint protocol. EVERY rank calls
        this in lockstep; all cross-rank coordination is the
        coordination-service ``distributed.exchange_host`` (pure gRPC — no
        XLA collectives, so the protocol runs on any backend):

        1. rank 0 stages ``ckpt_N.tmp`` (or decides to skip an
           already-valid ``ckpt_N``) and broadcasts the decision;
        2. every rank writes ``shard_rank{r}.pkl`` into the stage and
           exchanges its shard metadata (bytes, sha256, row range,
           fingerprint) — the exchange doubles as the all-shards-landed
           barrier;
        3. rank 0 writes model.txt, the GLOBAL state.pkl, PARTITION.json
           and (last) MANIFEST.json, then publishes with one rename.

        A rank killed at any point leaves either no ``ckpt_N`` (a stale
        ``.tmp`` readers ignore) or a complete one. Returns the published
        path on rank 0, None elsewhere."""
        import jax
        from . import distributed
        rank = jax.process_index()
        world = jax.process_count()
        name = f"ckpt_{iteration:08d}"
        path = os.path.join(self.directory, name)
        stage = path + ".tmp"
        # ---- decision: stage a new write, or skip an already-valid one
        decision = ""
        if rank == 0:
            os.makedirs(self.directory, exist_ok=True)
            self._clean_stale_tmp()
            if os.path.isdir(path) and self._quick_valid(path):
                decision = "skip"     # see _write: resume re-reached a
                                      # checkpointed iteration bit-identically
            else:
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                os.makedirs(stage, exist_ok=True)
                decision = "stage"
        decision = distributed.exchange_host(
            f"ckpt_decision_{iteration}", decision)[0]
        if decision == "skip":
            if rank == 0:
                self._prune()
                return path
            return None
        # ---- every rank writes its shard, then exchanges its metadata
        shard_bytes = pickle.dumps(local_state, protocol=4)
        atomic_write_bytes(os.path.join(stage, shard_name(rank)),
                           shard_bytes)
        faults.maybe_kill_in_shard_write(self._fault_plan, iteration)
        meta = {
            "rank": rank,
            "bytes": len(shard_bytes),
            "sha256": hashlib.sha256(shard_bytes).hexdigest(),
            "row_start": int(row_start),
            "row_count": int(row_count),
            "fingerprint": fingerprint,
            "label_sha256": label_sha256,
            "valid_counts": [int(c) for c in valid_counts],
        }
        metas = [json.loads(m) for m in distributed.exchange_host(
            f"ckpt_shard_{iteration}", json.dumps(meta))]
        if rank != 0:
            return None
        # ---- rank 0: global payloads, partition, manifest (LAST), rename
        model_bytes = model_text.encode()
        state_bytes = pickle.dumps(global_state, protocol=4)
        atomic_write_bytes(os.path.join(stage, MODEL_NAME), model_bytes)
        atomic_write_bytes(os.path.join(stage, STATE_NAME), state_bytes)
        faults.maybe_kill_in_ckpt_write(self._fault_plan, iteration)
        partition = {
            "world_size": world,
            "global_rows": int(global_rows),
            "ranks": [{"rank": m["rank"],
                       "row_start": m["row_start"],
                       "row_count": m["row_count"],
                       "label_sha256": m["label_sha256"],
                       "valid_counts": m["valid_counts"]}
                      for m in sorted(metas, key=lambda m: m["rank"])],
        }
        part_bytes = json.dumps(partition, indent=1, sort_keys=True).encode()
        atomic_write_bytes(os.path.join(stage, PARTITION_NAME), part_bytes)
        files = {
            MODEL_NAME: {"bytes": len(model_bytes),
                         "sha256": hashlib.sha256(model_bytes).hexdigest()},
            STATE_NAME: {"bytes": len(state_bytes),
                         "sha256": hashlib.sha256(state_bytes).hexdigest()},
            PARTITION_NAME: {"bytes": len(part_bytes),
                             "sha256": hashlib.sha256(part_bytes).hexdigest()},
        }
        for m in metas:
            files[shard_name(m["rank"])] = {"bytes": m["bytes"],
                                            "sha256": m["sha256"]}
        manifest = {
            "format": MANIFEST_FORMAT,
            "iteration": int(iteration),
            "params_hash": phash,
            "world_size": world,
            # per-RANK dataset fingerprints: each rank's local rows are a
            # different dataset slice, so one scalar cannot identify them
            "dataset_fingerprint": {str(m["rank"]): m["fingerprint"]
                                    for m in metas},
            "files": files,
            "health": distributed.health_snapshot(),
        }
        atomic_write_text(os.path.join(stage, MANIFEST_NAME),
                          json.dumps(manifest, indent=1, sort_keys=True))
        os.replace(stage, path)
        for m in metas:
            faults.maybe_corrupt_shard(
                self._fault_plan, os.path.join(path, shard_name(m["rank"])),
                m["rank"])
        faults.maybe_corrupt_checkpoint(self._fault_plan,
                                        os.path.join(path, MODEL_NAME))
        self._prune()
        return path

    def _write(self, booster, iteration: int) -> str:
        """Stage the whole checkpoint in ``ckpt_N.tmp`` and publish it with
        one directory rename. A writer killed at ANY point leaves either no
        ``ckpt_N`` at all (a stale ``.tmp`` the name filter ignores and the
        next write cleans) or a complete one — and within the stage the
        manifest still lands last, so even a non-staged legacy directory
        can only be complete-or-rejected."""
        name = f"ckpt_{iteration:08d}"
        path = os.path.join(self.directory, name)
        stage = path + ".tmp"
        os.makedirs(self.directory, exist_ok=True)
        self._clean_stale_tmp()
        if os.path.isdir(path):
            if self._quick_valid(path):
                # a resumed incarnation re-reaches an already-checkpointed
                # iteration: resume is bit-identical, so the existing
                # VALID checkpoint already holds these bytes — keeping it
                # (instead of delete-then-republish) means a kill can
                # never destroy a published valid checkpoint
                self._prune()
                return path
            shutil.rmtree(path, ignore_errors=True)
        os.makedirs(stage, exist_ok=True)
        model_bytes = booster.model_to_string(num_iteration=-1).encode()
        state_bytes = pickle.dumps(capture_state(booster), protocol=4)
        atomic_write_bytes(os.path.join(stage, MODEL_NAME), model_bytes)
        atomic_write_bytes(os.path.join(stage, STATE_NAME), state_bytes)
        faults.maybe_kill_in_ckpt_write(self._fault_plan, iteration)
        if self._dataset_fp is None:
            self._dataset_fp = dataset_fingerprint(
                booster._boosting.train_set)
        phash = getattr(booster, "_initial_params_hash", None) \
            or params_hash(booster.config)
        from . import distributed
        manifest = {
            "format": MANIFEST_FORMAT,
            "iteration": int(iteration),
            "params_hash": phash,
            "dataset_fingerprint": self._dataset_fp,
            "files": {
                MODEL_NAME: {"bytes": len(model_bytes),
                             "sha256": hashlib.sha256(model_bytes).hexdigest()},
                STATE_NAME: {"bytes": len(state_bytes),
                             "sha256": hashlib.sha256(state_bytes).hexdigest()},
            },
            # supervision telemetry: which incarnation wrote this, and the
            # gang's liveness view at write time (postmortem breadcrumbs)
            "health": distributed.health_snapshot(),
        }
        # the manifest lands LAST within the stage; the rename publishes
        # the complete checkpoint atomically (the target cannot exist:
        # valid ones short-circuited above, invalid ones were removed)
        atomic_write_text(os.path.join(stage, MANIFEST_NAME),
                          json.dumps(manifest, indent=1, sort_keys=True))
        os.replace(stage, path)
        faults.maybe_corrupt_checkpoint(self._fault_plan,
                                        os.path.join(path, MODEL_NAME))
        self._prune()
        return path

    def _clean_stale_tmp(self) -> None:
        """Remove ``ckpt_*.tmp`` staging directories a killed writer left
        behind (they never match ``_CKPT_RE`` so readers already ignore
        them; this reclaims the disk)."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for entry in entries:
            if entry.startswith("ckpt_") and entry.endswith(".tmp"):
                stale = os.path.join(self.directory, entry)
                log.warning(f"removing stale checkpoint staging dir "
                            f"{entry} (writer was killed mid-write)")
                shutil.rmtree(stale, ignore_errors=True)

    def _quick_valid(self, path: str) -> bool:
        """Cheap structural validation for PRUNING decisions: manifest
        parses and every listed file exists with the recorded byte length.
        (Checksums are deliberately skipped — pruning runs on every save;
        ``validate`` does the full sha256 pass on the read side.)"""
        mpath = os.path.join(path, MANIFEST_NAME)
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
            if manifest.get("format") != MANIFEST_FORMAT:
                return False
            files = manifest.get("files", {})
            if not files:
                return False
            for fname, meta in files.items():
                if os.path.getsize(os.path.join(path, fname)) \
                        != int(meta["bytes"]):
                    return False
        except (OSError, ValueError, KeyError, TypeError):
            return False
        return True

    def _prune(self) -> None:
        """Retention by VALIDITY, not by name: keep the newest ``keep``
        structurally valid checkpoints; checkpoints that fail validation
        are deleted (they can never be resumed from) and never count
        toward ``keep`` — so a run of damaged newer checkpoints can't
        evict the newest checkpoint that actually works."""
        valid, invalid = [], []
        for it, path in self.checkpoints():
            (valid if self._quick_valid(path) else invalid).append(
                (it, path))
        for it, path in valid[:-self.keep]:
            shutil.rmtree(path, ignore_errors=True)
        for it, path in invalid:
            log.warning(f"pruning invalid checkpoint "
                        f"{os.path.basename(path)} (failed structural "
                        f"validation; it could never be resumed from)")
            shutil.rmtree(path, ignore_errors=True)

    # -------------------------------------------------------------- read
    def checkpoints(self) -> List[Tuple[int, str]]:
        """(iteration, path) pairs sorted ascending by iteration."""
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        for entry in entries:
            m = _CKPT_RE.match(entry)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, entry)))
        return sorted(out)

    def validate(self, path: str) -> Dict[str, Any]:
        """Parse + integrity-check one checkpoint's manifest; raises
        ValueError naming what failed."""
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.exists(mpath):
            raise ValueError("no manifest (checkpoint write did not complete)")
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            raise ValueError(f"unreadable manifest: {e}")
        if manifest.get("format") != MANIFEST_FORMAT:
            raise ValueError(f"unknown manifest format "
                             f"{manifest.get('format')!r}")
        for fname, meta in manifest.get("files", {}).items():
            fpath = os.path.join(path, fname)
            if not os.path.exists(fpath):
                raise ValueError(f"missing file {fname}")
            size = os.path.getsize(fpath)
            if size != int(meta["bytes"]):
                raise ValueError(f"{fname} is {size} bytes, manifest says "
                                 f"{meta['bytes']} (truncated?)")
            with open(fpath, "rb") as fh:
                digest = hashlib.sha256(fh.read()).hexdigest()
            if digest != meta["sha256"]:
                raise ValueError(f"{fname} checksum mismatch (corrupt)")
        return manifest

    def load_latest_valid(self) -> Optional[LoadedCheckpoint]:
        """Newest checkpoint that passes integrity validation, falling back
        past truncated/corrupt ones with a warning; None when the
        directory holds no valid checkpoint. Sharded checkpoints (manifest
        lists shard files) also parse PARTITION.json — integrity of every
        shard was already part of ``validate``, so a checkpoint missing a
        shard (or with a shard checksum mismatch) falls back here exactly
        like a truncated replicated one."""
        for iteration, path in reversed(self.checkpoints()):
            try:
                manifest = self.validate(path)
                with open(os.path.join(path, MODEL_NAME), encoding="utf-8") as fh:
                    model_text = fh.read()
                with open(os.path.join(path, STATE_NAME), "rb") as fh:
                    state = pickle.load(fh)
                partition = None
                if PARTITION_NAME in manifest.get("files", {}):
                    with open(os.path.join(path, PARTITION_NAME)) as fh:
                        partition = json.load(fh)
            except (ValueError, OSError, pickle.UnpicklingError, EOFError,
                    TypeError) as e:
                # TypeError covers structurally-incompatible pickles: a
                # namedtuple in the state (e.g. GrowAux) that gained a
                # field since the checkpoint was written unpickles via
                # cls(*old_fields) and raises TypeError — treat it like
                # corruption and fall back rather than crash the resume
                log.warning(f"checkpoint {os.path.basename(path)} is corrupt "
                            f"or truncated ({e}); falling back to the "
                            f"previous checkpoint")
                continue
            return LoadedCheckpoint(path=path, iteration=iteration,
                                    manifest=manifest, model_text=model_text,
                                    state=state, partition=partition)
        return None


def load_shard(ckpt_path: str, rank: int) -> Dict[str, Any]:
    """Unpickle one rank's score-cache shard of a sharded checkpoint
    (integrity against the manifest was already checked by ``validate``)."""
    with open(os.path.join(ckpt_path, shard_name(rank)), "rb") as fh:
        return pickle.load(fh)


def _cumulative_ranges(counts: List[int]) -> List[Tuple[int, int]]:
    out, start = [], 0
    for c in counts:
        out.append((start, int(c)))
        start += int(c)
    return out


def reassemble_local_state(ckpt: LoadedCheckpoint, row_start: int,
                           row_count: int,
                           valid_ranges: List[Tuple[int, int]]) -> Dict[str, Any]:
    """Rebuild THIS rank's local trainer state (train/valid score caches)
    from a sharded checkpoint written under any world size: each requested
    row range is reassembled from the overlapping old shards
    (``distributed.repartition_rows``), touching only the shard files that
    overlap — a same-partition resume reads exactly its own shard."""
    from . import distributed
    part = ckpt.partition or {}
    ranks = part.get("ranks") or []
    old_train = [(e["row_start"], e["row_count"]) for e in ranks]
    cache: Dict[int, Dict[str, Any]] = {}

    def fetch(field, vi=None):
        def _fetch(r):
            import numpy as np
            if r not in cache:
                cache[r] = load_shard(ckpt.path, r)
            s = cache[r]
            return np.asarray(s[field] if vi is None
                              else s["valid_scores"][vi])
        return _fetch

    train_score = distributed.repartition_rows(
        old_train, row_start, row_count, fetch("train_score"))
    valid_scores = []
    for vi, (vs, vc) in enumerate(valid_ranges):
        old_valid = _cumulative_ranges(
            [e["valid_counts"][vi] for e in ranks])
        valid_scores.append(distributed.repartition_rows(
            old_valid, vs, vc, fetch(None, vi)))
    return {"train_score": train_score, "valid_scores": valid_scores}


def _validate_sharded_dataset(booster, ckpt: LoadedCheckpoint,
                              row_start: int, row_count: int) -> None:
    """Dataset-identity checks for a sharded resume. Same-partition ranks
    compare their per-rank fingerprint exactly; after a re-partition the
    new rank instead recomputes the recorded per-old-rank label hashes for
    every old range its new range fully contains — pure row content, so it
    works at any world size."""
    part = ckpt.partition or {}
    ts = booster._boosting.train_set
    global_rows = int(getattr(ts, "num_data", 0) or 0)
    if int(part.get("global_rows", -1)) != global_rows:
        log.fatal(
            f"cannot resume from {ckpt.path}: it was written for "
            f"{part.get('global_rows')} global rows, this dataset has "
            f"{global_rows}.")
    want_fp = ckpt.manifest.get("dataset_fingerprint")
    ranks = part.get("ranks") or []
    exact = next((e for e in ranks
                  if int(e["row_start"]) == row_start
                  and int(e["row_count"]) == row_count), None)
    if exact is not None and isinstance(want_fp, dict):
        rec = want_fp.get(str(exact["rank"]))
        fp = dataset_fingerprint(ts, local=True)
        if rec and rec != fp:
            log.fatal(
                f"cannot resume from {ckpt.path}: it was written against "
                f"a different training dataset (rank {exact['rank']} "
                f"fingerprint {rec} != {fp}).")
    label = ts.get_label() if hasattr(ts, "get_label") else None
    if label is None:
        return
    lo, hi = row_start, row_start + row_count
    for e in ranks:
        s, c = int(e["row_start"]), int(e["row_count"])
        if s >= lo and s + c <= hi and e.get("label_sha256"):
            got = label_range_sha256(label, s - lo, s + c - lo)
            if got != e["label_sha256"]:
                log.fatal(
                    f"cannot resume from {ckpt.path}: label rows "
                    f"[{s}, {s + c}) do not match the checkpoint's "
                    f"recorded content hash — the dataset changed (or "
                    f"rows were reordered) since the checkpoint was "
                    f"written.")


def restore_booster(booster, ckpt: LoadedCheckpoint) -> Dict[str, Any]:
    """Restore a freshly constructed training booster to the checkpointed
    state after validating that params and dataset match what the
    checkpoint was written with. Sharded checkpoints additionally
    reassemble this rank's score caches from the shard files under the
    CURRENT partition (resume at a different world size re-partitions on
    load). Returns the saved callback states (keyed by ``ckpt_key``) for
    the engine to hand to its callbacks."""
    phash = getattr(booster, "_initial_params_hash", None) \
        or params_hash(booster.config)
    want = ckpt.manifest.get("params_hash")
    if want and want != phash:
        log.fatal(
            f"cannot resume from {ckpt.path}: it was written with different "
            f"training parameters (params_hash {want} != {phash}) — "
            f"resuming would silently train a different model. Use the "
            f"original parameters, or delete the checkpoint directory to "
            f"start fresh.")
    boosting = booster._boosting
    if ckpt.partition is not None:
        from . import distributed
        ts = boosting.train_set
        row_start = int(getattr(ts, "local_row_start", 0) or 0)
        n_local = getattr(ts, "num_local_data", None)
        row_count = int(n_local if n_local is not None else ts.num_data)
        _validate_sharded_dataset(booster, ckpt, row_start, row_count)
        my_valid_counts = [int(s.shape[0]) for s in boosting._valid_scores]
        ranks = ckpt.partition.get("ranks") or []
        old_nvalid = len(ranks[0].get("valid_counts") or []) if ranks else 0
        if len(my_valid_counts) != old_nvalid:
            log.fatal(
                f"cannot resume from {ckpt.path}: it was written with "
                f"{old_nvalid} validation sets; this run has "
                f"{len(my_valid_counts)} — pass the same valid_sets in the "
                f"same order")
        if getattr(boosting, "_pre_part", False):
            # each new rank's valid-row offsets come from the counts of
            # the ranks below it (coordination-service exchange; trivial
            # at W=1)
            import jax
            all_counts = [json.loads(p) for p in distributed.exchange_host(
                "resume_valid_counts", json.dumps(my_valid_counts))]
            me = jax.process_index()
            valid_ranges = [
                (sum(c[vi] for c in all_counts[:me]), my_valid_counts[vi])
                for vi in range(len(my_valid_counts))]
        else:
            # REPLICATED booster reading a sharded checkpoint: every rank
            # holds the FULL row set, so every range starts at 0 (no
            # exchange — all ranks skip it consistently)
            valid_ranges = [(0, c) for c in my_valid_counts]
            if getattr(boosting, "_need_bagging", False):
                log.warning(
                    "resuming a pre-partitioned (sharded) checkpoint with "
                    "replicated data: the bagging sample stream is "
                    "mode-dependent (pre-partitioned draws are keyed per "
                    "global row), so continued training will not "
                    "bit-match a continuation of the original "
                    "pre-partitioned run")
        local = reassemble_local_state(ckpt, row_start, row_count,
                                       valid_ranges)
        merged = dict(ckpt.state["boosting"])
        merged.update(local)
        boosting.set_trainer_state(merged)
    else:
        import jax
        if getattr(boosting, "_pre_part", False) and jax.process_count() > 1:
            log.fatal(
                f"cannot resume from {ckpt.path}: the checkpoint is not "
                f"sharded (no {PARTITION_NAME}), but this is a "
                f"multi-process pre-partitioned run whose score caches "
                f"are process-local. Re-run the original training with "
                f"checkpoint_shards=true, or resume replicated.")
        fp = dataset_fingerprint(boosting.train_set)
        want_fp = ckpt.manifest.get("dataset_fingerprint")
        if want_fp and not isinstance(want_fp, dict) and want_fp != fp:
            log.fatal(
                f"cannot resume from {ckpt.path}: it was written against a "
                f"different training dataset (fingerprint {want_fp} != "
                f"{fp}).")
        boosting.set_trainer_state(ckpt.state["boosting"])
    b = ckpt.state.get("booster", {})
    booster.best_iteration = b.get("best_iteration", -1)
    booster.best_score = dict(b.get("best_score", {}))
    if b.get("attr"):
        booster._attr = dict(b["attr"])
    return dict(ckpt.state.get("callbacks", {}))


def _near_equal_counts(total: int, parts: int) -> List[int]:
    base, rem = divmod(int(total), int(parts))
    return [base + (1 if r < rem else 0) for r in range(parts)]


def repartition_checkpoint(ckpt_path: str, new_world_size: int,
                           dest_dir: str) -> str:
    """Offline re-shard: rewrite a SHARDED checkpoint for a different
    world size (near-equal contiguous row ranges) into ``dest_dir`` —
    what an operator runs before relaunching a pre-partitioned gang on a
    different machine count when they prefer the re-shard cost paid once,
    offline, instead of at load (the resume path re-partitions on load by
    itself either way; tests also use this to fabricate any-world
    checkpoints). Pure row movement — every row's f32 score bits are
    preserved exactly. Returns the new checkpoint path."""
    import numpy as np
    ckpt_path = os.path.abspath(ckpt_path)
    new_world_size = int(new_world_size)
    if new_world_size < 1:
        raise ValueError(f"new_world_size must be >= 1, got {new_world_size}")
    src_mgr = CheckpointManager(os.path.dirname(ckpt_path))
    manifest = src_mgr.validate(ckpt_path)
    if PARTITION_NAME not in manifest.get("files", {}):
        raise ValueError(f"{ckpt_path} is not a sharded checkpoint "
                         f"(no {PARTITION_NAME})")
    with open(os.path.join(ckpt_path, PARTITION_NAME)) as fh:
        partition = json.load(fh)
    ranks = partition["ranks"]
    shards = [load_shard(ckpt_path, e["rank"]) for e in ranks]
    train = np.concatenate([np.asarray(s["train_score"]) for s in shards],
                           axis=0)
    nvalid = len(ranks[0].get("valid_counts") or []) if ranks else 0
    valids = [np.concatenate([np.asarray(s["valid_scores"][vi])
                              for s in shards], axis=0)
              for vi in range(nvalid)]
    counts = _near_equal_counts(partition["global_rows"], new_world_size)
    vcounts = [_near_equal_counts(v.shape[0], new_world_size)
               for v in valids]
    old_by_range = {(int(e["row_start"]), int(e["row_count"])): e
                    for e in ranks}
    iteration = int(manifest["iteration"])
    name = f"ckpt_{iteration:08d}"
    os.makedirs(dest_dir, exist_ok=True)
    dest = os.path.join(dest_dir, name)
    stage = dest + ".tmp"
    if os.path.isdir(stage):
        shutil.rmtree(stage)
    os.makedirs(stage)
    files = {}
    new_ranks = []
    start = 0
    vstarts = [0] * nvalid
    for r, count in enumerate(counts):
        local = {
            "train_score": train[start:start + count],
            "valid_scores": [valids[vi][vstarts[vi]:vstarts[vi]
                                        + vcounts[vi][r]]
                             for vi in range(nvalid)],
        }
        shard_bytes = pickle.dumps(local, protocol=4)
        atomic_write_bytes(os.path.join(stage, shard_name(r)), shard_bytes)
        files[shard_name(r)] = {
            "bytes": len(shard_bytes),
            "sha256": hashlib.sha256(shard_bytes).hexdigest()}
        # content hashes / fingerprints are only carried over for ranges
        # that map EXACTLY onto an old rank (labels are not stored in the
        # checkpoint, so they cannot be recomputed offline)
        old = old_by_range.get((start, count))
        new_ranks.append({
            "rank": r, "row_start": start, "row_count": count,
            "label_sha256": old.get("label_sha256") if old else None,
            "valid_counts": [vcounts[vi][r] for vi in range(nvalid)]})
        start += count
        for vi in range(nvalid):
            vstarts[vi] += vcounts[vi][r]
    for fname in (MODEL_NAME, STATE_NAME):
        shutil.copy2(os.path.join(ckpt_path, fname),
                     os.path.join(stage, fname))
        files[fname] = dict(manifest["files"][fname])
    new_partition = {"world_size": new_world_size,
                     "global_rows": int(partition["global_rows"]),
                     "ranks": new_ranks}
    part_bytes = json.dumps(new_partition, indent=1, sort_keys=True).encode()
    atomic_write_bytes(os.path.join(stage, PARTITION_NAME), part_bytes)
    files[PARTITION_NAME] = {
        "bytes": len(part_bytes),
        "sha256": hashlib.sha256(part_bytes).hexdigest()}
    old_fp = manifest.get("dataset_fingerprint")
    new_fp = {}
    if isinstance(old_fp, dict):
        for e in new_ranks:
            old = old_by_range.get((e["row_start"], e["row_count"]))
            if old is not None and str(old["rank"]) in old_fp:
                new_fp[str(e["rank"])] = old_fp[str(old["rank"])]
    new_manifest = dict(manifest)
    new_manifest.update({"world_size": new_world_size,
                         "dataset_fingerprint": new_fp, "files": files})
    atomic_write_text(os.path.join(stage, MANIFEST_NAME),
                      json.dumps(new_manifest, indent=1, sort_keys=True))
    if os.path.isdir(dest):
        shutil.rmtree(dest)
    os.replace(stage, dest)
    return dest
