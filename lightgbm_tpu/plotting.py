"""Plotting utilities.

Mirrors the reference plotting module (reference:
python-package/lightgbm/plotting.py:25-623 — plot_importance,
plot_split_value_histogram, plot_metric, create_tree_digraph, plot_tree)
on matplotlib / graphviz, gated on availability like the reference's
compat shims."""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .booster import Booster
from .utils import log


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _to_booster(booster) -> Booster:
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Bar chart of feature importances (reference: plotting.py:25-140)."""
    import matplotlib.pyplot as plt

    booster = _to_booster(booster)
    importance = booster.feature_importance(importance_type=importance_type)
    feature_name = booster.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain" else str(x),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1 if values else 1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        xlabel = xlabel.replace("@importance_type@", importance_type)
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with @index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid: bool = True,
                               **kwargs):
    """Histogram of split thresholds used for one feature
    (reference: plotting.py:141-246)."""
    import matplotlib.pyplot as plt

    booster = _to_booster(booster)
    model = booster.dump_model()
    feature_names = model["feature_names"]
    if isinstance(feature, str):
        feat_idx = feature_names.index(feature)
    else:
        feat_idx = int(feature)

    values: List[float] = []

    def walk(node):
        if "split_feature" in node:
            if node["split_feature"] == feat_idx and node["decision_type"] == "<=":
                values.append(float(node["threshold"]))
            walk(node["left_child"])
            walk(node["right_child"])

    for ti in model["tree_info"]:
        walk(ti["tree_structure"])
    if not values:
        raise ValueError("Cannot plot split value histogram, "
                         "as feature was not used in splitting of the model.")
    hist, bin_edges = np.histogram(values, bins=bins or max(10, len(set(values))))
    centres = (bin_edges[:-1] + bin_edges[1:]) / 2

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centres, hist, align="center",
           width=width_coef * (bin_edges[1] - bin_edges[0]), **kwargs)
    if xlim is None:
        xlim = (bin_edges[0], bin_edges[-1])
    ax.set_xlim(xlim)
    if ylim is None:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@index/name@",
                              "name" if isinstance(feature, str) else "index")
        title = title.replace("@feature@", str(feature))
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot metric curves recorded by record_evaluation
    (reference: plotting.py:247-380). Accepts the evals_result dict or a
    fitted sklearn estimator."""
    import matplotlib.pyplot as plt

    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    first = eval_results[dataset_names[0]]
    if metric is None:
        metric = list(first.keys())[0]
    for name in dataset_names:
        if metric not in eval_results[name]:
            continue
        results = eval_results[name][metric]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


def _node_label(node: Dict[str, Any], feature_names, show_info, precision):
    if "split_feature" in node:
        feat = (feature_names[node["split_feature"]]
                if feature_names else f"f{node['split_feature']}")
        if node["decision_type"] == "<=":
            label = f"{feat} <= {node['threshold']:.{precision}g}"
        else:
            label = f"{feat} in {{{node['threshold']}}}"
        extras = []
        if "split_gain" in show_info:
            extras.append(f"gain: {node['split_gain']:.{precision}g}")
        if "internal_value" in show_info:
            extras.append(f"value: {node['internal_value']:.{precision}g}")
        if "internal_count" in show_info:
            extras.append(f"count: {node['internal_count']}")
        return "\n".join([label] + extras)
    extras = [f"leaf {node['leaf_index']}: {node['leaf_value']:.{precision}g}"]
    if "leaf_count" in show_info:
        extras.append(f"count: {node['leaf_count']}")
    if "leaf_weight" in show_info:
        extras.append(f"weight: {node['leaf_weight']:.{precision}g}")
    return "\n".join(extras)


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: int = 3, orientation: str = "horizontal",
                        **kwargs):
    """Graphviz digraph of one tree (reference: plotting.py:468-544)."""
    try:
        import graphviz
    except ImportError as err:
        raise ImportError("You must install graphviz and restart your session "
                          "to plot tree.") from err

    booster = _to_booster(booster)
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError("tree_index is out of range.")
    tree_info = model["tree_info"][tree_index]
    feature_names = model.get("feature_names")
    show_info = show_info or []

    graph = graphviz.Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr(rankdir=rankdir)

    counter = [0]

    def add(node, parent=None, decision=None):
        name = f"node{counter[0]}"
        counter[0] += 1
        shape = "rectangle" if "split_feature" in node else "ellipse"
        graph.node(name, label=_node_label(node, feature_names, show_info,
                                           precision), shape=shape)
        if parent is not None:
            graph.edge(parent, name, label=decision)
        if "split_feature" in node:
            add(node["left_child"], name, "yes")
            add(node["right_child"], name, "no")

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: int = 3,
              orientation: str = "horizontal", **kwargs):
    """Render one tree with matplotlib via graphviz
    (reference: plotting.py:545-623). Falls back to a pure-matplotlib
    rendering when graphviz is unavailable."""
    import matplotlib.image as mimage
    import matplotlib.pyplot as plt

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    graphviz_missing: Tuple = (ImportError, FileNotFoundError)
    try:
        import graphviz as _gv
        graphviz_missing = graphviz_missing + (_gv.ExecutableNotFound,)
    except ImportError:
        pass
    try:
        graph = create_tree_digraph(booster, tree_index=tree_index,
                                    show_info=show_info, precision=precision,
                                    orientation=orientation, **kwargs)
        from io import BytesIO
        s = BytesIO(graph.pipe(format="png"))
        img = mimage.imread(s)
        ax.imshow(img)
        ax.axis("off")
        return ax
    except graphviz_missing:   # graphviz package or dot binary missing
        return _plot_tree_matplotlib(booster, ax, tree_index, show_info or [],
                                     precision)


def _plot_tree_matplotlib(booster, ax, tree_index, show_info, precision):
    """Minimal text-box tree rendering without graphviz."""
    booster = _to_booster(booster)
    model = booster.dump_model()
    tree_info = model["tree_info"][tree_index]
    feature_names = model.get("feature_names")

    # compute (depth, order) positions via in-order traversal
    positions: List[Tuple[float, float, str]] = []
    x_counter = [0.0]

    def walk(node, depth):
        if "split_feature" in node:
            lx = walk(node["left_child"], depth + 1)
            label = _node_label(node, feature_names, show_info, precision)
            x = x_counter[0]
            x_counter[0] += 1
            rx = walk(node["right_child"], depth + 1)
            positions.append((x, -depth, label))
            return x
        label = _node_label(node, feature_names, show_info, precision)
        x = x_counter[0]
        x_counter[0] += 1
        positions.append((x, -depth, label))
        return x

    walk(tree_info["tree_structure"], 0)
    for x, y, label in positions:
        ax.text(x, y, label, ha="center", va="center", fontsize=7,
                bbox=dict(boxstyle="round", fc="lightyellow", ec="gray"))
    xs = [p[0] for p in positions]
    ys = [p[1] for p in positions]
    ax.set_xlim(min(xs) - 1, max(xs) + 1)
    ax.set_ylim(min(ys) - 1, max(ys) + 1)
    ax.axis("off")
    return ax
