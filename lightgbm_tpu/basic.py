"""Dataset: binned training container + metadata.

Mirrors the reference's Python ``Dataset`` API surface
(reference: python-package/lightgbm/basic.py:1195+) on top of the core data
layer (reference: src/io/dataset.cpp Dataset, src/io/metadata.cpp Metadata,
src/io/dataset_loader.cpp DatasetLoader):

- lazy construction (bin mappers fitted on first use, basic.py:1195),
- validation sets aligned to the training set's bin mappers via ``reference``
  (reference: DatasetLoader::LoadFromFileAlignWithOtherDataset,
  dataset_loader.cpp:262-314),
- metadata fields label/weight/group/init_score with ``set_field``/
  ``get_field`` (reference: dataset.h:41-249 Metadata),
- trivial (single-bin) features dropped from the device matrix the way the
  reference drops unused features (``used_feature_map_``, dataset.cpp).

The binned matrix lives device-resident as ``[N, F_used]`` uint8/int32 — the
TPU analog of the reference's FeatureGroup bin storage (dense_bin.hpp), laid
out row-major for row-blocked histogram kernels. EFB bundling
(feature_group.h) is unnecessary for dense device storage and is not applied.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from . import binning
from .config import Config
from .ops.split import FeatureMeta
from .utils import log


def _to_2d_float(data) -> np.ndarray:
    if hasattr(data, "values"):  # pandas DataFrame/Series
        data = data.values
    if hasattr(data, "toarray"):  # scipy sparse
        data = data.toarray()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


class Dataset:
    """Training/validation data container (reference: basic.py Dataset)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List[int], List[str]] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._constructed = False
        # populated by construct():
        self.mappers: List[binning.BinMapper] = []
        self.used_features: np.ndarray = np.array([], dtype=np.int32)
        self.bins: Optional[jnp.ndarray] = None       # [N, F_used] device
        self.num_data: int = 0
        self.num_total_features: int = 0
        # per-column category lists for pandas category dtypes; raw values
        # are mapped to these codes at train AND predict time (reference:
        # basic.py:504-568 pandas_categorical capture)
        self.pandas_categorical: Dict[int, list] = {}

    # ------------------------------------------------------------ fields
    def set_label(self, label):
        self.label = label
        return self

    def set_weight(self, weight):
        self.weight = weight
        return self

    def set_group(self, group):
        self.group = group
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        return self

    def set_field(self, name: str, data):
        if name == "label":
            self.label = data
        elif name == "weight":
            self.weight = data
        elif name == "group":
            self.group = data
        elif name == "init_score":
            self.init_score = data
        else:
            log.fatal(f"Unknown field: {name}")
        return self

    def get_field(self, name: str):
        return {"label": self.get_label(), "weight": self.get_weight(),
                "group": self.group, "init_score": self.init_score}[name]

    def get_label(self) -> Optional[np.ndarray]:
        return None if self.label is None else np.asarray(
            self.label.values if hasattr(self.label, "values") else self.label,
            dtype=np.float64).reshape(-1)

    def get_weight(self) -> Optional[np.ndarray]:
        return None if self.weight is None else np.asarray(
            self.weight, dtype=np.float64).reshape(-1)

    def get_group(self) -> Optional[np.ndarray]:
        if self.group is None:
            return None
        return np.asarray(self.group, dtype=np.int64).reshape(-1)

    def num_feature(self) -> int:
        self.construct()
        return self.num_total_features

    def get_feature_names(self) -> List[str]:
        self.construct()
        return self._feature_names

    # --------------------------------------------------------- construct
    def _resolve_categorical(self, num_features: int,
                             names: List[str]) -> List[int]:
        cf = self.categorical_feature
        if cf == "auto" or cf is None:
            # pandas categorical dtype capture (reference: basic.py:504-568)
            if hasattr(self.data, "dtypes"):
                return [i for i, dt in enumerate(self.data.dtypes)
                        if str(dt) in ("category",)]
            return []
        out = []
        for c in cf:
            if isinstance(c, str):
                if c in names:
                    out.append(names.index(c))
            else:
                out.append(int(c))
        return out

    def _pandas_to_codes(self, raw):
        """Convert pandas category-dtype columns to codes, capturing (train)
        or reusing (predict) the category lists so train and predict agree
        (reference: basic.py:504-568 _data_from_pandas pandas_categorical)."""
        if not hasattr(raw, "dtypes"):
            return raw
        import pandas as pd  # noqa: F401
        raw = raw.copy()
        for ci, col in enumerate(raw.columns):
            if str(raw[col].dtype) != "category":
                continue
            if ci in self.pandas_categorical:
                cats = self.pandas_categorical[ci]
                codes = pd.Categorical(raw[col], categories=cats).codes
            else:
                self.pandas_categorical[ci] = list(raw[col].cat.categories)
                codes = raw[col].cat.codes
            # unseen categories -> -1 -> NaN (routes to the other/NaN bin)
            raw[col] = np.where(np.asarray(codes) >= 0,
                                np.asarray(codes, dtype=np.float64), np.nan)
        return raw

    def construct(self) -> "Dataset":
        if self._constructed:
            return self
        config = Config.from_params(self.params)
        if self.reference is not None:
            self.pandas_categorical = self.reference.construct().pandas_categorical
        raw = self._pandas_to_codes(self.data)
        X = _to_2d_float(raw)
        self.num_data, self.num_total_features = X.shape
        if self.feature_name == "auto" or self.feature_name is None:
            if hasattr(self.data, "columns"):
                self._feature_names = [str(c) for c in self.data.columns]
            else:
                self._feature_names = [f"Column_{i}" for i in range(self.num_total_features)]
        else:
            self._feature_names = list(self.feature_name)

        if self.reference is not None:
            ref = self.reference.construct()
            if self.num_total_features != ref.num_total_features:
                log.fatal("validation data has different number of features")
            self.mappers = ref.mappers
            self.used_features = ref.used_features
            self._feature_meta = ref._feature_meta
            self._missing_bin = ref._missing_bin
            self.max_num_bins = ref.max_num_bins
            self.has_categorical = ref.has_categorical
        else:
            cats = self._resolve_categorical(self.num_total_features, self._feature_names)
            self.mappers = binning.find_bin_mappers(X, config, cats)
            self.used_features = np.array(
                [j for j, m in enumerate(self.mappers) if not m.is_trivial],
                dtype=np.int32)
            if len(self.used_features) == 0:
                log.warning("There are no meaningful features, as all feature values"
                            " are constant.")
            self._build_feature_meta(config)

        used = [self.mappers[j] for j in self.used_features]
        Xu = X[:, self.used_features] if len(self.used_features) else np.zeros((self.num_data, 0))
        bins_np = binning.bin_data(Xu, used)
        dtype = np.uint8 if self.max_num_bins <= 256 else np.int32
        self.bins = jnp.asarray(bins_np.astype(dtype))
        # raw feature retention for linear trees (reference: dataset.h:720
        # raw_data_, kept when linear_tree so leaves can fit linear models)
        keep_raw = config.linear_tree or (
            self.reference is not None
            and getattr(self.reference, "raw_data_np", None) is not None)
        self.raw_data_np = X.astype(np.float32) if keep_raw else None
        self._constructed = True
        if self.free_raw_data:
            self.data = None
        total_bins = int(sum(m.num_bin for m in used))
        log.info(f"Total Bins {total_bins}")
        log.info(f"Number of data points in the train set: {self.num_data}, "
                 f"number of used features: {len(self.used_features)}")
        return self

    def _build_feature_meta(self, config: Config):
        used = [self.mappers[j] for j in self.used_features]
        nb = np.array([m.num_bin for m in used], dtype=np.int32)
        self.max_num_bins = int(nb.max()) if len(nb) else 2
        missing = np.array([m.missing_type for m in used], dtype=np.int32)
        default_bin = np.array([m.default_bin for m in used], dtype=np.int32)
        is_cat = np.array([m.bin_type == binning.BIN_TYPE_CATEGORICAL for m in used])
        # missing_bin: the bin routed by the split's default direction, or -1
        # (mode analysis in ops/split.py docstring)
        mode_a = (nb > 2) & (missing != binning.MISSING_NONE)
        missing_bin = np.where(mode_a & (missing == binning.MISSING_NAN), nb - 1,
                               np.where(mode_a & (missing == binning.MISSING_ZERO),
                                        default_bin, -1)).astype(np.int32)
        self.has_categorical = bool(is_cat.any())
        f = max(len(used), 1)
        # per-feature monotone direction and contri multiplier, mapped from
        # ORIGINAL feature indices to used-feature space (reference:
        # feature_histogram.hpp:1170-1177 FeatureMetainfo init)
        monotone = np.zeros((f,), dtype=np.int8)
        mc = list(config.monotone_constraints or [])
        if mc and len(mc) != self.num_total_features:
            log.fatal(f"monotone_constraints should be the same size as "
                      f"feature number ({self.num_total_features}), "
                      f"got {len(mc)}")
        for i, j in enumerate(self.used_features):
            if j < len(mc):
                monotone[i] = np.int8(mc[j])
        penalty = np.ones((f,), dtype=np.float32)
        fc = list(config.feature_contri or [])
        if fc and len(fc) != self.num_total_features:
            log.fatal(f"feature_contri should be the same size as feature "
                      f"number ({self.num_total_features}), got {len(fc)}")
        for i, j in enumerate(self.used_features):
            if j < len(fc):
                penalty[i] = np.float32(fc[j])
        self._feature_meta = FeatureMeta(
            num_bins=jnp.asarray(nb if len(nb) else np.array([2], np.int32)),
            missing_type=jnp.asarray(missing if len(missing) else np.zeros(1, np.int32)),
            default_bin=jnp.asarray(default_bin if len(default_bin) else np.zeros(1, np.int32)),
            is_categorical=jnp.asarray(is_cat if len(is_cat) else np.zeros(1, bool)),
            monotone=jnp.asarray(monotone),
            penalty=jnp.asarray(penalty),
        )
        self._missing_bin = jnp.asarray(missing_bin if len(missing_bin)
                                        else np.full(1, -1, np.int32))

    # ------------------------------------------------------- helpers
    @property
    def feature_meta(self) -> FeatureMeta:
        self.construct()
        return self._feature_meta

    @property
    def missing_bin(self):
        self.construct()
        return self._missing_bin

    @property
    def bins_T(self):
        """Feature-major [F, N] copy of the bin matrix, built lazily: split
        routing extracts one feature column per split, which on TPU is a
        contiguous slice here vs a strided read of the whole row-major
        matrix (reference keeps per-feature bin arrays natively,
        dense_bin.hpp)."""
        self.construct()
        if getattr(self, "_bins_T", None) is None:
            self._bins_T = jnp.asarray(self.bins.T)
        return self._bins_T

    def num_used_features(self) -> int:
        self.construct()
        return max(len(self.used_features), 1)

    def bin_new_data(self, X) -> np.ndarray:
        """Bin raw features with this dataset's mappers (prediction path)."""
        self.construct()
        X = _to_2d_float(self._pandas_to_codes(X))
        if X.shape[1] != self.num_total_features:
            log.fatal(f"The number of features in data ({X.shape[1]}) is not the same"
                      f" as it was in training data ({self.num_total_features}).")
        used = [self.mappers[j] for j in self.used_features]
        Xu = X[:, self.used_features] if len(self.used_features) else np.zeros((len(X), 0))
        return binning.bin_data(Xu, used)

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        """Row subset sharing this dataset's mappers (reference: basic.py
        Dataset.subset / CopySubrow, dataset.h:416). Requires raw data."""
        if self.data is None:
            log.fatal("Cannot subset a Dataset whose raw data was freed")
        idx = np.asarray(used_indices)
        data = self.data.iloc[idx] if hasattr(self.data, "iloc") else _to_2d_float(self.data)[idx]
        lbl = self.get_label()
        w = self.get_weight()
        return Dataset(data, label=None if lbl is None else lbl[idx],
                       reference=self,
                       weight=None if w is None else w[idx],
                       params=params or self.params)
