"""Dataset: binned training container + metadata.

Mirrors the reference's Python ``Dataset`` API surface
(reference: python-package/lightgbm/basic.py:1195+) on top of the core data
layer (reference: src/io/dataset.cpp Dataset, src/io/metadata.cpp Metadata,
src/io/dataset_loader.cpp DatasetLoader):

- lazy construction (bin mappers fitted on first use, basic.py:1195),
- validation sets aligned to the training set's bin mappers via ``reference``
  (reference: DatasetLoader::LoadFromFileAlignWithOtherDataset,
  dataset_loader.cpp:262-314),
- metadata fields label/weight/group/init_score with ``set_field``/
  ``get_field`` (reference: dataset.h:41-249 Metadata),
- trivial (single-bin) features dropped from the device matrix the way the
  reference drops unused features (``used_feature_map_``, dataset.cpp).

The binned matrix lives device-resident as ``[N, F_used]`` uint8/int32 — the
TPU analog of the reference's FeatureGroup bin storage (dense_bin.hpp), laid
out row-major for row-blocked histogram kernels. EFB bundling IS applied on
the sparse construction path (``_construct_sparse`` -> bundling.py, the
analog of dataset.cpp:239 FastFeatureBundling): mutually-exclusive sparse
features share one dense device column each, so the matrix is ``[N, G]``
with G ~ bundles rather than features; dense float input skips bundling
(every column already owns its device column). High-sparsity columns can
further drop out of the dense matrix entirely into (row, bin) streams
(``_maybe_extract_sparse``, the SparseBin analog).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import binning
from .config import Config
from .ops.split import FeatureMeta
from .utils import log


def _to_2d_float(data) -> np.ndarray:
    if hasattr(data, "values"):  # pandas DataFrame/Series
        data = data.values
    if hasattr(data, "toarray"):  # scipy sparse
        data = data.toarray()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


def _is_scipy_sparse(data) -> bool:
    """scipy CSR/CSC/COO — handled without densifying (the reference's
    sparse-input path, c_api.h LGBM_DatasetCreateFromCSR/CSC)."""
    return (hasattr(data, "tocsc") and hasattr(data, "nnz")
            and not hasattr(data, "values"))


def _load_forced_bins(config: Config, num_features: int,
                      categorical: Sequence[int]) -> Dict[int, List[float]]:
    """Forced bin upper bounds from JSON (reference:
    DatasetLoader::GetForcedBins, dataset_loader.cpp:1373-1408; format
    [{"feature": i, "bin_upper_bound": [...]}, ...])."""
    if not config.forcedbins_filename:
        return {}
    import json
    try:
        with open(config.forcedbins_filename) as fh:
            arr = json.load(fh)
    except OSError:
        log.warning(f"Could not open {config.forcedbins_filename}. "
                    f"Will ignore.")
        return {}
    cats = set(int(c) for c in categorical)
    out: Dict[int, List[float]] = {}
    for entry in arr:
        j = int(entry["feature"])
        if j >= num_features:
            log.fatal(f"forced bins feature index {j} out of range")
        if j in cats:
            log.warning(f"Feature {j} is categorical. Will ignore forced "
                        f"bins for this feature.")
            continue
        bounds = [float(v) for v in entry["bin_upper_bound"]]
        deduped = []
        for v in bounds:      # remove consecutive duplicates (reference)
            if not deduped or v != deduped[-1]:
                deduped.append(v)
        out[j] = deduped
    return out


class Dataset:
    """Training/validation data container (reference: basic.py Dataset)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List[int], List[str]] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        # chunk-source streaming construction (from_chunks): a re-iterable
        # chunk stream instead of a monolithic matrix
        self._chunk_source = None
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._constructed = False
        # populated by construct():
        self.mappers: List[binning.BinMapper] = []
        self.used_features: np.ndarray = np.array([], dtype=np.int32)
        self.bins: Optional[jnp.ndarray] = None       # [N, F_used] device
        self.num_data: int = 0
        self.num_total_features: int = 0
        # per-column category lists for pandas category dtypes; raw values
        # are mapped to these codes at train AND predict time (reference:
        # basic.py:504-568 pandas_categorical capture)
        self.pandas_categorical: Dict[int, list] = {}
        # EFB bundles (bundling.py): None = plain per-feature columns
        self.bundles = None
        # sparse device storage (see _maybe_extract_sparse): None = all
        # device columns dense
        self.sp_cols = None
        self.sp_rows = None
        self.sp_bins = None
        self.sp_default = None

    # ------------------------------------------------------------ fields
    def set_label(self, label):
        self.label = label
        return self

    def set_weight(self, weight):
        self.weight = weight
        return self

    def set_group(self, group):
        self.group = group
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        return self

    def set_field(self, name: str, data):
        if name == "label":
            self.label = data
        elif name == "weight":
            self.weight = data
        elif name == "group":
            self.group = data
        elif name == "init_score":
            self.init_score = data
        else:
            log.fatal(f"Unknown field: {name}")
        return self

    def get_field(self, name: str):
        return {"label": self.get_label(), "weight": self.get_weight(),
                "group": self.group, "init_score": self.init_score}[name]

    def get_label(self) -> Optional[np.ndarray]:
        return None if self.label is None else np.asarray(
            self.label.values if hasattr(self.label, "values") else self.label,
            dtype=np.float64).reshape(-1)

    def get_weight(self) -> Optional[np.ndarray]:
        return None if self.weight is None else np.asarray(
            self.weight, dtype=np.float64).reshape(-1)

    def get_group(self) -> Optional[np.ndarray]:
        if self.group is None:
            return None
        return np.asarray(self.group, dtype=np.int64).reshape(-1)

    def num_feature(self) -> int:
        self.construct()
        return self.num_total_features

    def get_feature_names(self) -> List[str]:
        self.construct()
        return self._feature_names

    # ------------------------------------------ reference API completeness
    def get_feature_name(self) -> List[str]:
        """reference: basic.py Dataset.get_feature_name."""
        return self.get_feature_names()

    def get_data(self):
        """Raw data if still held (reference: Dataset.get_data; raises the
        same way once free_raw_data has dropped it)."""
        if self._constructed and self.data is None:
            log.fatal("Cannot call get_data after freeing raw data, "
                      "set free_raw_data=False when constructing the Dataset")
        return self.data

    def get_init_score(self) -> Optional[np.ndarray]:
        return None if self.init_score is None else np.asarray(
            self.init_score, dtype=np.float64)

    def get_params(self) -> dict:
        """reference: Dataset.get_params (the dataset-relevant params)."""
        return dict(self.params)

    def get_ref_chain(self, ref_limit: int = 100):
        """The chain of reference datasets (reference: Dataset.get_ref_chain)."""
        chain, seen = [], set()
        cur = self
        while cur is not None and id(cur) not in seen \
                and len(chain) < ref_limit:
            chain.append(cur)
            seen.add(id(cur))
            cur = cur.reference
        return chain

    def set_feature_name(self, feature_name) -> "Dataset":
        """reference: Dataset.set_feature_name (pre-construct)."""
        if self._constructed:
            log.fatal("set_feature_name after construct is not supported")
        self.feature_name = list(feature_name)
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """reference: Dataset.set_categorical_feature (pre-construct)."""
        if self._constructed:
            log.fatal("set_categorical_feature after construct is not "
                      "supported; pass it to the Dataset constructor")
        self.categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """reference: Dataset.set_reference (align to a train set's
        binning; pre-construct)."""
        if self._constructed:
            log.fatal("set_reference after construct is not supported")
        self.reference = reference
        return self

    def save_binary(self, filename: str) -> "Dataset":
        """Serialize to the .bin snapshot format the CLI's save_binary task
        writes (reference: Dataset.save_binary -> SaveBinaryFile; loadable
        with data=<file>.bin / lgb.Dataset(path))."""
        if self.data is None:
            log.fatal("save_binary needs the raw data (free_raw_data=False)")
        if _is_scipy_sparse(self.data):
            # the .bin format stores dense float arrays (cli._save_binary /
            # np.load with allow_pickle=False); a pickled sparse object
            # would save fine and then fail to load
            log.fatal("save_binary does not support scipy-sparse data")
        if self.label is None:
            log.fatal("save_binary needs a label")
        from .cli import _save_binary
        X = _to_2d_float(self._pandas_to_codes(self.data))
        _save_binary(filename, X, self.get_label(), self.get_weight(),
                     self.get_group(), self.get_init_score())
        return self

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Column-wise merge of another dataset's features (reference:
        Dataset.add_features_from). Both must still hold raw data; the
        merged dataset re-bins from scratch."""
        if self.data is None or other.data is None:
            log.fatal("add_features_from needs raw data on both datasets "
                      "(free_raw_data=False)")
        a = _to_2d_float(self._pandas_to_codes(self.data))
        b = _to_2d_float(other._pandas_to_codes(other.data))
        if a.shape[0] != b.shape[0]:
            log.fatal("add_features_from: row counts differ "
                      f"({a.shape[0]} vs {b.shape[0]})")
        self.data = np.column_stack([a, b])
        if self.feature_name not in ("auto", None) \
                and other.feature_name not in ("auto", None):
            self.feature_name = list(self.feature_name) + \
                list(other.feature_name)
        else:
            self.feature_name = "auto"
        # merge categorical designations (other's indices shift by our
        # original width); name-based entries carry over as-is
        def _cats(ds, offset):
            cf = ds.categorical_feature
            if cf in ("auto", None):
                return []
            return [c if isinstance(c, str) else int(c) + offset
                    for c in cf]
        merged = _cats(self, 0) + _cats(other, a.shape[1])
        if merged:
            self.categorical_feature = merged
        self._constructed = False
        return self

    # --------------------------------------------------------- construct
    def _resolve_categorical(self, num_features: int,
                             names: List[str]) -> List[int]:
        cf = self.categorical_feature
        if cf == "auto" or cf is None:
            # pandas categorical dtype capture (reference: basic.py:504-568)
            if hasattr(self.data, "dtypes"):
                return [i for i, dt in enumerate(self.data.dtypes)
                        if str(dt) in ("category",)]
            return []
        out = []
        for c in cf:
            if isinstance(c, str):
                if c in names:
                    out.append(names.index(c))
            else:
                out.append(int(c))
        return out

    def _pandas_to_codes(self, raw):
        """Convert pandas category-dtype columns to codes, capturing (train)
        or reusing (predict) the category lists so train and predict agree
        (reference: basic.py:504-568 _data_from_pandas pandas_categorical)."""
        if not hasattr(raw, "dtypes"):
            return raw
        import pandas as pd  # noqa: F401
        raw = raw.copy()
        for ci, col in enumerate(raw.columns):
            if str(raw[col].dtype) != "category":
                continue
            if ci in self.pandas_categorical:
                cats = self.pandas_categorical[ci]
                codes = pd.Categorical(raw[col], categories=cats).codes
            else:
                self.pandas_categorical[ci] = list(raw[col].cat.categories)
                codes = raw[col].cat.codes
            # unseen categories -> -1 -> NaN (routes to the other/NaN bin)
            raw[col] = np.where(np.asarray(codes) >= 0,
                                np.asarray(codes, dtype=np.float64), np.nan)
        return raw

    @classmethod
    def from_chunks(cls, chunks, label=None, reference: Optional["Dataset"]
                    = None, weight=None, group=None, init_score=None,
                    feature_name: Union[str, List[str]] = "auto",
                    categorical_feature: Union[str, List[int], List[str]]
                    = "auto",
                    params: Optional[Dict[str, Any]] = None,
                    free_raw_data: bool = True) -> "Dataset":
        """Dataset over a CHUNK STREAM instead of a monolithic matrix —
        the O(chunk)-host-memory construction front end (ISSUE 14). The
        raw feature matrix never materializes: construction runs two
        passes over the source (a streaming quantile/frequency sketch
        pass that fits the bin mappers, then a device bin pass writing
        each quantized chunk into its slot of the ``[N, F]`` bin matrix,
        H2D overlapped with host parsing).

        ``chunks`` is a callable returning a fresh iterator of chunks, a
        sequence of chunk arrays, or a 2-D array (sliced into
        ``construct_chunk_rows`` views). Each chunk is ``[rows, F]`` or
        an ``(X, y)`` pair — per-chunk labels concatenate into the
        dataset label (pass ``label=`` OR chunk labels, not both).
        Pre-partitioned multi-host loading wants
        ``distributed.load_partitioned_chunks`` instead (it merges the
        per-rank sketches over ``exchange_host``)."""
        ds = cls(None, label=label, reference=reference, weight=weight,
                 group=group, init_score=init_score,
                 feature_name=feature_name,
                 categorical_feature=categorical_feature, params=params,
                 free_raw_data=free_raw_data)
        ds._chunk_source = chunks
        return ds

    def construct(self, streaming: Optional[bool] = None) -> "Dataset":
        if self._constructed:
            return self
        config = Config.from_params(self.params)
        stream = self._chunk_source is not None or (
            streaming if streaming is not None
            else config.construct_streaming)
        if stream:
            return self._construct_streaming(config)
        if _is_scipy_sparse(self.data) or (
                self.reference is not None
                and getattr(self.reference.construct(), "bundles", None)
                is not None):
            return self._construct_sparse(config)
        self.bundles = None
        if self.reference is not None:
            self.pandas_categorical = self.reference.construct().pandas_categorical
        raw = self._pandas_to_codes(self.data)
        X = _to_2d_float(raw)
        self.num_data, self.num_total_features = X.shape
        if self.feature_name == "auto" or self.feature_name is None:
            if hasattr(self.data, "columns"):
                self._feature_names = [str(c) for c in self.data.columns]
            else:
                self._feature_names = [f"Column_{i}" for i in range(self.num_total_features)]
        else:
            self._feature_names = list(self.feature_name)

        if self.reference is not None:
            ref = self.reference.construct()
            if self.num_total_features != ref.num_total_features:
                log.fatal("validation data has different number of features")
            self.mappers = ref.mappers
            self.used_features = ref.used_features
            self._feature_meta = ref._feature_meta
            self._missing_bin = ref._missing_bin
            self.max_num_bins = ref.max_num_bins
            self.has_categorical = ref.has_categorical
        else:
            cats = self._resolve_categorical(self.num_total_features, self._feature_names)
            forced = _load_forced_bins(config, self.num_total_features, cats)
            self.mappers = binning.find_bin_mappers(X, config, cats,
                                                    forced_bounds=forced)
            self.used_features = np.array(
                [j for j, m in enumerate(self.mappers) if not m.is_trivial],
                dtype=np.int32)
            if len(self.used_features) == 0:
                log.warning("There are no meaningful features, as all feature values"
                            " are constant.")
            self._build_feature_meta(config)

        used = [self.mappers[j] for j in self.used_features]
        dtype = np.uint8 if self.max_num_bins <= 256 else np.int32
        raw_np = raw.values if hasattr(raw, "values") else raw
        # float32 input on a TPU backend quantizes ON DEVICE (bit-exact vs
        # the host path, see binning.device_bin_tables): the host
        # searchsorted loop is the construct bottleneck on small hosts
        # (reference bins at memory speed with OpenMP, dense_bin.hpp)
        use_device = (jax.default_backend() == "tpu"
                      and len(self.used_features)
                      and isinstance(raw_np, np.ndarray) and raw_np.ndim == 2
                      and raw_np.dtype == np.float32
                      and all(m.bin_type == binning.BIN_TYPE_NUMERICAL
                              for m in used))
        if use_device:
            Xu32 = raw_np if len(used) == raw_np.shape[1] \
                else np.ascontiguousarray(raw_np[:, self.used_features])
            self.bins = binning.bin_data_device(Xu32, used)
        else:
            Xu = X[:, self.used_features] if len(self.used_features) \
                else np.zeros((self.num_data, 0))
            bins_np = binning.bin_data(Xu, used).astype(dtype)
            bins_np = self._maybe_extract_sparse(bins_np, config)
            self.bins = jnp.asarray(bins_np)
        # raw feature retention for linear trees (reference: dataset.h:720
        # raw_data_, kept when linear_tree so leaves can fit linear models)
        keep_raw = config.linear_tree or (
            self.reference is not None
            and getattr(self.reference, "raw_data_np", None) is not None)
        self.raw_data_np = X.astype(np.float32) if keep_raw else None
        self._constructed = True
        if self.free_raw_data:
            self.data = None
        total_bins = int(sum(m.num_bin for m in used))
        log.info(f"Total Bins {total_bins}")
        log.info(f"Number of data points in the train set: {self.num_data}, "
                 f"number of used features: {len(self.used_features)}")
        return self

    # ------------------------------------------------ streaming construct
    def _construct_streaming(self, config: Config) -> "Dataset":
        """Two-pass chunked construction: host memory is O(chunk), never
        O(N*F) raw (the 10.5M-row monolithic construct held a 1.2 GB f32
        matrix before binning; at 100M rows that ceiling is fatal —
        ROADMAP item 2).

        Pass 1 (``sketch_pass``): fold each chunk into per-feature
        mergeable :class:`binning.FeatureSketch` es and fit BinMappers
        from the merged summaries — bit-identical to the sampled
        ``find_bin_mappers`` whenever one chunk covers the sample (the
        sketches stay exact and the sample is all rows). Pass 2
        (``bin_pass``): quantize each chunk on device and write it into
        its row slot of the preallocated bin matrix
        (:class:`binning.StreamingBinWriter`), the async dispatch queue
        double-buffering chunk k's H2D against chunk k+1's host parse;
        the blocking drain at the end is the ``h2d_overlap`` sub-scope.
        Non-float32 or categorical-bearing streams take a host per-chunk
        ``bin_data`` fallback (same O(chunk) raw residency).

        Always-on gauges: ``construct_sketch_s`` / ``construct_bin_s`` /
        ``construct_h2d_overlap_s`` / ``construct_peak_bytes`` (max raw
        chunk bytes resident, <= 2 chunks) / ``construct_rows`` — the
        flight-recorder header and bench.py's construct fields read them
        (telemetry.construct_snapshot). EFB bundling and sparse-column
        extraction do not apply (dense chunk input, like the dense
        monolithic path); ``linear_tree`` needs the raw matrix resident
        and is rejected."""
        import time as _time
        from .utils import profiling

        if config.linear_tree:
            log.fatal("linear_tree keeps the raw matrix resident and is "
                      "not supported with streaming construction")
        source = self._chunk_source if self._chunk_source is not None \
            else self.data
        if _is_scipy_sparse(source) or hasattr(source, "dtypes"):
            log.fatal("streaming construction supports dense arrays or "
                      "chunk sources only (scipy-sparse and pandas input "
                      "take the monolithic paths)")
        # the process-level construct_* gauges describe the LAST streaming
        # construction (bench/smoke read them right after constructing);
        # per-dataset attribution rides self.construct_stats instead
        profiling.drop_gauges("construct_")
        factory = binning.chunk_factory(source, config.construct_chunk_rows)
        peak = [0]

        def track(nbytes, mult=1):
            peak[0] = max(peak[0], mult * int(nbytes))

        t0 = _time.time()
        # aligned valid sets take the LIGHT pass (fold=False): their
        # mappers come from the reference, so only row/size/label
        # accounting (and the mid-stream width check) is needed — the
        # per-column fold is the dominant sketch wall
        with profiling.timer("sketch_pass"):
            sketches, num_data, sizes, chunk_labels = binning.sketch_chunks(
                factory, max_size=config.sketch_max_size, track_bytes=track,
                fold=self.reference is None)
        num_features = len(sketches)
        if self.reference is not None:
            sketches = None
        sketch_s = _time.time() - t0
        self.num_data, self.num_total_features = num_data, num_features
        if chunk_labels is not None:
            if self.label is not None:
                log.fatal("labels were passed both to the Dataset and in "
                          "the chunk stream; pass one or the other")
            self.label = chunk_labels
        if self.feature_name == "auto" or self.feature_name is None:
            self._feature_names = [f"Column_{i}"
                                   for i in range(self.num_total_features)]
        else:
            self._feature_names = list(self.feature_name)
        self.bundles = None

        if self.reference is not None:
            ref = self.reference.construct()
            if getattr(ref, "bundles", None) is not None:
                log.fatal("streaming construction cannot align to an "
                          "EFB-bundled reference dataset")
            if self.num_total_features != ref.num_total_features:
                log.fatal("validation data has different number of features")
            self.mappers = ref.mappers
            self.used_features = ref.used_features
            self._feature_meta = ref._feature_meta
            self._missing_bin = ref._missing_bin
            self.max_num_bins = ref.max_num_bins
            self.has_categorical = ref.has_categorical
            self.pandas_categorical = ref.pandas_categorical
        else:
            cats = self._resolve_categorical(self.num_total_features,
                                             self._feature_names)
            forced = _load_forced_bins(config, self.num_total_features, cats)
            self.mappers = binning.fit_mappers_from_sketches(
                sketches, num_data, config, cats, forced_bounds=forced)
            self.used_features = np.array(
                [j for j, m in enumerate(self.mappers) if not m.is_trivial],
                dtype=np.int32)
            if len(self.used_features) == 0:
                log.warning("There are no meaningful features, as all "
                            "feature values are constant.")
            self._build_feature_meta(config)
        del sketches

        used = [self.mappers[j] for j in self.used_features]
        uf = self.used_features
        all_numeric = all(m.bin_type == binning.BIN_TYPE_NUMERICAL
                          for m in used)
        max_chunk = max(sizes) if sizes else 1
        t0 = _time.time()
        overlap_s = 0.0
        # device writer only for float32 streams: it is bit-exact vs the
        # host path for f32 input (device_bin_tables), while a silent
        # f64 -> f32 cast could move values across bin bounds
        it = iter(factory())
        first_chunk = next(it, None)
        if first_chunk is None:
            log.fatal("chunk source yielded no chunks on the bin pass "
                      "(but did on the sketch pass): the source must be "
                      "re-iterable — a callable must return a FRESH "
                      "iterator per call, not a shared one-shot "
                      "generator")
        first = binning.split_chunk(first_chunk)[0]
        first_chunk = None
        use_device = (all_numeric and len(used)
                      and isinstance(first, np.ndarray)
                      and first.dtype == np.float32)
        if use_device:
            writer = binning.StreamingBinWriter(used, num_data, max_chunk)
            staged_bytes = writer.chunk_pad * writer.f * 4

            def _write(X):
                if X.dtype != np.float32:
                    # the f32 device-path decision was made on the FIRST
                    # chunk; a later wider-dtype chunk silently cast to
                    # f32 could land values in the wrong bin
                    log.fatal(
                        f"chunk dtype changed mid-stream ({X.dtype} after "
                        f"float32): streaming construction requires a "
                        f"uniform chunk dtype — make every chunk float32, "
                        f"or every chunk float64 for the exact host path")
                if len(uf) == X.shape[1]:
                    # resident: the source chunk + the in-flight staged copy
                    track(X.nbytes + staged_bytes)
                    writer.write(X)
                else:
                    Xu = np.ascontiguousarray(X[:, uf])
                    # resident: chunk + column-subset copy + staged copy
                    track(X.nbytes + Xu.nbytes + staged_bytes)
                    writer.write(Xu)

            with profiling.timer("bin_pass"):
                _write(first)
                first = None
                while True:                    # ref-dropping next() loop
                    chunk = next(it, None)
                    if chunk is None:
                        break
                    X = binning.split_chunk(chunk)[0]
                    chunk = None
                    _write(X)
                    X = None
                t1 = _time.time()
                with profiling.timer("h2d_overlap"):
                    self.bins = writer.finalize()
                overlap_s = _time.time() - t1
        else:
            dtype = np.uint8 if self.max_num_bins <= 256 else np.int32
            bins_np = np.zeros((num_data, max(len(uf), 1)), dtype)
            first = it = None              # host helper re-iterates itself
            with profiling.timer("bin_pass"):
                binning.bin_chunks_host(factory, used, uf, bins_np, track)
                t1 = _time.time()
                with profiling.timer("h2d_overlap"):
                    self.bins = jnp.asarray(bins_np)
                    jax.block_until_ready(self.bins)
                overlap_s = _time.time() - t1
        bin_s = _time.time() - t0

        profiling.set_gauge("construct_sketch_s", sketch_s)
        profiling.set_gauge("construct_bin_s", bin_s)
        profiling.set_gauge("construct_h2d_overlap_s", overlap_s)
        profiling.set_gauge("construct_peak_bytes", float(peak[0]))
        profiling.set_gauge("construct_rows", float(num_data))
        # per-dataset attribution (the flight-recorder header reads THIS,
        # not the process gauges, so a later construct cannot steal or
        # wipe the training set's stats)
        self.construct_stats = {
            "sketch_pass": round(sketch_s, 6),
            "bin_pass": round(bin_s, 6),
            "h2d_overlap": round(overlap_s, 6),
            "peak_host_bytes": int(peak[0]),
            "rows": int(num_data),
        }
        # no monolithic raw reference may survive a streaming construct
        # (the whole point is that it never existed)
        self.sp_cols = self.sp_rows = self.sp_bins = self.sp_default = None
        self.raw_data_np = None
        self._constructed = True
        if self.free_raw_data:
            self.data = None
            self._chunk_source = None
        total_bins = int(sum(m.num_bin for m in used))
        log.info(f"Total Bins {total_bins}")
        log.info(f"Number of data points in the train set: {self.num_data},"
                 f" number of used features: {len(self.used_features)} "
                 f"(streaming construct: {len(sizes)} chunks, peak raw "
                 f"{peak[0]} bytes, sketch {sketch_s:.2f}s + bin "
                 f"{bin_s:.2f}s, drain {overlap_s:.2f}s)")
        return self

    @property
    def has_sparse_cols(self) -> bool:
        return self.sp_cols is not None and len(self.sp_cols) > 0

    def _maybe_extract_sparse(self, bins_np: np.ndarray,
                              config: Config) -> np.ndarray:
        """Sparse device storage for heavily-concentrated columns — the TPU
        re-design of the reference's SparseBin (reference: sparse_bin.hpp
        delta/val streams chosen when sparse_rate > kSparseThreshold=0.7,
        bin.h:39, with the elided most-frequent bin reconstructed by
        FixHistogram, dataset.cpp FixHistogram decl dataset.h:506).

        A device column whose most-frequent bin covers >= 90% of rows is
        dropped from the dense [N, F] matrix and stored as padded
        (row, bin) streams [F_sp, M] holding only the NON-default entries;
        histogram planes for these columns scatter-add O(nnz) entries per
        pass and the default-bin cell is reconstructed from the per-leaf
        totals (exactly the reference's most_freq elision + FixHistogram).
        The threshold is 0.9 (not the reference's 0.7): a stream entry
        costs 5 bytes (int32 row + uint8 bin) against 1 byte/row dense, so
        the memory break-even sits at 80% concentration, and TPU
        scatter-adds are slow enough that the pass-cost win also needs the
        nnz fraction small. Applies to the primary training dataset on the
        serial learner only: aligned validation sets stay dense (their
        bins are traversed per tree), and the distributed learners shard
        dense columns.
        """
        threshold, min_rows = 0.90, 512
        if (not config.is_enable_sparse or self.reference is not None
                or config.linear_tree
                or getattr(self, "is_pre_partitioned", False)
                or str(config.tree_learner or "serial") != "serial"
                # dart (drop-score re-traversal) and rf (mean rollback)
                # re-traverse the TRAIN bins with logical feature ids,
                # which sparse storage no longer materializes full-width
                or str(config.boosting or "gbdt") in ("dart", "rf",
                                                      "random_forest")):
            return bins_np
        n, fc = bins_np.shape
        if n < min_rows or fc == 0:
            return bins_np
        sp, defaults, nnz = [], [], []
        for c in range(fc):
            cnt = np.bincount(bins_np[:, c].astype(np.int64))
            mode = int(np.argmax(cnt))
            if cnt[mode] >= threshold * n:
                sp.append(c)
                defaults.append(mode)
                nnz.append(n - int(cnt[mode]))
        if not sp:
            return bins_np
        m = max(max(nnz), 1)
        f_sp = len(sp)
        rows = np.full((f_sp, m), n, dtype=np.int32)      # pad = out of range
        vals = np.zeros((f_sp, m), dtype=bins_np.dtype)
        for i, c in enumerate(sp):
            nz = np.nonzero(bins_np[:, c] != defaults[i])[0]
            rows[i, :len(nz)] = nz
            vals[i, :len(nz)] = bins_np[nz, c]
        self.sp_cols = np.asarray(sp, dtype=np.int32)
        self.sp_rows = jnp.asarray(rows)
        self.sp_bins = jnp.asarray(vals)
        self.sp_default = jnp.asarray(np.asarray(defaults, np.int32))
        dense_cols = np.asarray([c for c in range(fc) if c not in set(sp)],
                                dtype=np.int32)
        log.info(f"sparse storage: {f_sp} of {fc} device columns "
                 f"(max {m} non-default entries; >= {threshold:.0%} "
                 f"concentrated)")
        return np.ascontiguousarray(bins_np[:, dense_cols])

    # ------------------------------------------------- sparse + EFB path
    def _construct_sparse(self, config: Config) -> "Dataset":
        """Construct from scipy sparse input (and/or with EFB bundling)
        without ever densifying the raw matrix (reference: sparse_bin.hpp
        storage + dataset.cpp:239 FastFeatureBundling; here sparse features
        bundle into shared dense device columns, which is the TPU-correct
        storage: a dense [N, G] bin matrix with G ~ bundles, not features)."""
        if config.linear_tree:
            log.fatal("linear_tree is not supported with sparse input")
        sparse = _is_scipy_sparse(self.data)
        if sparse:
            X = self.data.tocsc()
        else:
            X = _to_2d_float(self._pandas_to_codes(self.data))
        self.num_data, self.num_total_features = X.shape
        if self.feature_name == "auto" or self.feature_name is None:
            self._feature_names = [f"Column_{i}"
                                   for i in range(self.num_total_features)]
        else:
            self._feature_names = list(self.feature_name)

        if self.reference is not None:
            ref = self.reference.construct()
            if self.num_total_features != ref.num_total_features:
                log.fatal("validation data has different number of features")
            for attr in ("mappers", "used_features", "_feature_meta",
                         "_missing_bin", "max_num_bins", "has_categorical",
                         "bundles", "_bundle_meta", "_owner_orig",
                         "_thr_fwd", "_thr_rev", "pandas_categorical"):
                setattr(self, attr, getattr(ref, attr, None))
        else:
            cats = self._resolve_categorical(self.num_total_features,
                                             self._feature_names)
            sample = binning.sample_indices(
                self.num_data, config.bin_construct_sample_cnt,
                config.data_random_seed)
            if sparse:
                Xs = self.data.tocsr()[sample].tocsc()
            else:
                Xs = X[sample]
            forced = _load_forced_bins(config, self.num_total_features, cats)
            self.mappers = self._fit_mappers_from_sample(Xs, len(sample),
                                                         config, cats, forced)
            self.used_features = np.array(
                [j for j, m in enumerate(self.mappers) if not m.is_trivial],
                dtype=np.int32)
            if len(self.used_features) == 0:
                log.warning("There are no meaningful features, as all feature"
                            " values are constant.")
            self._run_bundling(Xs, len(sample), config)
            self._build_feature_meta_bundled(config)

        if self.bundles is None:
            # reference was constructed dense (no EFB bundles): bin through
            # the per-feature mappers column-wise so this sparse valid set
            # aligns with the reference's [N, F_used] layout
            bins_np = self._bin_columns_unbundled(X)
        else:
            bins_np = self._bin_columns(X)
        dtype = np.uint8 if self.max_num_bins <= 256 else np.int32
        bins_np = self._maybe_extract_sparse(bins_np.astype(dtype), config)
        self.bins = jnp.asarray(bins_np)
        self.raw_data_np = None
        self._constructed = True
        if self.free_raw_data:
            self.data = None
        g = len(self.bundles) if self.bundles else 0
        nb_total = sum(b.num_bin for b in (self.bundles or []))
        log.info(f"Total Bins {nb_total}")
        log.info(f"Number of data points in the train set: {self.num_data}, "
                 f"number of used features: {len(self.used_features)}"
                 + (f" (bundled into {g} columns)"
                    if g and g != len(self.used_features) else ""))
        return self

    def _fit_mappers_from_sample(self, Xs, total, config, cats,
                                 forced_bounds=None):
        """Per-feature BinMapper from a row sample; for CSC input only the
        nonzeros are touched (zeros implied by the count, the reference's
        sparse sampling protocol, dataset_loader.cpp:953+)."""
        sparse = _is_scipy_sparse(Xs)
        filter_cnt = binning.filter_cnt_for_sample(config, total,
                                                   self.num_data)
        cat_set = set(int(c) for c in cats)
        mappers = []
        for j in range(self.num_total_features):
            if sparse:
                vals = np.asarray(
                    Xs.data[Xs.indptr[j]:Xs.indptr[j + 1]], dtype=np.float64)
            else:
                col = np.asarray(Xs[:, j], dtype=np.float64)
                vals = col[col != 0.0]
            mappers.append(binning.fit_mapper_for_column(
                j, vals, total, config, cat_set, filter_cnt, forced_bounds))
        return mappers

    def _run_bundling(self, Xs, total, config) -> None:
        """Greedy EFB over the bundle-eligible used features
        (reference: dataset.cpp:239 FastFeatureBundling)."""
        from .bundling import Bundle, fast_feature_bundling
        used = self.used_features
        mc = list(config.monotone_constraints or [])
        fc = list(config.feature_contri or [])
        sparse = _is_scipy_sparse(Xs)
        num_bins = []
        nonzero_rows = []
        bundle_ok = np.zeros(len(used), dtype=bool)
        for i, j in enumerate(used):
            m = self.mappers[j]
            num_bins.append(m.num_bin)
            ok = (config.enable_bundle
                  and m.bin_type == binning.BIN_TYPE_NUMERICAL
                  and m.missing_type != binning.MISSING_NAN
                  and m.most_freq_bin == m.default_bin
                  and not (j < len(mc) and int(mc[j]) != 0)
                  and not (j < len(fc) and float(fc[j]) != 1.0))
            if not ok:
                nonzero_rows.append(None)
                continue
            if sparse:
                rows = Xs.indices[Xs.indptr[j]:Xs.indptr[j + 1]]
                vals = np.asarray(Xs.data[Xs.indptr[j]:Xs.indptr[j + 1]],
                                  dtype=np.float64)
            else:
                col = np.asarray(Xs[:, j], dtype=np.float64)
                rows = np.nonzero(col != 0.0)[0]
                vals = col[rows]
            b = m.values_to_bins(vals)
            nonzero_rows.append(np.asarray(rows)[b != m.most_freq_bin])
            bundle_ok[i] = True
        self.bundles = fast_feature_bundling(nonzero_rows, num_bins,
                                             bundle_ok, total)

    def _build_feature_meta_bundled(self, config: Config) -> None:
        """Per-COLUMN metadata for bundled datasets: each device column is a
        bundle (or a single feature); bundle columns get segment arrays for
        the EFB-aware split search (ops/split.py BundleMeta)."""
        from .ops.split import BundleMeta
        used = self.used_features
        bundles = self.bundles
        g = max(len(bundles), 1)
        nb = np.full(g, 2, np.int32)
        missing = np.zeros(g, np.int32)
        default_bin = np.zeros(g, np.int32)
        is_cat = np.zeros(g, bool)
        monotone = np.zeros(g, np.int8)
        penalty = np.ones(g, np.float32)
        missing_bin = np.full(g, -1, np.int32)
        mc = list(config.monotone_constraints or [])
        fc = list(config.feature_contri or [])
        for gi, bd in enumerate(bundles):
            if len(bd.members) == 1:
                j = int(used[bd.members[0]])
                m = self.mappers[j]
                nb[gi] = m.num_bin
                missing[gi] = m.missing_type
                default_bin[gi] = m.default_bin
                is_cat[gi] = m.bin_type == binning.BIN_TYPE_CATEGORICAL
                if j < len(mc):
                    monotone[gi] = np.int8(mc[j])
                if j < len(fc):
                    penalty[gi] = np.float32(fc[j])
                mode_a = (m.num_bin > 2
                          and m.missing_type != binning.MISSING_NONE)
                if mode_a and m.missing_type == binning.MISSING_NAN:
                    missing_bin[gi] = m.num_bin - 1
                elif mode_a and m.missing_type == binning.MISSING_ZERO:
                    missing_bin[gi] = m.default_bin
            else:
                nb[gi] = bd.num_bin
        self.max_num_bins = int(nb.max()) if len(bundles) else 2
        b = self.max_num_bins
        seg_lo = np.zeros((g, b), np.int32)
        seg_hi = np.zeros((g, b), np.int32)
        is_bundle = np.zeros(g, bool)
        fwd_ok = np.zeros((g, b), bool)
        rev_ok = np.zeros((g, b), bool)
        owner_orig = np.zeros((g, b), np.int32)
        thr_fwd = np.tile(np.arange(b, dtype=np.int32), (g, 1))
        thr_rev = np.tile(np.arange(b, dtype=np.int32), (g, 1))
        # tie-break preference tables (higher wins among equal-gain
        # candidates), ordered by the candidate's ORIGINAL feature index
        # first so within-bundle and cross-column ties resolve exactly as
        # the unbundled scan's feature-major order would (ops/split.py
        # BundleMeta docstring; without these a within-bundle tie goes to
        # the highest-offset member — the opposite of the unbundled run)
        u = int(self.num_total_features)
        pref_fwd = np.zeros((g, b), np.int32)
        pref_rev = np.zeros((g, b), np.int32)

        def _owner_base(j):
            return (u - 1 - j) * 4 * b

        for gi, bd in enumerate(bundles):
            if len(bd.members) == 1:
                j = int(used[bd.members[0]])
                seg_hi[gi, :] = nb[gi] - 1
                owner_orig[gi, :] = j
                # plain column: the standard rev-first / high-threshold /
                # fwd low-threshold order, keyed by the original feature
                t = np.arange(b, dtype=np.int32)
                pref_rev[gi, :] = _owner_base(j) + 2 * b + t
                pref_fwd[gi, :] = _owner_base(j) + (b - 1) - t
                continue
            is_bundle[gi] = True
            # per-bin candidate masks reproducing each member's UNBUNDLED
            # scan exactly: the member's most-frequent mass (reconstructed
            # from leaf totals) sits at its ordinal position z, so forward
            # candidates are thresholds below z (mass right) and reverse
            # candidates thresholds at/above z (mass left); the leading
            # phantom bin hosts the z-only-left candidate when z == 0
            for mi, off in zip(bd.members, bd.offsets):
                j = int(used[mi])
                m = self.mappers[j]
                nbm = m.num_bin
                z = m.most_freq_bin
                span = nbm                      # phantom + (nbm - 1) data
                seg_lo[gi, off:off + span] = off
                seg_hi[gi, off:off + span] = off + span - 1
                owner_orig[gi, off:off + span] = j
                r = np.arange(nbm - 1)          # data-bin ranks
                dslice = slice(off + 1, off + span)
                mode_zero = (m.missing_type == binning.MISSING_ZERO
                             and nbm > 2)
                if mode_zero:
                    # zero-as-missing member: both directions, default-bin
                    # threshold skipped (SKIP_DEFAULT_BIN semantics)
                    t_orig = r + (r >= z)
                    ok = t_orig <= nbm - 2
                    fwd_ok[gi, dslice] = ok
                    rev_ok[gi, dslice] = ok
                    thr_fwd[gi, dslice] = t_orig
                    thr_rev[gi, dslice] = t_orig
                    # unbundled mode-A scan order: rev first (high
                    # threshold wins), fwd on strictly-greater only
                    pref_rev[gi, dslice] = _owner_base(j) + 2 * b + t_orig
                    pref_fwd[gi, dslice] = _owner_base(j) + (b - 1) - t_orig
                else:
                    fwd_ok[gi, dslice] = r < z
                    rev_ok[gi, dslice] = (r >= z - 1) & (r <= nbm - 3)
                    thr_fwd[gi, dslice] = r
                    thr_rev[gi, dslice] = r + 1
                    # the member's UNBUNDLED scan is a single REVERSE pass
                    # (missing_type none): every candidate — including the
                    # ones the bundle must evaluate as forward-direction —
                    # competes with the rev preference of its original
                    # threshold, so ties resolve to the highest threshold
                    # like the plain column's scan
                    pref_fwd[gi, dslice] = _owner_base(j) + 2 * b + r
                    pref_rev[gi, dslice] = _owner_base(j) + 2 * b + (r + 1)
                    if z == 0:                  # phantom: left = z mass only
                        rev_ok[gi, off] = True
                        thr_rev[gi, off] = 0
                        pref_rev[gi, off] = _owner_base(j) + 2 * b
        self._bundle_meta = BundleMeta(seg_lo=jnp.asarray(seg_lo),
                                       seg_hi=jnp.asarray(seg_hi),
                                       is_bundle=jnp.asarray(is_bundle),
                                       fwd_ok=jnp.asarray(fwd_ok),
                                       rev_ok=jnp.asarray(rev_ok),
                                       pref_fwd=jnp.asarray(pref_fwd),
                                       pref_rev=jnp.asarray(pref_rev))
        self._owner_orig = owner_orig
        self._thr_fwd = thr_fwd
        self._thr_rev = thr_rev
        self.has_categorical = bool(is_cat.any())
        self._feature_meta = FeatureMeta(
            num_bins=jnp.asarray(nb),
            missing_type=jnp.asarray(missing),
            default_bin=jnp.asarray(default_bin),
            is_categorical=jnp.asarray(is_cat),
            monotone=jnp.asarray(monotone),
            penalty=jnp.asarray(penalty),
        )
        self._missing_bin = jnp.asarray(missing_bin)

    def _bin_columns(self, X) -> np.ndarray:
        """Raw matrix -> bundled bin matrix [N, G] (the analog of
        FeatureGroup::PushData placement, feature_group.h)."""
        sparse = _is_scipy_sparse(X)
        if sparse:
            X = X.tocsc()
            n = X.shape[0]
        else:
            X = _to_2d_float(X)
            n = X.shape[0]
        used = self.used_features
        g = len(self.bundles) if self.bundles else 0
        out = np.zeros((n, max(g, 1)), dtype=np.int32)
        for gi, bd in enumerate(self.bundles or []):
            for mi, off in zip(bd.members, bd.offsets):
                j = int(used[mi])
                m = self.mappers[j]
                if sparse:
                    rows = X.indices[X.indptr[j]:X.indptr[j + 1]]
                    vals = np.asarray(X.data[X.indptr[j]:X.indptr[j + 1]],
                                      dtype=np.float64)
                else:
                    col = np.asarray(X[:, j], dtype=np.float64)
                    rows = np.nonzero((col != 0.0) | np.isnan(col))[0]
                    vals = col[rows]
                if len(bd.members) == 1:
                    out[:, gi] = m.default_bin
                    if len(rows):
                        out[rows, gi] = m.values_to_bins(vals)
                else:
                    bvals = m.values_to_bins(vals)
                    sel = bvals != m.most_freq_bin
                    bb = bvals[sel]
                    bb = bb - (bb > m.most_freq_bin)
                    # +1: data bins follow the member's phantom candidate bin
                    out[np.asarray(rows)[sel], gi] = off + 1 + bb
        return out

    def _bin_columns_unbundled(self, X) -> np.ndarray:
        """Raw matrix -> UNBUNDLED bin matrix [N, F_used] through the
        per-feature mappers, column-wise without densifying sparse input
        (the valid-against-dense-reference path: the reference has no EFB
        bundles, so device column i is used feature i directly)."""
        assert _is_scipy_sparse(X), "dense input takes the dense bin path"
        X = X.tocsc()
        n = X.shape[0]
        f = max(len(self.used_features), 1)
        out = np.zeros((n, f), dtype=np.int32)
        for i, j in enumerate(self.used_features):
            j = int(j)
            m = self.mappers[j]
            rows = X.indices[X.indptr[j]:X.indptr[j + 1]]
            vals = np.asarray(X.data[X.indptr[j]:X.indptr[j + 1]],
                              dtype=np.float64)
            # implicit zeros take the bin of value 0 (bin.h GetDefaultBin)
            out[:, i] = m.default_bin
            if len(rows):
                out[rows, i] = m.values_to_bins(vals)
        return out

    @property
    def bundle_meta(self):
        self.construct()
        return getattr(self, "_bundle_meta", None) \
            if self.bundles is not None else None

    def _build_feature_meta(self, config: Config):
        used = [self.mappers[j] for j in self.used_features]
        nb = np.array([m.num_bin for m in used], dtype=np.int32)
        self.max_num_bins = int(nb.max()) if len(nb) else 2
        missing = np.array([m.missing_type for m in used], dtype=np.int32)
        default_bin = np.array([m.default_bin for m in used], dtype=np.int32)
        is_cat = np.array([m.bin_type == binning.BIN_TYPE_CATEGORICAL for m in used])
        # missing_bin: the bin routed by the split's default direction, or -1
        # (mode analysis in ops/split.py docstring)
        mode_a = (nb > 2) & (missing != binning.MISSING_NONE)
        missing_bin = np.where(mode_a & (missing == binning.MISSING_NAN), nb - 1,
                               np.where(mode_a & (missing == binning.MISSING_ZERO),
                                        default_bin, -1)).astype(np.int32)
        self.has_categorical = bool(is_cat.any())
        f = max(len(used), 1)
        # per-feature monotone direction and contri multiplier, mapped from
        # ORIGINAL feature indices to used-feature space (reference:
        # feature_histogram.hpp:1170-1177 FeatureMetainfo init)
        monotone = np.zeros((f,), dtype=np.int8)
        mc = list(config.monotone_constraints or [])
        if mc and len(mc) != self.num_total_features:
            log.fatal(f"monotone_constraints should be the same size as "
                      f"feature number ({self.num_total_features}), "
                      f"got {len(mc)}")
        for i, j in enumerate(self.used_features):
            if j < len(mc):
                monotone[i] = np.int8(mc[j])
        penalty = np.ones((f,), dtype=np.float32)
        fc = list(config.feature_contri or [])
        if fc and len(fc) != self.num_total_features:
            log.fatal(f"feature_contri should be the same size as feature "
                      f"number ({self.num_total_features}), got {len(fc)}")
        for i, j in enumerate(self.used_features):
            if j < len(fc):
                penalty[i] = np.float32(fc[j])
        self._feature_meta = FeatureMeta(
            num_bins=jnp.asarray(nb if len(nb) else np.array([2], np.int32)),
            missing_type=jnp.asarray(missing if len(missing) else np.zeros(1, np.int32)),
            default_bin=jnp.asarray(default_bin if len(default_bin) else np.zeros(1, np.int32)),
            is_categorical=jnp.asarray(is_cat if len(is_cat) else np.zeros(1, bool)),
            monotone=jnp.asarray(monotone),
            penalty=jnp.asarray(penalty),
        )
        self._missing_bin = jnp.asarray(missing_bin if len(missing_bin)
                                        else np.full(1, -1, np.int32))

    # ------------------------------------------------------- helpers
    @property
    def feature_meta(self) -> FeatureMeta:
        self.construct()
        return self._feature_meta

    @property
    def missing_bin(self):
        self.construct()
        return self._missing_bin

    @property
    def bins_T(self):
        """Feature-major [F, N] copy of the bin matrix, built lazily: split
        routing extracts one feature column per split, which on TPU is a
        contiguous slice here vs a strided read of the whole row-major
        matrix (reference keeps per-feature bin arrays natively,
        dense_bin.hpp)."""
        self.construct()
        if getattr(self, "_bins_T", None) is None:
            if getattr(self, "is_pre_partitioned", False):
                # global row-sharded bins: transpose as an SPMD program
                # with an explicit output sharding (every process reaches
                # this property in lockstep during training)
                from jax.sharding import NamedSharding, PartitionSpec as P
                sh = self.bins.sharding
                self._bins_T = jax.jit(
                    lambda b: b.T,
                    out_shardings=NamedSharding(
                        sh.mesh, P(None, sh.spec[0])))(self.bins)
            else:
                self._bins_T = jnp.asarray(self.bins.T)
        return self._bins_T

    def num_used_features(self) -> int:
        """Number of DEVICE COLUMNS (bundles count as one column each)."""
        self.construct()
        if self.bundles is not None:
            return max(len(self.bundles), 1)
        return max(len(self.used_features), 1)

    def bin_new_data(self, X) -> np.ndarray:
        """Bin raw features with this dataset's mappers (prediction path)."""
        self.construct()
        if self.bundles is not None:
            if not _is_scipy_sparse(X):
                X = _to_2d_float(self._pandas_to_codes(X))
            if X.shape[1] != self.num_total_features:
                log.fatal(f"The number of features in data ({X.shape[1]}) is "
                          f"not the same as it was in training data "
                          f"({self.num_total_features}).")
            return self._bin_columns(X)
        if _is_scipy_sparse(X):
            if X.shape[1] != self.num_total_features:
                log.fatal(f"The number of features in data ({X.shape[1]}) is "
                          f"not the same as it was in training data "
                          f"({self.num_total_features}).")
            return self._bin_columns_unbundled(X)
        X = _to_2d_float(self._pandas_to_codes(X))
        if X.shape[1] != self.num_total_features:
            log.fatal(f"The number of features in data ({X.shape[1]}) is not the same"
                      f" as it was in training data ({self.num_total_features}).")
        used = [self.mappers[j] for j in self.used_features]
        Xu = X[:, self.used_features] if len(self.used_features) else np.zeros((len(X), 0))
        return binning.bin_data(Xu, used)

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        """Row subset sharing this dataset's mappers (reference: basic.py
        Dataset.subset / CopySubrow, dataset.h:416). Requires raw data."""
        if self.data is None:
            log.fatal("Cannot subset a Dataset whose raw data was freed")
        idx = np.asarray(used_indices)
        data = self.data.iloc[idx] if hasattr(self.data, "iloc") else _to_2d_float(self.data)[idx]
        lbl = self.get_label()
        w = self.get_weight()
        return Dataset(data, label=None if lbl is None else lbl[idx],
                       reference=self,
                       weight=None if w is None else w[idx],
                       params=params or self.params)
