"""Booster: the user-facing trained-model handle.

Mirrors the reference Python ``Booster`` (reference:
python-package/lightgbm/basic.py Booster) over the boosting layer, playing
the role of the C API's Booster wrapper (reference: src/c_api.cpp:52-106) —
here there is no C boundary; the boosting object is held directly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Dataset
from .config import Config
from .models.boosting import create_boosting
from .utils import log


class Booster:
    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params or {})
        self.config = Config.from_params(self.params)
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set = train_set
        if model_file is not None or model_str is not None:
            from .io.model_text import load_model
            if model_file is not None:
                with open(model_file) as fh:
                    model_str = fh.read()
            self._boosting = load_model(model_str, self.config)
        elif train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            # num_machines > 1: bootstrap jax.distributed before any device
            # work (the reference calls Network::Init before training,
            # application.cpp:167-178)
            from . import distributed
            distributed.maybe_init_from_config(self.config)
            # merge dataset params before construction
            merged = dict(train_set.params or {})
            merged.update(self.params)
            train_set.params = merged
            self._boosting = create_boosting(self.config, train_set)
            # params identity BEFORE any mid-training reset_parameter
            # mutation: both the checkpointing and the resuming run hash
            # their construction-time config, so learning-rate schedules
            # don't produce spurious resume mismatches
            from .checkpoint import params_hash
            self._initial_params_hash = params_hash(self.config)
        else:
            raise ValueError("need at least one of train_set, model_file or model_str")

    # ------------------------------------------------------------ training
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        self._boosting.add_valid(data, name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; with ``fobj`` the gradients come from
        Python (reference: basic.py Booster.update + c_api.cpp:1645
        LGBM_BoosterUpdateOneIterCustom)."""
        if train_set is not None and train_set is not self._train_set:
            log.fatal("Replacing the training set in update() is not supported")
        if fobj is None:
            return self._boosting.train_one_iter()
        grad, hess = fobj(np.asarray(self._boosting.train_score, dtype=np.float64),
                          self._train_set)
        return self._boosting.train_one_iter(grad, hess)

    def rollback_one_iter(self) -> "Booster":
        self._boosting.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self._boosting.current_iteration()

    def num_trees(self) -> int:
        return self._boosting.num_trees

    def num_model_per_iteration(self) -> int:
        return self._boosting.num_tree_per_iteration

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """reference: basic.py Booster.reset_parameter (learning_rate etc.)."""
        self.params.update(params)
        self.config = Config.from_params(self.params)
        self._boosting.reset_config(self.config)
        return self

    # ---------------------------------------------------------------- eval
    def eval_set(self, feval=None):
        return self._boosting.eval_set(feval)

    def eval(self, data, name: str, feval=None):
        """Evaluate the configured metrics on an arbitrary train-aligned
        Dataset (reference: basic.py Booster.eval / GBDT valid metric
        flow). Returns (name, metric, value, bigger_is_better) tuples."""
        import numpy as np
        b = self._boosting
        score = np.asarray(b.score_dataset(data), dtype=np.float64)
        return b.eval_metrics(score, data, name, feval)

    def eval_train(self, feval=None):
        old = self.config.is_provide_training_metric
        self.config.is_provide_training_metric = True
        try:
            return [r for r in self._boosting.eval_set(feval) if r[0] == "training"]
        finally:
            self.config.is_provide_training_metric = old

    def eval_valid(self, feval=None):
        return [r for r in self._boosting.eval_set(feval) if r[0] != "training"]

    # ------------------------------------------------------------- predict
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                **kwargs) -> np.ndarray:
        """Predict on new data (reference: basic.py Booster.predict).

        Serving runs on the device-resident inference engine
        (models/predict_engine.py): one ensemble-scan dispatch with f64
        accumulation on device, returning only the [N, K] result —
        batch shapes are bucketed so varying sizes reuse compiled
        programs. Tuned by the ``predict_bucket_min_rows`` /
        ``predict_chunk_rows`` (streaming) / ``predict_sharded``
        (multi-device row sharding) / ``predict_accum`` params."""
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        if pred_leaf:
            return self._boosting.predict_leaf(data, num_iteration)
        if pred_contrib:
            return self._boosting.predict_contrib(data, num_iteration)
        return self._boosting.predict(data, raw_score=raw_score,
                                      num_iteration=num_iteration,
                                      start_iteration=start_iteration,
                                      pred_early_stop=pred_early_stop,
                                      pred_early_stop_freq=pred_early_stop_freq,
                                      pred_early_stop_margin=pred_early_stop_margin)

    # ------------------------------------------------------------ model IO
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        # atomic (tmp + fsync + rename): a crash mid-write must leave the
        # previous file, never a truncated model.txt that parses into a
        # silently shorter model
        from .utils.atomic_write import atomic_write_text
        atomic_write_text(filename,
                          self.model_to_string(num_iteration, start_iteration))
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        from .io.model_text import dump_model_text
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        return dump_model_text(self._boosting, num_iteration, start_iteration)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> dict:
        from .io.model_text import dump_model_json
        return dump_model_json(self._boosting, num_iteration or -1, start_iteration)

    # ------------------------------------------------------ importance etc
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """reference: gbdt.cpp FeatureImportance (split counts / total gains)."""
        imp = self._boosting.feature_importance(importance_type)
        if importance_type == "split":
            return imp.astype(np.int32)
        return imp

    def feature_name(self) -> List[str]:
        b = self._boosting
        ts = getattr(b, "train_set", None)
        if ts is not None:
            return ts.get_feature_names()
        return list(b.feature_names)

    def num_feature(self) -> int:
        b = self._boosting
        ts = getattr(b, "train_set", None)
        if ts is not None:
            return ts.num_total_features
        return b.max_feature_idx + 1

    # ----------------------------------------------- misc reference API
    def attr(self, key: str):
        """Runtime attribute (reference: basic.py Booster.attr/set_attr —
        a key/value store on the booster object)."""
        return getattr(self, "_attr", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        store = getattr(self, "_attr", None)
        if store is None:
            store = self._attr = {}
        for k, v in kwargs.items():
            if v is None:
                store.pop(k, None)
            else:
                store[k] = str(v)
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """reference: basic.py Booster.set_train_data_name."""
        self._train_data_name = name
        return self

    def free_dataset(self) -> "Booster":
        """Release the training data (reference: Booster.free_dataset —
        prediction and model IO keep working; further training does not).
        The binning metadata (mappers, bundles, missing routing) stays so
        new data can still be binned for prediction; the O(N) arrays go."""
        b = self._boosting
        b._flush_pending()
        ts = getattr(b, "train_set", None)
        if ts is not None:
            ts.bins = None
            ts._bins_T = None
            # all four sparse-storage fields go together: leaving sp_cols
            # set would keep has_sparse_cols reporting True on a dataset
            # whose streams are gone (ADVICE r5 low)
            ts.sp_rows = ts.sp_bins = ts.sp_cols = ts.sp_default = None
            ts._traversal_bins_cache = None
            ts.label = ts.weight = ts.init_score = None
            ts.raw_data_np = None
            # streaming-construct datasets must not keep the chunk source
            # pinned either (it may hold file handles or closures over
            # generator state) — the construct-re-entry audit twin of the
            # monolithic raw release above
            ts._chunk_source = None
        b.train_score = None
        # valid sets hold the other O(N) device arrays (bins, per-row
        # scores, raw caches) — the reference frees its datasets wholesale
        for vs in b.valid_sets:
            vs.bins = None
            vs._bins_T = None
            vs.raw_data_np = None
        b.valid_sets = []
        b.valid_names = []
        b._valid_scores = []
        b._valid_raw_cache = {}
        self._train_set = None
        return self

    def free_network(self) -> "Booster":
        """reference: Booster.free_network (tears down the comm layer)."""
        from . import distributed
        distributed.shutdown()
        return self

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """reference: Booster.set_network -> Network::Init; here the
        machine list feeds jax.distributed via distributed.init."""
        from . import distributed
        if isinstance(machines, (list, tuple)):
            machines = ",".join(str(m) for m in machines)
        distributed.init(machines=machines, num_machines=num_machines or None)
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """reference: Booster.get_leaf_output (Tree::LeafOutput)."""
        ht = self._boosting.host_trees[tree_id]
        return float(ht.leaf_value[leaf_id])

    def lower_bound(self) -> float:
        """Minimum possible raw score (reference: Booster.lower_bound ->
        GBDT sum of per-tree minima, tree.cpp:316 per-tree bounds)."""
        import numpy as np
        return float(sum(float(np.min(ht.leaf_value))
                         for ht in self._boosting.host_trees))

    def upper_bound(self) -> float:
        """Maximum possible raw score (reference: Booster.upper_bound)."""
        import numpy as np
        return float(sum(float(np.max(ht.leaf_value))
                         for ht in self._boosting.host_trees))

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Randomly permute tree order in [start, end) iterations
        (reference: Booster.shuffle_models -> GBDT::ShuffleModels; the
        prediction SUM is order-independent, refit/early-stop sequences
        are not). Deterministic like the reference's fixed-seed
        ``Random tmp_rand(17)`` (gbdt.h:95): fresh boosters produce the
        same order, and like the reference's MEMBER rng, successive calls
        on one booster draw successive permutations rather than repeating
        the first."""
        import random
        b = self._boosting
        b._flush_pending()
        if not hasattr(b, "_shuffle_rand"):
            b._shuffle_rand = random.Random(17)
        k = b.num_tree_per_iteration
        total = len(b.trees) // k
        end = total if end_iteration <= 0 else min(end_iteration, total)
        idx = list(range(start_iteration, end))
        perm = idx[:]
        b._shuffle_rand.shuffle(perm)
        for attr in ("trees", "_host_trees", "tree_bias"):
            arr = getattr(b, attr)
            orig = list(arr)
            for src, dst in zip(idx, perm):
                for c in range(k):
                    arr[dst * k + c] = orig[src * k + c]
        b._mt_cache.clear()
        b._stacked_cache = None
        b._engine_cache.clear()   # stacked order changed under the engine
        b._contrib_tree_cache = None
        return self

    def get_split_value_histogram(self, feature, bins=None):
        """Histogram of a feature's split thresholds across the model
        (reference: Booster.get_split_value_histogram). Returns
        (counts, bin_edges) like np.histogram."""
        import numpy as np
        model = self.dump_model()
        feature_names = model["feature_names"]
        feat_idx = feature_names.index(feature) if isinstance(feature, str) \
            else int(feature)
        values = []

        def walk(node):
            if "split_feature" in node:
                if node["split_feature"] == feat_idx \
                        and node["decision_type"] == "<=":
                    values.append(float(node["threshold"]))
                walk(node["left_child"])
                walk(node["right_child"])

        for ti in model["tree_info"]:
            walk(ti["tree_structure"])
        if not values:
            raise ValueError("feature was never used for splitting")
        return np.histogram(values,
                            bins=bins or max(10, len(set(values))))

    def trees_to_dataframe(self):
        """All nodes of all trees as one pandas DataFrame (reference:
        basic.py Booster.trees_to_dataframe — same column names)."""
        import pandas as pd
        model = self.dump_model()
        feature_names = model["feature_names"]
        rows = []

        def walk(tree_index, node, depth, parent):
            # a splitless tree's dump is a bare {'leaf_value': ...} with no
            # leaf_index (io/model_text.py single-leaf form)
            node_idx = (f"{tree_index}-S{node['split_index']}"
                        if "split_index" in node
                        else f"{tree_index}-L{node.get('leaf_index', 0)}")
            if "split_feature" in node:
                rows.append({
                    "tree_index": tree_index, "node_depth": depth,
                    "node_index": node_idx,
                    "left_child": None, "right_child": None,
                    "parent_index": parent,
                    "split_feature": feature_names[node["split_feature"]],
                    "split_gain": node.get("split_gain"),
                    "threshold": node.get("threshold"),
                    "decision_type": node.get("decision_type"),
                    "missing_direction":
                        "left" if node.get("default_left") else "right",
                    "missing_type": node.get("missing_type"),
                    "value": node.get("internal_value"),
                    "weight": node.get("internal_weight"),
                    "count": node.get("internal_count")})
                me = len(rows) - 1
                lid = walk(tree_index, node["left_child"], depth + 1,
                           node_idx)
                rid = walk(tree_index, node["right_child"], depth + 1,
                           node_idx)
                rows[me]["left_child"] = lid
                rows[me]["right_child"] = rid
            else:
                rows.append({
                    "tree_index": tree_index, "node_depth": depth,
                    "node_index": node_idx,
                    "left_child": None, "right_child": None,
                    "parent_index": parent,
                    "split_feature": None, "split_gain": None,
                    "threshold": None, "decision_type": None,
                    "missing_direction": None, "missing_type": None,
                    "value": node.get("leaf_value"),
                    "weight": node.get("leaf_weight"),
                    "count": node.get("leaf_count")})
            return node_idx

        for ti in model["tree_info"]:
            walk(ti["tree_index"], ti["tree_structure"], 1, None)
        return pd.DataFrame(rows)

    def model_from_string(self, model_str: str) -> "Booster":
        """Replace this booster's model with one parsed from text
        (reference: basic.py Booster.model_from_string)."""
        from .io.model_text import load_model
        self._boosting = load_model(model_str, self.config)
        return self

    def refit(self, data, label=None, weight=None, group=None,
              decay_rate: float = 0.9) -> "Booster":
        """Re-fit the leaf values of the existing tree structure on new data
        (reference: GBDT::RefitTree gbdt.cpp:285-321 +
        SerialTreeLearner::FitByExistingTree serial_tree_learner.cpp:211-244;
        Python surface basic.py Booster.refit). Returns a NEW Booster.
        Linear-leaf coefficients are kept as-is; only leaf constants refit."""
        from .io.model_text import load_model
        from .objectives import create_objective
        import jax.numpy as jnp

        loaded = load_model(self.model_to_string(), Config.from_params(self.params))
        if label is None and hasattr(data, "get_label"):
            label = data.get_label()
            weight = data.get_weight() if weight is None else weight
            group = data.get_group() if group is None else group
            data = data.data
        X = data
        label = np.asarray(label, dtype=np.float64).reshape(-1)
        leaf = loaded.predict_leaf(X)               # [N, T]
        n = leaf.shape[0]
        cfg = loaded.config
        objective = create_objective(cfg)
        if objective is None:
            log.fatal("Cannot refit a model without a built-in objective")
        objective.init(label, None if weight is None else
                       np.asarray(weight, np.float64).reshape(-1),
                       None if group is None else
                       np.asarray(group, np.int64).reshape(-1))
        k = loaded.num_tree_per_iteration
        score = np.zeros((n, k) if k > 1 else (n,), np.float64)
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        mds = cfg.max_delta_step
        eps = 1e-15

        def leaf_output(sg, sh):
            out = -np.sign(sg) * np.maximum(np.abs(sg) - l1, 0.0) / (sh + l2)
            if mds > 0:
                out = np.clip(out, -mds, mds)
            return out

        Xmat = None
        if any(t.is_linear for t in loaded.trees):
            Xmat = np.asarray(X, np.float64)
            if Xmat.ndim == 1:
                Xmat = Xmat.reshape(1, -1)
        iters = loaded.num_iteration
        for it in range(iters):
            g, h = objective.get_grad_hess(jnp.asarray(score, jnp.float32))
            g = np.asarray(g, np.float64)
            h = np.asarray(h, np.float64)
            for c in range(k):
                tree = loaded.trees[it * k + c]
                lp = leaf[:, it * k + c]
                gc = g[:, c] if k > 1 else g
                hc = h[:, c] if k > 1 else h
                nl = tree.num_leaves
                sum_g = np.bincount(lp, weights=gc, minlength=nl)[:nl]
                sum_h = np.bincount(lp, weights=hc, minlength=nl)[:nl] + eps
                new_out = leaf_output(sum_g, sum_h) * tree.shrinkage
                tree.leaf_value = (decay_rate * tree.leaf_value
                                   + (1.0 - decay_rate) * new_out)
                if tree.is_linear:
                    # re-solve the per-leaf ridge system and decay-blend
                    # const/coeffs (linear_tree_learner.cpp:320-380
                    # CalculateLinear(is_refit=true))
                    self._refit_linear_leaves(tree, lp, gc, hc, Xmat,
                                              cfg.linear_lambda, decay_rate,
                                              new_out)
                delta = tree.predict(Xmat) if tree.is_linear else tree.leaf_value[lp]
                if k > 1:
                    score[:, c] += delta
                else:
                    score += delta
        new_booster = Booster.__new__(Booster)
        new_booster.params = dict(self.params)
        new_booster.config = loaded.config
        new_booster.best_iteration = -1
        new_booster.best_score = {}
        new_booster._train_set = None
        new_booster._boosting = loaded
        return new_booster

    @staticmethod
    def _refit_linear_leaves(tree, lp, g, h, Xmat, linear_lambda, decay_rate,
                             new_out) -> None:
        """Decay-blend linear leaf const/coeffs toward a fresh per-leaf ridge
        fit on the refit data (linear_tree_learner.cpp is_refit path; leaves
        with too few usable rows fall back to the blended plain output with
        zeroed coefficients, :323-329)."""
        shrink = tree.shrinkage
        for li in range(tree.num_leaves):
            feats = tree.leaf_features[li] if li < len(tree.leaf_features) else []
            old_coeffs = (tree.leaf_coeff[li]
                          if li < len(tree.leaf_coeff) else [])
            rows = lp == li
            Xl = (Xmat[rows][:, feats] if feats
                  else np.zeros((int(rows.sum()), 0)))
            ok = ~(np.isnan(Xl).any(axis=1) | np.isinf(Xl).any(axis=1)) \
                if feats else np.ones(int(rows.sum()), bool)
            if ok.sum() < len(feats) + 1:
                tree.leaf_const[li] = (decay_rate * tree.leaf_const[li]
                                       + (1.0 - decay_rate) * new_out[li])
                tree.leaf_coeff[li] = [0.0] * len(feats)
                continue
            X1 = np.concatenate([Xl[ok], np.ones((int(ok.sum()), 1))], axis=1)
            hl = h[rows][ok]
            gl = g[rows][ok]
            A = X1.T @ (X1 * hl[:, None])
            A[np.arange(len(feats)), np.arange(len(feats))] += linear_lambda
            try:
                sol = -np.linalg.solve(A, X1.T @ gl)
            except np.linalg.LinAlgError:
                sol = -(np.linalg.pinv(A) @ (X1.T @ gl))
            tree.leaf_coeff[li] = [
                decay_rate * (old_coeffs[i] if i < len(old_coeffs) else 0.0)
                + (1.0 - decay_rate) * float(sol[i]) * shrink
                for i in range(len(feats))]
            tree.leaf_const[li] = (decay_rate * tree.leaf_const[li]
                                   + (1.0 - decay_rate) * float(sol[-1]) * shrink)
