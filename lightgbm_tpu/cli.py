"""Command-line application.

Mirrors the reference CLI (reference: src/main.cpp:11-42,
src/application/application.cpp:31-271): ``python -m lightgbm_tpu
config=train.conf [key=value ...]`` with tasks train / predict /
convert_model / refit / save_binary. Data files are parsed by the native
C++ loader (native/text_parser.cpp); side files ``<data>.weight`` /
``<data>.query`` / ``<data>.init`` supply metadata the way the reference's
Metadata loader does (reference: src/io/metadata.cpp)."""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from .basic import Dataset
from .booster import Booster
from .config import Config, parse_config_file
from .engine import train as engine_train
from .native import parse_text_file
from .utils import log


def _parse_argv(argv: List[str]) -> Dict[str, str]:
    """key=value args + config file merge (reference: application.cpp:31-85 —
    command-line pairs override the config file)."""
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            log.fatal(f"Unknown argument: {arg} (expected key=value)")
        key, value = arg.split("=", 1)
        params[key.strip()] = value.strip()
    if "config" in params:
        file_params = parse_config_file(params.pop("config"))
        for key, value in file_params.items():
            params.setdefault(key, value)
    return params


def _column_index(spec: str, header_names: Optional[List[str]]) -> Optional[int]:
    """Column spec: int index or 'name:<col>' (reference: config.h label_column
    docs)."""
    if spec == "":
        return None
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names is None or name not in header_names:
            log.fatal(f"Column name {name} requires header=true and a matching "
                      f"header line")
        return header_names.index(name)
    return int(spec)


def _read_header(path: str, config: Config) -> Optional[List[str]]:
    if not config.header:
        return None
    with open(path) as fh:
        first = fh.readline().rstrip("\n")
    if "," in first:
        return first.split(",")
    if "\t" in first:
        return first.split("\t")
    # whitespace-separated files (the native parser's auto-detected format)
    return first.split()


def _side_file(path: str, suffix: str) -> Optional[np.ndarray]:
    """Optional metadata side file (reference: metadata.cpp loads
    <data>.weight/.query/.init when present)."""
    side = path + suffix
    if os.path.exists(side):
        return np.loadtxt(side, ndmin=1)
    return None


def _resolve_columns(path: str, config: Config):
    """Shared column resolution for both loading paths: returns
    (header_names, label_idx, weight_idx, group_idx, drop-set)."""
    header_names = _read_header(path, config)
    label_idx = _column_index(config.label_column, header_names)
    if label_idx is None:
        label_idx = 0
    drop = {label_idx}
    if config.ignore_column:
        for part in str(config.ignore_column).split(","):
            idx = _column_index(part, header_names)
            if idx is not None:
                drop.add(idx)
    weight_idx = _column_index(config.weight_column, header_names)
    group_idx = _column_index(config.group_column, header_names)
    if weight_idx is not None:
        drop.add(weight_idx)
    if group_idx is not None:
        drop.add(group_idx)
    return header_names, label_idx, weight_idx, group_idx, drop


def _qid_to_group(group_col: np.ndarray) -> np.ndarray:
    """Per-row query ids -> query boundary counts by CONSECUTIVE RUNS in
    file order (reference: metadata.cpp query column handling — qids need
    not be globally sorted, only grouped)."""
    group_col = np.asarray(group_col)
    if len(group_col) == 0:
        return np.zeros(0, np.int64)
    change = np.nonzero(np.diff(group_col) != 0)[0]
    bounds = np.concatenate([[0], change + 1, [len(group_col)]])
    return np.diff(bounds)


def load_data_file(path: str, config: Config
                   ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray],
                              Optional[np.ndarray], Optional[np.ndarray]]:
    """Load one data file -> (X, y, weight, group, init_score)."""
    if path.endswith(".bin"):
        return _load_binary(path)
    (header_names, label_idx, weight_idx, group_idx,
     drop) = _resolve_columns(path, config)
    mat, _fmt = parse_text_file(path, has_header=config.header,
                                num_threads=config.num_threads)

    y = mat[:, label_idx]
    weight = mat[:, weight_idx] if weight_idx is not None else None
    group_col = mat[:, group_idx] if group_idx is not None else None
    keep = [j for j in range(mat.shape[1]) if j not in drop]
    X = mat[:, keep]

    if weight is None:
        weight = _side_file(path, ".weight")
    group = _side_file(path, ".query")
    if group is None and group_col is not None:
        group = _qid_to_group(group_col)
    init_score = _side_file(path, ".init")
    return X, y, weight, group, init_score


def _save_binary(path: str, X, y, weight, group, init_score) -> None:
    """Dataset binary serialization (reference: dataset_loader.cpp:316
    LoadFromBinFile / save_binary — here a versioned npz container),
    written atomically (a killed save must not leave a truncated .bin a
    later run would trip over). Streams straight into the tmp file — no
    in-memory copy of the compressed archive."""
    from .utils.atomic_write import atomic_open
    with atomic_open(path) as fh:   # file object: np.savez won't append .npz
        np.savez_compressed(fh, version=1, X=X, y=y,
                            weight=weight if weight is not None else np.zeros(0),
                            group=group if group is not None else np.zeros(0),
                            init_score=(init_score if init_score is not None
                                        else np.zeros(0)))


def _load_binary(path: str):
    z = np.load(path, allow_pickle=False)
    opt = lambda a: None if a.size == 0 else a
    return (z["X"], z["y"], opt(z["weight"]), opt(z["group"]),
            opt(z["init_score"]))


def _iter_parsed_chunks(path: str, config: Config,
                        chunk_bytes: int = 64 << 20):
    """Stream a text file in line-aligned chunks, parsing each with the
    native parser (the streaming half of the reference's two-round loading,
    dataset_loader.cpp:225-244 + pipeline_reader.h)."""
    from .native import parse_buffer
    carry = b""
    first = True
    ncols = None

    def emit(data):
        nonlocal ncols
        mat = parse_buffer(data, has_header=False,
                           num_threads=config.num_threads)[0]
        # the parser infers the width per buffer; ragged rows or format
        # drift across chunk boundaries would silently corrupt columns
        if ncols is None:
            ncols = mat.shape[1]
        elif mat.shape[1] != ncols:
            log.fatal(f"two_round loading needs a fixed column count: "
                      f"{path} yielded {mat.shape[1]} columns in a chunk "
                      f"where earlier chunks had {ncols} (ragged rows?)")
        return mat

    with open(path, "rb") as fh:
        while True:
            blk = fh.read(chunk_bytes)
            if not blk:
                if carry.strip():
                    yield emit(carry)
                return
            blk = carry + blk
            cut = blk.rfind(b"\n")
            if cut < 0:
                carry = blk
                continue
            chunk, carry = blk[:cut + 1], blk[cut + 1:]
            if first and config.header:
                chunk = chunk[chunk.find(b"\n") + 1:]
            first = False
            if chunk.strip():
                yield emit(chunk)


def _metadata_tail(path: str, ws: list, gs: list):
    """Shared weight/group/init_score precedence for the streaming loaders:
    in-file columns win, then side files, with qid runs converted to group
    boundaries (metadata.cpp)."""
    weight = np.concatenate(ws) if ws else _side_file(path, ".weight")
    group = _side_file(path, ".query")
    if group is None and gs:
        group = _qid_to_group(np.concatenate(gs))
    return weight, group, _side_file(path, ".init")


def _two_round_eligible(path: str, config: Config) -> bool:
    """CSV/TSV with fixed columns only; linear trees need resident raw
    features. Ineligible files fall back to in-memory loading."""
    if config.linear_tree:
        log.warning("two_round is not supported with linear_tree; "
                    "falling back to in-memory loading")
        return False
    # chunked parsing needs a fixed column count per line; LibSVM's sparse
    # rows make per-chunk column inference unstable -> in-memory fallback
    # (sniff several lines: a LibSVM file may open with label-only rows)
    with open(path) as fh:
        if config.header:
            fh.readline()
        probe = [fh.readline() for _ in range(5)]
    if any(":" in t for line in probe for t in line.split()[1:]):
        log.warning("two_round loading supports CSV/TSV only; "
                    "falling back to in-memory loading for LibSVM input")
        return False
    return True


def load_valid_two_round(path: str, config: Config, params: Dict[str, str],
                         reference: Dataset) -> Optional[Dataset]:
    """Stream-bin a VALIDATION file against the reference's mappers (the
    second round only — mappers come from the train set; reference:
    dataset_loader.cpp:262-314 LoadFromFileAlignWithOtherDataset under
    two-round mode)."""
    from . import binning
    if getattr(reference, "bundles", None) is not None:
        return None   # bundled references bin through bundle columns
    if not _two_round_eligible(path, config):
        return None
    (header_names, label_idx, weight_idx, group_idx,
     drop) = _resolve_columns(path, config)
    used_idx = reference.used_features
    used = [reference.mappers[j] for j in used_idx]
    dtype = np.uint8 if reference.max_num_bins <= 256 else np.int32
    ys, ws, gs, chunks = [], [], [], []
    for mat in _iter_parsed_chunks(path, config):
        keep = [j for j in range(mat.shape[1]) if j not in drop]
        if len(keep) != reference.num_total_features:
            log.fatal(f"validation file {path} has {len(keep)} features; "
                      f"training data had "
                      f"{reference.num_total_features}")
        ys.append(mat[:, label_idx].copy())
        if weight_idx is not None:
            ws.append(mat[:, weight_idx].copy())
        if group_idx is not None:
            gs.append(mat[:, group_idx].copy())
        Xc = mat[:, keep][:, used_idx] if len(used_idx) \
            else np.zeros((mat.shape[0], 0))
        chunk_bins = binning.bin_data(Xc, used) if used \
            else np.zeros((mat.shape[0], 1), np.int32)
        chunks.append(chunk_bins.astype(dtype))
    if not chunks:
        log.fatal(f"empty validation file {path}")
    y = np.concatenate(ys)
    ds = Dataset(None, label=y, params=dict(params),
                 feature_name=list(reference._feature_names))
    for attr in ("mappers", "used_features", "_feature_meta",
                 "_missing_bin", "max_num_bins", "has_categorical",
                 "bundles", "pandas_categorical"):
        setattr(ds, attr, getattr(reference, attr, None))
    import jax.numpy as jnp
    ds.bins = jnp.asarray(np.concatenate(chunks))
    ds.num_data = len(y)
    ds.num_total_features = reference.num_total_features
    ds._feature_names = list(reference._feature_names)
    ds.raw_data_np = None
    ds._constructed = True
    ds.weight, ds.group, ds.init_score = _metadata_tail(path, ws, gs)
    log.info(f"two-round valid loading: {len(y)} rows")
    return ds


def load_dataset_two_round(path: str, config: Config,
                           params: Dict[str, str]) -> Optional[Dataset]:
    """Two-round low-memory loading (reference: dataset_loader.cpp:225-244
    use_two_round_loading): round 1 streams the file collecting the label/
    weight/group columns and a row sample for bin finding; round 2 streams
    again, binning each chunk against the fitted mappers — the full raw
    feature matrix is never resident (peak memory = the 1-byte bin matrix
    plus one parsed chunk)."""
    from . import binning
    if not _two_round_eligible(path, config):
        return None
    (header_names, label_idx, weight_idx, group_idx,
     drop) = _resolve_columns(path, config)

    # round 1: labels/metadata + reservoir sample of feature rows
    # (algorithm R, seeded — the analog of the reference's Random::Sample
    # over the stream)
    rng = np.random.RandomState(config.data_random_seed)
    cap = config.bin_construct_sample_cnt
    sample_rows: List[np.ndarray] = []
    ys, ws, gs = [], [], []
    keep = None
    n_total = 0
    for mat in _iter_parsed_chunks(path, config):
        if keep is None:
            keep = [j for j in range(mat.shape[1]) if j not in drop]
        ys.append(mat[:, label_idx].copy())
        if weight_idx is not None:
            ws.append(mat[:, weight_idx].copy())
        if group_idx is not None:
            gs.append(mat[:, group_idx].copy())
        Xc = mat[:, keep]
        m = Xc.shape[0]
        take = min(max(cap - n_total, 0), m)
        if take:                            # filling phase, vectorized
            sample_rows.extend(list(Xc[:take].copy()))
        if take < m:
            # vectorized reservoir (algorithm R) for the rest of the chunk
            draws = rng.randint(0, n_total + np.arange(take, m) + 1)
            hit = np.nonzero(draws < cap)[0]
            for r in hit:
                sample_rows[draws[r]] = Xc[take + r].copy()
        n_total += m
    if keep is None:
        log.fatal(f"empty data file {path}")
    y = np.concatenate(ys)
    sample = np.asarray(sample_rows)

    names = ([header_names[j] for j in keep] if header_names
             else [f"Column_{i}" for i in range(len(keep))])
    ds = Dataset(None, label=y, params=dict(params), feature_name=names)
    cats = ds._resolve_categorical(len(keep), names)
    cat_set = set(int(c) for c in cats)
    from .basic import _load_forced_bins
    forced = _load_forced_bins(config, len(keep), cats)
    filter_cnt = binning.filter_cnt_for_sample(config, len(sample), n_total)
    ds.mappers = [binning.fit_mapper_for_column(
        j, np.asarray(sample[:, j], np.float64), len(sample), config,
        cat_set, filter_cnt, forced) for j in range(len(keep))]
    ds.used_features = np.array(
        [j for j, m in enumerate(ds.mappers) if not m.is_trivial], np.int32)
    ds.num_data = n_total
    ds.num_total_features = len(keep)
    ds._feature_names = names
    ds.bundles = None
    ds._build_feature_meta(config)

    # round 2: bin chunk by chunk against the agreed mappers
    used = [ds.mappers[j] for j in ds.used_features]
    dtype = np.uint8 if ds.max_num_bins <= 256 else np.int32
    bins_np = np.zeros((n_total, max(len(ds.used_features), 1)), dtype)
    if used:
        row = 0
        for mat in _iter_parsed_chunks(path, config):
            Xc = mat[:, keep][:, ds.used_features]
            bins_np[row:row + mat.shape[0]] = binning.bin_data(Xc, used)
            row += mat.shape[0]
    import jax.numpy as jnp
    ds.bins = jnp.asarray(bins_np)
    ds.raw_data_np = None
    ds._constructed = True

    ds.weight, ds.group, ds.init_score = _metadata_tail(path, ws, gs)
    log.info(f"two-round loading: {n_total} rows, "
             f"{len(ds.used_features)} used features")
    return ds


def _make_dataset(path: str, config: Config, params: Dict[str, str],
                  reference: Optional[Dataset] = None) -> Dataset:
    if config.two_round and not path.endswith(".bin"):
        ds = (load_dataset_two_round(path, config, params)
              if reference is None
              else load_valid_two_round(path, config, params,
                                        reference.construct()))
        if ds is not None:
            return ds
    X, y, weight, group, init_score = load_data_file(path, config)
    return Dataset(X, label=y, weight=weight, group=group,
                   init_score=init_score, reference=reference, params=params,
                   free_raw_data=False)


def run_train(config: Config, params: Dict[str, str]) -> None:
    """task=train (reference: application.cpp InitTrain/Train)."""
    if not config.data:
        log.fatal("No training data: set data=<file>")
    train_set = _make_dataset(config.data, config, params)
    valid_sets, valid_names = [], []
    for vf in config.valid:
        valid_sets.append(_make_dataset(vf, config, params, reference=train_set))
        valid_names.append(os.path.basename(vf))

    callbacks = []
    resume_from = None
    if config.snapshot_freq > 0:
        # snapshot_freq rides the atomic checkpoint subsystem (replacing
        # the reference's non-atomic model.txt.snapshot_iter_N dumps,
        # gbdt.cpp:277-281): full trainer state, manifest-validated files,
        # and AUTO-RESUME — a killed run restarted with the same command
        # continues bit-identically from the newest valid checkpoint
        from . import callback as callback_mod
        ckpt_dir = config.checkpoint_path or (config.output_model + ".ckpt")
        callbacks.append(callback_mod.checkpoint(
            ckpt_dir, period=config.snapshot_freq,
            keep=config.checkpoint_keep))
        if os.path.isdir(ckpt_dir):
            resume_from = ckpt_dir
            log.info(f"checkpoint directory {ckpt_dir} exists; resuming "
                     f"from the newest valid checkpoint")

    booster = engine_train(
        dict(params), train_set, num_boost_round=config.num_iterations,
        valid_sets=valid_sets, valid_names=valid_names,
        init_model=config.input_model or None,
        early_stopping_rounds=config.early_stopping_round or None,
        verbose_eval=config.metric_freq if (valid_sets or
                                            config.is_provide_training_metric)
        else False,
        callbacks=callbacks, resume_from=resume_from)
    booster.save_model(config.output_model)
    log.info(f"Finished training, model saved to {config.output_model}")


def run_predict(config: Config, params: Dict[str, str]) -> None:
    """task=predict (reference: application.cpp Predict + predictor.hpp)."""
    if not config.input_model:
        log.fatal("No model file: set input_model=<file>")
    if not config.data:
        log.fatal("No prediction data: set data=<file>")
    booster = Booster(model_file=config.input_model)
    X, _y, _w, _g, _i = load_data_file(config.data, config)
    result = booster.predict(
        X, raw_score=config.predict_raw_score,
        pred_leaf=config.predict_leaf_index,
        pred_contrib=config.predict_contrib,
        num_iteration=config.num_iteration_predict,
        start_iteration=config.start_iteration_predict)
    result = np.atleast_2d(np.asarray(result))
    if result.shape[0] == 1 and X.shape[0] != 1:
        result = result.T
    np.savetxt(config.output_result, result, fmt="%.10g", delimiter="\t")
    log.info(f"Finished prediction, results saved to {config.output_result}")


def run_convert_model(config: Config, params: Dict[str, str]) -> None:
    """task=convert_model: if-else C++ codegen
    (reference: gbdt_model_text.cpp ModelToIfElse)."""
    if not config.input_model:
        log.fatal("No model file: set input_model=<file>")
    booster = Booster(model_file=config.input_model)
    from .io.codegen import model_to_if_else
    from .utils.atomic_write import atomic_write_text
    atomic_write_text(config.convert_model, model_to_if_else(booster._boosting))
    log.info(f"Converted model saved to {config.convert_model}")


def run_refit(config: Config, params: Dict[str, str]) -> None:
    """task=refit: re-fit leaf values of an existing model on new data
    (reference: application.cpp:221 ConvertModel task=refit ->
    GBDT::RefitTree, gbdt.cpp:285-321)."""
    if not config.input_model:
        log.fatal("No model file: set input_model=<file>")
    if not config.data:
        log.fatal("No refit data: set data=<file>")
    booster = Booster(model_file=config.input_model)
    X, y, weight, group, _i = load_data_file(config.data, config)
    refitted = booster.refit(X, y, weight=weight, group=group,
                             decay_rate=config.refit_decay_rate)
    refitted.save_model(config.output_model)
    log.info(f"Finished refit, model saved to {config.output_model}")


def run_save_binary(config: Config, params: Dict[str, str]) -> None:
    """task=save_binary (reference: application.cpp:260-270)."""
    if not config.data:
        log.fatal("No data: set data=<file>")
    X, y, weight, group, init_score = load_data_file(config.data, config)
    out = config.data + ".bin"
    _save_binary(out, X, y, weight, group, init_score)
    log.info(f"Dataset saved to {out}")


def main(argv: Optional[List[str]] = None) -> int:
    # honor JAX_PLATFORMS explicitly: some environments (e.g. a TPU-tunnel
    # sitecustomize) override jax's backend selection, and a dead tunnel
    # then stalls CLI startup for minutes retrying; a no-op elsewhere
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    argv = argv if argv is not None else sys.argv[1:]
    params = _parse_argv(argv)
    config = Config.from_params(dict(params))
    task = config.task
    runners = {"train": run_train, "predict": run_predict,
               "prediction": run_predict, "test": run_predict,
               "convert_model": run_convert_model, "refit": run_refit,
               "refit_tree": run_refit, "save_binary": run_save_binary}
    if task not in runners:
        log.fatal(f"Unknown task: {task}")
    runners[task](config, params)
    return 0


if __name__ == "__main__":
    sys.exit(main())
