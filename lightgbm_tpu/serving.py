"""Resilient serving front end over the inference engine.

ROADMAP item 4 — the layer that makes "millions of users" falsifiable.
PR 4's engine made a SINGLE predict call near-optimal (2–3 dispatches,
``N*K*8`` bytes D2H, bucketed compile cache); this module supplies what
production traffic needs ABOVE it, the serve-side twin of the training
robustness stack (PRs 5/8 watchdogs, degradation ladders, health
snapshots):

- **Deadline-driven micro-batching.** Concurrent small requests coalesce
  into ONE bucketed engine dispatch: the dispatcher thread flushes the
  queue ``serve_flush_ms`` after the first request arrives (or as soon as
  ``serve_max_batch_rows`` rows are queued), concatenates same-model
  requests in arrival order, predicts once, and splits the result by row
  ranges. Per-row traversal/accumulation never reads another row, so a
  coalesced response is BIT-IDENTICAL to the unbatched single-request
  predict (padding rows are zeros either way and are sliced off) — the
  batching is pure throughput, never a numerics knob.
- **Per-request deadlines.** A request that cannot be answered by its
  deadline raises a diagnosable :class:`ServeTimeoutError` NAMING the
  phase it died in — ``queue-wait`` (never dispatched; the batcher sheds
  it without wasting device time) vs ``dispatch`` (the engine call itself
  overran) — mirroring ``DistributedTimeoutError``'s suspect-naming
  contract on the training side.
- **Admission control / load shedding.** A request that would push
  queued + in-flight rows past ``serve_max_queue_rows`` is REJECTED at
  admission with a retriable :class:`ServeOverloadError` instead of
  growing an unbounded queue (the failure mode where every request
  eventually times out). Shed bursts are recorded through
  ``distributed.record_degradation`` and surface in ``health_snapshot()``
  next to the training plane's OOM events.
- **Multi-model registry with validated hot swap.** Models are named and
  versioned; :meth:`ServeFrontend.swap` loads a candidate, smoke-validates
  it against the entry's pinned probe batch (predict succeeds — which
  builds the engine —, output shape and class arity correct, every value
  finite) and only then atomically replaces the registry pointer. On ANY
  validation failure the old model keeps serving and a
  :class:`ServeSwapError` surfaces the reason — never a half-swapped
  registry. Requests admitted before the swap complete on the version
  they were admitted under (batches hold the entry reference, not the
  name). Engine programs are module-level jits keyed by shape bucket +
  statics, so a new version with the same ensemble shape re-uses the old
  version's compiled programs (no recompile storm on reload).
- **Steady-state donated buffers.** Registered boosters serve through the
  engine's donated per-bucket slots (``predict_engine._serve_chunk``):
  the padded bin matrix and the accumulation carry are recycled via
  buffer donation, so the serve loop never re-allocates its large device
  operands.
- **Degradation, not death.** A serve-time RESOURCE_EXHAUSTED rides PR
  8's predict-chunk ladder per model (``_maybe_degrade_predict_oom``):
  the chunk shrinks, the event lands in ``health_snapshot()``, the
  request is retried — the training rungs are never consumed.

Health gauges (``utils/profiling.set_gauge``, always-on, surfaced by
``distributed.health_snapshot()["serve"]``): ``serve_queue_rows``,
``serve_inflight_rows``, ``serve_shed_count``, ``serve_timeout_count``,
``serve_requests``, ``serve_batches``, ``serve_p50_ms``, ``serve_p99_ms``.

Metrics exposition (``serve_metrics=True`` / ``metrics=True``): a
Prometheus-style text endpoint — ``GET /metrics`` renders
``telemetry.prometheus_text()`` (``lightgbm_tpu_serve_p99_ms`` and
friends from the latency ring, plus the scopes/counters/dispatch/health
planes) from a daemon HTTP listener on ``serve_metrics_port`` (0 = an
ephemeral port; read :attr:`ServeFrontend.metrics_addr`). The handler
first mirrors the frontend's AUTHORITATIVE counters into the gauges, so
a scrape never reads stale percentiles.

Fault drills (``utils/faults.py``, env + config twins):
``LGBM_TPU_FAULT_SLOW_PREDICT_MS`` delays inside the dispatch path;
``LGBM_TPU_FAULT_OOM_AT_PREDICT`` raises simulated RESOURCE_EXHAUSTED
from the next N predict dispatches.

TF Boosted Trees (PAPERS.md) is the exemplar for serving-integrated
boosting; the micro-batching front end is the standard accelerator-serving
shape (coalesce-or-flush with a deadline) applied to the engine's
shape-bucketed compile cache.
"""

from __future__ import annotations

import copy
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Union

import numpy as np

from .utils import log, profiling

__all__ = ["ServeFrontend", "ServeTimeoutError", "ServeOverloadError",
           "ServeSwapError"]


class ServeTimeoutError(Exception):
    """A request missed its deadline. ``phase`` names where it died:
    ``"queue-wait"`` — never dispatched (the batcher dropped it without
    spending device time) — or ``"dispatch"`` — the engine call itself
    overran. Mirrors DistributedTimeoutError's diagnosable-message
    contract: model, version, row count, the deadline and the time
    actually waited, plus the queue state at the moment of death."""

    def __init__(self, *, phase: str, model: str, version: int, rows: int,
                 deadline_ms: float, waited_ms: float,
                 queued_rows: int = 0, inflight_rows: int = 0):
        self.phase = phase
        self.model = model
        self.version = version
        self.rows = rows
        self.deadline_ms = deadline_ms
        self.waited_ms = waited_ms
        super().__init__(
            f"serve deadline ({deadline_ms:g} ms) exceeded in {phase}: "
            f"request of {rows} row(s) for model {model!r} v{version} "
            f"waited {waited_ms:.1f} ms "
            f"(queued {queued_rows} rows, in-flight {inflight_rows}). "
            f"The request was "
            + ("never dispatched — raise the deadline, shrink "
               "serve_flush_ms, or add capacity."
               if phase == "queue-wait" else
               "dispatched but the engine call overran — look for a slow "
               "dispatch (health_snapshot() serve gauges) or shrink the "
               "batch caps."))


class ServeOverloadError(Exception):
    """Admission control shed this request: accepting it would push
    queued + in-flight rows past ``serve_max_queue_rows``. RETRIABLE —
    the queue is full, not broken; back off and resend (``retriable`` is
    the attribute load balancers should branch on)."""

    retriable = True

    def __init__(self, *, model: str, rows: int, queued_rows: int,
                 inflight_rows: int, limit: int):
        self.model = model
        self.rows = rows
        self.queued_rows = queued_rows
        self.inflight_rows = inflight_rows
        self.limit = limit
        super().__init__(
            f"serve queue full: admitting {rows} row(s) for model "
            f"{model!r} would exceed serve_max_queue_rows={limit} "
            f"(queued {queued_rows} + in-flight {inflight_rows}). "
            f"Retriable — back off and resend.")


class ServeSwapError(Exception):
    """A hot-swap candidate failed load or smoke validation. The registry
    is untouched: the OLD version keeps serving (callers observe the
    failure, traffic never does)."""


class _Request:
    """One admitted predict request, owned by the caller thread until the
    dispatcher completes it (``event``). Phase transitions (queued ->
    dispatch) happen under the frontend lock; the caller reads ``phase``
    after a timed-out wait to name the phase it died in."""

    __slots__ = ("X", "rows", "raw_score", "entry", "deadline", "enqueue_t",
                 "event", "result", "error", "phase", "abandoned")

    def __init__(self, X, rows, raw_score, entry, deadline):
        self.X = X
        self.rows = rows
        self.raw_score = raw_score
        self.entry = entry
        self.deadline = deadline          # absolute monotonic, or None
        self.enqueue_t = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.phase = "queued"
        self.abandoned = False            # caller gave up (deadline)


class _ModelEntry:
    """One registered (name, version): the booster, its pinned probe batch
    and the validated output arity. Immutable after registration — a swap
    installs a NEW entry, so in-flight batches holding the old reference
    complete on the version they were admitted under."""

    __slots__ = ("name", "version", "booster", "probe", "arity")

    def __init__(self, name, version, booster, probe, arity):
        self.name = name
        self.version = version
        self.booster = booster
        self.probe = probe
        self.arity = arity


def _clone_exc(e: BaseException) -> BaseException:
    """Shallow-copy an exception so each of a coalesced batch's caller
    threads re-raises its own instance (falling back to the shared one
    for exceptions copy.copy cannot handle)."""
    try:
        c = copy.copy(e)
        c.__cause__ = e.__cause__
        return c
    except Exception:
        return e


def _as_request_matrix(X) -> np.ndarray:
    """Canonical request payload: a C-contiguous float64 [n, F] matrix.
    Coalescing concatenates payloads, so every request must carry the
    SAME dtype the unbatched predict would see — float64 is what the
    binning path converts to anyway (``_to_2d_float``), which is what
    keeps batched == unbatched bit-identical."""
    if hasattr(X, "dtypes") or hasattr(X, "toarray"):
        raise TypeError(
            "ServeFrontend.predict takes dense numeric arrays; convert "
            "pandas/sparse inputs on the client (Booster.predict still "
            "accepts them directly)")
    X = np.ascontiguousarray(np.asarray(X, np.float64))
    if X.ndim == 1:
        X = X.reshape(1, -1)
    if X.ndim != 2 or X.shape[0] == 0:
        raise ValueError(f"expected a non-empty [n, F] matrix, got shape "
                         f"{X.shape}")
    return X


class ServeFrontend:
    """Deadline-aware micro-batching serving front end (module docstring
    has the full model).

    >>> fe = ServeFrontend(booster)                  # registers "default"
    >>> out = fe.predict(X_batch, deadline_ms=50.0)
    >>> fe.swap("default", "model_v2.txt")           # validated hot swap
    >>> fe.close()

    Thread-safe: ``predict`` may be called from any number of caller
    threads; a single dispatcher thread owns batching and the engine's
    donated serve buffers. Batching policy comes from the ``serve_*``
    params (keyword overrides win, then the first registered booster's
    config, then the dataclass defaults)."""

    def __init__(self, model=None, *, name: str = "default",
                 probe: Optional[np.ndarray] = None,
                 flush_ms: Optional[float] = None,
                 max_batch_rows: Optional[int] = None,
                 max_queue_rows: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 metrics: Optional[bool] = None,
                 metrics_port: Optional[int] = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._queued_rows = 0
        self._inflight_rows = 0
        self._registry: Dict[str, _ModelEntry] = {}
        self._policy_name: Optional[str] = None   # first-registered model
        self._next_version: Dict[str, int] = {}
        self._closing = False
        self._requests = 0
        self._batches = 0
        self._shed_count = 0
        self._timeout_count = 0
        self._lat_ms: deque = deque(maxlen=2048)   # completed-request ring
        self._lat_gauge_t = 0.0                    # last percentile refresh
        self._shed_episode: Optional[dict] = None
        self._last_shed_t = 0.0
        # coerce overrides NOW: a malformed knob must fail the
        # constructor, not poison the dispatcher thread later
        self._flush_ms = None if flush_ms is None else float(flush_ms)
        self._max_batch_rows = None if max_batch_rows is None \
            else int(max_batch_rows)
        self._max_queue_rows = None if max_queue_rows is None \
            else int(max_queue_rows)
        self._default_deadline_ms = None if default_deadline_ms is None \
            else float(default_deadline_ms)
        self._metrics = None if metrics is None else bool(metrics)
        self._metrics_port = None if metrics_port is None \
            else int(metrics_port)
        self._metrics_server = None
        self._metrics_thread: Optional[threading.Thread] = None
        self._thread = threading.Thread(
            target=self._run, name="lgbm-tpu-serve-dispatch", daemon=True)
        self._thread.start()
        if model is not None:
            try:
                self.register(name, model, probe=probe)
            except BaseException:
                # a failed constructor must not leak the dispatcher
                # thread (the thread's bound-method target keeps self
                # alive, so __del__ would never run it down)
                self.close()
                raise

    # ------------------------------------------------------------ registry
    def _load(self, model):
        from .booster import Booster
        if isinstance(model, str):
            try:
                return Booster(model_file=model)
            except Exception as e:
                raise ServeSwapError(
                    f"candidate model file {model!r} failed to load: "
                    f"{e}") from e
        if isinstance(model, Booster):
            return model
        raise TypeError(f"model must be a Booster or a model-file path, "
                        f"got {type(model).__name__}")

    def _policy(self, cfg_attr: str, override, default):
        """Serve knob resolution: explicit kwarg > the first-registered
        model's CURRENT config (swaps included) > dataclass default.
        Lock-free — called both from caller threads pre-lock and from the
        dispatcher while it holds the (non-reentrant) frontend lock, so
        it reads single atomic attribute/dict-get snapshots instead of
        iterating the registry."""
        if override is not None:
            return override
        name = self._policy_name
        entry = self._registry.get(name) if name is not None else None
        if entry is not None:
            return getattr(entry.booster.config, cfg_attr, default)
        return default

    @property
    def flush_s(self) -> float:
        return float(self._policy("serve_flush_ms", self._flush_ms,
                                  2.0)) / 1e3

    @property
    def max_batch_rows(self) -> int:
        return int(self._policy("serve_max_batch_rows",
                                self._max_batch_rows, 8192))

    @property
    def max_queue_rows(self) -> int:
        return int(self._policy("serve_max_queue_rows",
                                self._max_queue_rows, 65536))

    @property
    def default_deadline_ms(self) -> float:
        return float(self._policy("serve_deadline_ms",
                                  self._default_deadline_ms, 0.0))

    @property
    def metrics_enabled(self) -> bool:
        return bool(self._policy("serve_metrics", self._metrics, False))

    @property
    def metrics_port(self) -> int:
        return int(self._policy("serve_metrics_port", self._metrics_port,
                                0))

    @property
    def metrics_host(self) -> str:
        return str(self._policy("serve_metrics_host", None, "127.0.0.1"))

    def _validate(self, booster, probe: np.ndarray,
                  expect_arity: Optional[int] = None) -> int:
        """Smoke-validate a candidate against the pinned probe batch: the
        predict must SUCCEED (which builds the engine — a model whose
        engine cannot compile is caught here, not by live traffic), return
        one row per probe row with the expected class arity, and every
        value must be finite. Returns the arity."""
        try:
            out = np.asarray(booster.predict(probe, raw_score=True))
        except ServeSwapError:
            raise
        except Exception as e:
            raise ServeSwapError(
                f"candidate failed to predict the probe batch "
                f"({type(e).__name__}: {e})") from e
        if out.shape[0] != probe.shape[0]:
            raise ServeSwapError(
                f"candidate probe output has {out.shape[0]} rows for a "
                f"{probe.shape[0]}-row probe (shape {out.shape})")
        arity = 1 if out.ndim == 1 else int(out.shape[1])
        if expect_arity is not None and arity != expect_arity:
            raise ServeSwapError(
                f"candidate predicts {arity} value(s) per row where the "
                f"serving version predicts {expect_arity} — class arity "
                f"is part of the serving contract")
        if not np.all(np.isfinite(out)):
            bad = int(np.size(out) - np.isfinite(out).sum())
            raise ServeSwapError(
                f"candidate probe output contains {bad} non-finite "
                f"value(s) — refusing to serve NaN/Inf")
        return arity

    def _warm_serve_bucket(self, booster) -> None:
        """Best-effort AOT warmup of the ``serve_max_batch_rows`` row
        bucket on the model's inference engine (trained boosters only —
        file-loaded models predict through the host tree walk and have
        no engine to warm). Never fails registration."""
        try:
            boosting = getattr(booster, "_boosting", None)
            ts = getattr(boosting, "train_set", None)
            if boosting is None or ts is None \
                    or not hasattr(boosting, "_predict_engine"):
                return
            eng = boosting._predict_engine()
            if eng is None:
                return
            # the predict path bins new data via bin_data: int32, one
            # column per USED feature (basic.py bin_new_data). serve=True
            # warms the donated-carry serve program — the one the
            # steady-state flush loop dispatches, not the plain
            # build-carry-in-program variant
            eng.warm_aot(self.max_batch_rows, ts.num_used_features(),
                         np.int32, ts.missing_bin, serve=True)
        except Exception as e:
            log.warning(f"serve bucket AOT warmup skipped: {e}")

    def register(self, name: str, model, *,
                 probe: Optional[np.ndarray] = None) -> int:
        """Register (or replace, validated) a named model. ``probe``: the
        pinned smoke-validation batch every later :meth:`swap` candidate
        is judged against; defaults to the first rows the model was
        trained to see (an all-zeros [4, num_feature] matrix when the
        feature count is discoverable). Returns the installed version."""
        booster = self._load(model)
        existing = self._registry.get(name)
        if probe is None:
            if existing is not None:
                probe = existing.probe
            else:
                nf = int(booster.num_feature())
                probe = np.zeros((4, nf), np.float64)
        probe = _as_request_matrix(probe)
        arity = self._validate(booster, probe)
        # compile wall, serve side: point this process at the persistent
        # compilation cache and AOT-warm the engine's serve-size bucket
        # BEFORE traffic arrives — the probe predict above only compiled
        # the probe's (small) bucket; without this the first full
        # coalesced batch pays the big bucket's XLA compile (a disk read
        # when a previous process already compiled the shape). Warmup
        # only runs WITH a cache configured: jax's AOT compile does not
        # feed the jit call cache, so a cacheless warmup would just
        # compile the bucket twice
        from . import compile_cache
        if compile_cache.configure(booster.config):
            self._warm_serve_bucket(booster)
        if existing is not None and arity != existing.arity:
            # register() is the UNGUARDED replace path (swap() enforces
            # same-arity): changing the serving contract is allowed here
            # but must never be silent
            log.warning(f"serve: re-registering {name!r} changes the "
                        f"class arity {existing.arity} -> {arity} (use "
                        f"swap() for a contract-preserving reload)")
        gb = getattr(booster, "_boosting", None)
        if gb is not None and hasattr(gb, "enable_serve_mode"):
            gb.enable_serve_mode(True)
        with self._lock:
            version = self._next_version.get(name, 0) + 1
            self._next_version[name] = version
            self._registry[name] = _ModelEntry(name, version, booster,
                                               probe, arity)
            if self._policy_name is None:
                self._policy_name = name
        profiling.set_gauge("serve_models", float(len(self._registry)))
        # metrics endpoint policy resolves through the registered
        # booster's config — (re)check it now that one exists. Best
        # effort: the model is already committed to the registry, and a
        # bind failure (port in use by another frontend, a stale
        # listener) must not turn a successful registration into an
        # error — explicit start_metrics_server() calls still raise
        if self.metrics_enabled:
            try:
                self.start_metrics_server()
            except Exception as e:
                log.warning(f"serve: metrics endpoint failed to start "
                            f"(continuing without it): {e}")
        log.info(f"serve: registered model {name!r} v{version} "
                 f"(arity {arity}, probe {probe.shape[0]} rows)")
        return version

    def swap(self, name: str, model, *,
             probe: Optional[np.ndarray] = None) -> int:
        """Validated hot swap: load the candidate, smoke-validate it
        against the pinned probe (same class arity required), then
        atomically replace the registry entry. On ANY failure the old
        version keeps serving and a ServeSwapError is raised (the event
        is also recorded in health_snapshot()'s degradation log).
        Requests already admitted complete on the old version; requests
        admitted after the return serve the new one. Returns the new
        version number."""
        with self._lock:
            old = self._registry.get(name)
        if old is None:
            raise KeyError(f"unknown model {name!r}; register() it first")
        try:
            booster = self._load(model)
            use_probe = _as_request_matrix(probe) if probe is not None \
                else old.probe
            self._validate(booster, use_probe, expect_arity=old.arity)
        except Exception as e:
            # ANY candidate failure — load, probe conversion, validation —
            # honors the contract: the registry is untouched, the event is
            # recorded, and the caller sees a ServeSwapError
            from . import distributed
            distributed.record_degradation({
                "kind": "serve_swap_rejected", "model": name,
                "serving_version": old.version, "error": str(e)[:200]})
            profiling.inc_gauge("serve_swap_rejected")
            log.warning(f"serve: hot-swap candidate for {name!r} REJECTED "
                        f"(v{old.version} keeps serving): {e}")
            if isinstance(e, ServeSwapError):
                raise
            raise ServeSwapError(
                f"candidate for {name!r} rejected "
                f"({type(e).__name__}: {e})") from e
        gb = getattr(booster, "_boosting", None)
        if gb is not None and hasattr(gb, "enable_serve_mode"):
            gb.enable_serve_mode(True)
        with self._lock:
            version = self._next_version.get(name, 0) + 1
            self._next_version[name] = version
            self._registry[name] = _ModelEntry(name, version, booster,
                                               use_probe, old.arity)
            still_serving = any(e.booster is old.booster
                                for e in self._registry.values())
        if not still_serving:
            # the swapped-OUT booster leaves serve mode: a user-held
            # reference to the old model must not keep pinning donated
            # per-bucket device buffers (in-flight batches on the old
            # entry still complete — the ordinary chunk path is
            # bit-identical)
            gb = getattr(old.booster, "_boosting", None)
            if gb is not None and hasattr(gb, "enable_serve_mode"):
                gb.enable_serve_mode(False)
        profiling.set_gauge(f"serve_version_{name}", float(version))
        log.info(f"serve: model {name!r} hot-swapped "
                 f"v{old.version} -> v{version}")
        return version

    def version(self, name: str = "default") -> int:
        with self._lock:
            entry = self._registry.get(name)
        if entry is None:
            raise KeyError(f"unknown model {name!r}")
        return entry.version

    # ------------------------------------------------------------ predict
    def predict(self, X, model: str = "default", *,
                raw_score: bool = False,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        """Blocking predict through the micro-batcher. Bit-identical to
        ``booster.predict(X, raw_score=...)`` on the registered model —
        coalescing never changes bits. Raises ServeOverloadError (shed at
        admission, retriable), ServeTimeoutError (deadline exceeded,
        ``.phase`` names queue-wait vs dispatch), or re-raises the
        dispatch error for this request's batch."""
        X = _as_request_matrix(X)
        rows = int(X.shape[0])
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline_ms = float(deadline_ms or 0.0)
        now = time.monotonic()
        deadline = (now + deadline_ms / 1e3) if deadline_ms > 0 else None
        with self._lock:
            if self._closing:
                raise RuntimeError("ServeFrontend is closed")
            entry = self._registry.get(model)
            if entry is None:
                raise KeyError(f"unknown model {model!r}; register() it "
                               f"first")
            total = self._queued_rows + self._inflight_rows
            limit = self.max_queue_rows
            # an oversized LONE request (rows > limit on an idle frontend)
            # still admits — like the batch-row cap, the head always ships
            # and the engine chunks internally; shedding it "retriable"
            # would never come true
            if total + rows > limit and not (total == 0 and rows > limit):
                self._record_shed(model, rows, limit)
                raise ServeOverloadError(
                    model=model, rows=rows, queued_rows=self._queued_rows,
                    inflight_rows=self._inflight_rows, limit=limit)
            req = _Request(X, rows, bool(raw_score), entry, deadline)
            self._queue.append(req)
            self._queued_rows += rows
            self._requests += 1
            profiling.set_gauge("serve_queue_rows",
                                float(self._queued_rows))
            profiling.set_gauge("serve_requests", float(self._requests))
            self._cond.notify()
        remaining = None if deadline is None else max(deadline - now, 0.0)
        completed = req.event.wait(remaining)
        if completed:
            if req.error is not None:
                if isinstance(req.error, ServeTimeoutError):
                    # dropped by the dispatcher at flush time (deadline
                    # already past): count it here, where it surfaces
                    with self._lock:
                        self._timeout_count += 1
                    profiling.inc_gauge("serve_timeout_count")
                raise req.error
            self._note_latency(req)
            return req.result
        # deadline expired before completion: name the phase it died in
        with self._lock:
            if req.event.is_set():          # completion raced the timeout
                pass
            else:
                req.abandoned = True
                if req.phase == "queued":
                    # still queued: remove it so the batcher never pays
                    # for a dead request
                    try:
                        self._queue.remove(req)
                        self._queued_rows -= rows
                        profiling.set_gauge("serve_queue_rows",
                                            float(self._queued_rows))
                    except ValueError:
                        pass
            phase = req.phase
            queued, inflight = self._queued_rows, self._inflight_rows
        if req.event.is_set():
            if req.error is None:
                self._note_latency(req)
                return req.result
            if not isinstance(req.error, ServeTimeoutError):
                # completion raced the deadline with a REAL dispatch
                # error (e.g. an exhausted OOM ladder): surface the root
                # cause — reporting it as a timeout would send the
                # operator chasing latency instead of memory
                raise req.error
        with self._lock:
            self._timeout_count += 1
        profiling.inc_gauge("serve_timeout_count")
        raise ServeTimeoutError(
            phase=("dispatch" if phase == "dispatch" else "queue-wait"),
            model=entry.name, version=entry.version, rows=rows,
            deadline_ms=deadline_ms,
            waited_ms=(time.monotonic() - req.enqueue_t) * 1e3,
            queued_rows=queued, inflight_rows=inflight)

    # -------------------------------------------------------- shed events
    def _record_shed(self, model: str, rows: int, limit: int) -> None:
        """Count a shed and record the overload in health_snapshot().
        Degradation events are recorded per EPISODE (a burst of sheds
        separated by <5 s quiet updates one event's count in place) so a
        sustained overload can't grow the process degradation log without
        bound."""
        from . import distributed
        self._shed_count += 1
        profiling.inc_gauge("serve_shed_count")
        now = time.monotonic()
        if self._shed_episode is None or now - self._last_shed_t > 5.0 \
                or self._shed_episode["model"] != model:
            # a new episode per model too: folding model B's sheds into
            # A's event would hide B's overload from the log entirely
            # keep the STORED dict (record_degradation copies its input)
            # so the in-place episode updates below reach the log
            self._shed_episode = distributed.record_degradation({
                "kind": "serve_shed", "model": model, "count": 1,
                "queued_rows": int(self._queued_rows),
                "inflight_rows": int(self._inflight_rows),
                "limit": int(limit)})
        else:
            # recorded dict updated in place: one episode, one log entry
            self._shed_episode["count"] += 1
            self._shed_episode["queued_rows"] = int(self._queued_rows)
        self._last_shed_t = now

    def _note_latency(self, req: _Request) -> None:
        """Record a completed request's latency and refresh the percentile
        gauges. Ring append and snapshot both run under the frontend lock —
        caller threads complete concurrently, and an unlocked np.fromiter
        over the deque races appends (deque mutated during iteration)."""
        dt = (time.monotonic() - req.enqueue_t) * 1e3
        now = time.monotonic()
        with self._lock:
            self._lat_ms.append(dt)
            # gauge refresh is throttled: rebuilding the 2048-entry ring
            # + two percentile sorts per completed request would tax the
            # hot path just to update telemetry (stats() computes fresh
            # percentiles on demand either way)
            if len(self._lat_ms) > 16 and now - self._lat_gauge_t < 0.25:
                return
            self._lat_gauge_t = now
            lat = np.fromiter(self._lat_ms, dtype=np.float64)
        profiling.set_gauge("serve_p50_ms", float(np.percentile(lat, 50)))
        profiling.set_gauge("serve_p99_ms", float(np.percentile(lat, 99)))

    # ---------------------------------------------------------- dispatcher
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._closing and not self._queue:
                    # untimed: every state change this waits for
                    # (admission, close) notifies the condition — an idle
                    # frontend costs zero wakeups
                    self._cond.wait()
                if self._closing and not self._queue:
                    return
                head = self._queue[0]
                try:
                    flush_at = head.enqueue_t + self.flush_s
                    cap = self.max_batch_rows
                except BaseException as e:  # noqa: BLE001 — relayed
                    # a poisoned policy knob (e.g. a registered booster
                    # whose config carries a non-numeric serve_flush_ms)
                    # must fail the head REQUEST, never kill the
                    # dispatcher thread
                    self._queue.popleft()
                    self._queued_rows -= head.rows
                    profiling.set_gauge("serve_queue_rows",
                                        float(self._queued_rows))
                    head.error = e
                    head.event.set()
                    continue
                now = time.monotonic()
                if now < flush_at and self._queued_rows < cap:
                    self._cond.wait(min(flush_at - now, 0.05))
                    continue
                batch = self._take_batch(cap)
                rows = sum(r.rows for r in batch)
                self._inflight_rows += rows
                profiling.set_gauge("serve_queue_rows",
                                    float(self._queued_rows))
                profiling.set_gauge("serve_inflight_rows",
                                    float(self._inflight_rows))
            try:
                self._dispatch(batch)
            except BaseException as e:       # noqa: BLE001 — relayed
                # _dispatch relays predict errors itself; anything that
                # escapes it (batch concatenate / result split) must not
                # kill the dispatcher thread — a dead dispatcher strands
                # every queued and future request forever
                first = True
                for req in batch:
                    if not req.event.is_set():
                        req.error = e if first else _clone_exc(e)
                        first = False
                        req.event.set()
            finally:
                with self._lock:
                    self._inflight_rows -= rows
                    self._batches += 1
                    profiling.set_gauge("serve_inflight_rows",
                                        float(self._inflight_rows))
                    profiling.set_gauge("serve_batches",
                                        float(self._batches))

    def _take_batch(self, cap: int) -> List[_Request]:
        """Pop the flush batch under the lock: same-(entry, raw_score,
        feature-width) requests as the queue head, in arrival order, up
        to ``cap`` rows (the head always ships, even oversized — the
        engine chunks internally). Non-matching requests keep their
        relative order for the next flush."""
        head = self._queue[0]
        key = (head.entry, head.raw_score, head.X.shape[1])
        batch: List[_Request] = []
        rows = 0
        full = False
        keep: deque = deque()
        while self._queue:
            req = self._queue.popleft()
            match = (req.entry, req.raw_score, req.X.shape[1]) == key
            if match and not full and (not batch
                                       or rows + req.rows <= cap):
                batch.append(req)
                rows += req.rows
                req.phase = "dispatch"
                self._queued_rows -= req.rows
            else:
                if match:
                    # cap reached: later same-key requests must NOT jump
                    # this one (FIFO within a key)
                    full = True
                keep.append(req)
        self._queue = keep
        return batch

    def _queue_wait_timeout(self, req: _Request,
                            now: float) -> ServeTimeoutError:
        """The dispatcher-side queue-wait drop error: a dead request
        found at flush time was never dispatched, and its caller must
        see (or already saw) a deadline timeout naming that phase."""
        return ServeTimeoutError(
            phase="queue-wait", model=req.entry.name,
            version=req.entry.version, rows=req.rows,
            deadline_ms=0.0 if req.deadline is None else
            (req.deadline - req.enqueue_t) * 1e3,
            waited_ms=(now - req.enqueue_t) * 1e3,
            queued_rows=self._queued_rows,
            inflight_rows=self._inflight_rows)

    def _dispatch(self, batch: List[_Request]) -> None:
        """One coalesced engine dispatch (dispatcher thread only). Dead
        requests (abandoned or past deadline) are dropped BEFORE the
        predict so the device never works for a caller that stopped
        listening."""
        now = time.monotonic()
        live: List[_Request] = []
        for req in batch:
            if req.abandoned:
                # the caller timed out (usually it has already raised) —
                # but in the narrow race where its post-wait re-check sees
                # our event first, it must find a timeout ERROR, never a
                # None "result"
                req.error = self._queue_wait_timeout(req, now)
                req.event.set()
            elif req.deadline is not None and now >= req.deadline:
                # dispatcher-side queue-wait shed: the caller's wait will
                # wake to the error (phase stays pre-dispatch semantics)
                req.phase = "queued"
                req.error = self._queue_wait_timeout(req, now)
                req.event.set()
            else:
                live.append(req)
        if not live:
            return
        entry = live[0].entry
        raw = live[0].raw_score
        X = live[0].X if len(live) == 1 else \
            np.concatenate([r.X for r in live], axis=0)
        try:
            out = entry.booster.predict(X, raw_score=raw)
        except BaseException as e:          # noqa: BLE001 — relayed
            for i, req in enumerate(live):
                # each caller re-raises its OWN instance: N threads
                # raising one shared exception object race on its
                # __traceback__/__context__ mutation
                req.error = e if i == 0 else _clone_exc(e)
                req.event.set()
            return
        out = np.asarray(out)
        off = 0
        for req in live:
            # copy, not slice: a contiguous row slice is a VIEW keeping
            # the whole coalesced batch output alive in every caller
            # that retains its (possibly 1-row) result
            req.result = out[off:off + req.rows].copy()
            off += req.rows
            req.phase = "done"
            req.event.set()

    # ------------------------------------------------------------ metrics
    def metrics_text(self) -> str:
        """The Prometheus-style exposition of :func:`telemetry.snapshot`
        — what ``GET /metrics`` serves. Mirrors the frontend's
        AUTHORITATIVE counters (requests/batches/shed/timeouts/latency
        percentiles, computed under the frontend lock) into the serve_*
        gauges first, so a scrape never reads the throttled refresh's
        stale percentiles."""
        from . import telemetry
        st = self.stats()
        profiling.set_gauge("serve_requests", float(st["requests"]))
        profiling.set_gauge("serve_batches", float(st["batches"]))
        profiling.set_gauge("serve_shed_count", float(st["shed"]))
        profiling.set_gauge("serve_timeout_count", float(st["timeouts"]))
        profiling.set_gauge("serve_queue_rows", float(st["queued_rows"]))
        profiling.set_gauge("serve_inflight_rows",
                            float(st["inflight_rows"]))
        if "p50_ms" in st:
            profiling.set_gauge("serve_p50_ms", st["p50_ms"])
            profiling.set_gauge("serve_p99_ms", st["p99_ms"])
        return telemetry.prometheus_text()

    def start_metrics_server(self, port: Optional[int] = None,
                             host: Optional[str] = None) -> str:
        """Start (idempotently) the daemon HTTP listener serving
        ``GET /metrics`` and return its ``host:port`` address. ``port``/
        ``host`` override the ``serve_metrics_port``/``serve_metrics_host``
        policies (0 = ephemeral port; the default host is LOOPBACK — the
        exposition has no auth, so off-host scraping requires opting in
        with ``serve_metrics_host="0.0.0.0"`` or an interface address)."""
        with self._lock:
            if self._metrics_server is not None:
                return self.metrics_addr
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        frontend = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 — http.server API
                if self.path.split("?", 1)[0].rstrip("/") \
                        not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = frontend.metrics_text().encode()
                    status = 200
                except Exception as e:
                    # the scrape must not kill the server, but a broken
                    # exposition must read as a FAILED scrape (500), not
                    # a successful empty one — up==1 with every series
                    # silently stale would defeat scrape alerting
                    body = f"# metrics render failed: {e}\n".encode()
                    status = 500
                self.send_response(status)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not log events
                pass

        srv = ThreadingHTTPServer(
            (self.metrics_host if host is None else str(host),
             int(self.metrics_port if port is None else port)),
            _Handler)
        srv.daemon_threads = True
        thread = threading.Thread(target=srv.serve_forever,
                                  name="lgbm-tpu-serve-metrics", daemon=True)
        with self._lock:
            if self._metrics_server is not None:   # lost the race
                srv.server_close()
                return self.metrics_addr
            self._metrics_server = srv
            self._metrics_thread = thread
        thread.start()
        addr = self.metrics_addr
        log.info(f"serve: metrics endpoint at http://{addr}/metrics")
        return addr

    @property
    def metrics_addr(self) -> Optional[str]:
        """``host:port`` of the live metrics listener (None when off)."""
        srv = self._metrics_server
        if srv is None:
            return None
        host, port = srv.server_address[:2]
        return f"{host}:{port}"

    # ------------------------------------------------------------- status
    def stats(self) -> dict:
        """Frontend counters (authoritative; the serve_* gauges mirror
        them into health_snapshot())."""
        with self._lock:
            lat = list(self._lat_ms)
            out = {
                "requests": self._requests,
                "batches": self._batches,
                "shed": self._shed_count,
                "timeouts": self._timeout_count,
                "queued_rows": self._queued_rows,
                "inflight_rows": self._inflight_rows,
                "models": {n: e.version
                           for n, e in self._registry.items()},
            }
        if lat:
            arr = np.asarray(lat)
            out["p50_ms"] = float(np.percentile(arr, 50))
            out["p99_ms"] = float(np.percentile(arr, 99))
        return out

    # ------------------------------------------------------------ shutdown
    def close(self) -> None:
        """Stop the dispatcher. Queued requests still flush (their callers
        are waiting); new admissions fail."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            self._cond.notify_all()
            srv, self._metrics_server = self._metrics_server, None
            mthread, self._metrics_thread = self._metrics_thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
            if mthread is not None:
                mthread.join(timeout=10.0)
        self._thread.join(timeout=30.0)
        # release serve resources: a closed frontend must not leave its
        # boosters pinning donated per-bucket device buffers or routing
        # later direct predicts through the (now pointless) serve path
        with self._lock:
            entries = list(self._registry.values())
        for entry in entries:
            gb = getattr(entry.booster, "_boosting", None)
            if gb is not None and hasattr(gb, "enable_serve_mode"):
                gb.enable_serve_mode(False)

    def __enter__(self) -> "ServeFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        # NOTE: there is deliberately no __del__ — the dispatcher
        # thread's bound-method target keeps the frontend alive, so
        # finalizer-based cleanup can never run while the thread does.
        # Owners must close() (or use the context manager).
