"""scikit-learn estimator API.

Mirrors the reference's sklearn wrapper layer (reference:
python-package/lightgbm/sklearn.py:348-1014 — LGBMModel base plus
LGBMRegressor / LGBMClassifier / LGBMRanker): constructor params map to
booster params, ``fit`` drives ``engine.train`` with eval-set handling and
early stopping, objective/eval callables are adapted from sklearn signatures
to the (grad, hess) / (name, value, is_higher_better) protocol
(reference: sklearn.py:16-152 _ObjectiveFunctionWrapper/_EvalFunctionWrapper).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Dataset
from .booster import Booster
from .engine import train as engine_train
from .utils import log

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder
    _SKLEARN = True
except ImportError:   # pragma: no cover - sklearn is in the image
    _SKLEARN = False

    class BaseEstimator:       # minimal stand-ins
        pass

    class ClassifierMixin:
        pass

    class RegressorMixin:
        pass


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style fobj(y_true, y_pred[, weight/group]) -> (grad, hess)
    to the engine protocol (reference: sklearn.py:16-89)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds: np.ndarray, dataset: Dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(),
                                   dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2-4 arguments, "
                            f"got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt sklearn-style feval(y_true, y_pred[, weight/group]) ->
    (name, value, is_higher_better) (reference: sklearn.py:91-152)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds: np.ndarray, dataset: Dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2-4 "
                        f"arguments, got {argc}")


class LGBMModel(BaseEstimator):
    """Base sklearn estimator (reference: sklearn.py:348-817 LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state=None, n_jobs: int = -1,
                 silent: bool = True, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for key, val in kwargs.items():
            setattr(self, key, val)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._best_score: Dict = {}
        self._objective = objective
        self._n_features = 0
        self._classes = None
        self._n_classes = -1

    # --------------------------------------------------------------- params
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN else {}
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _booster_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        # sklearn names -> booster canonical names
        ren = {"boosting_type": "boosting", "min_split_gain": "min_gain_to_split",
               "min_child_weight": "min_sum_hessian_in_leaf",
               "min_child_samples": "min_data_in_leaf",
               "subsample": "bagging_fraction", "subsample_freq": "bagging_freq",
               "colsample_bytree": "feature_fraction",
               "reg_alpha": "lambda_l1", "reg_lambda": "lambda_l2",
               "subsample_for_bin": "bin_construct_sample_cnt",
               "random_state": "seed", "n_jobs": "num_threads"}
        out = {}
        for key, val in params.items():
            if val is None and key in ("objective", "random_state"):
                continue
            out[ren.get(key, key)] = val
        if out.get("seed") is None:
            out.pop("seed", None)
        num_threads = out.get("num_threads")
        if num_threads is not None and num_threads < 0:
            out["num_threads"] = 0
        if callable(out.get("objective")):
            out.pop("objective")
        elif not out.get("objective"):
            out["objective"] = self._default_objective()
        if self.silent:
            out.setdefault("verbosity", -1)
        return out

    # ------------------------------------------------------------------ fit
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        params = self._booster_params()
        fobj = None
        if callable(self.objective):
            fobj = _ObjectiveFunctionWrapper(self.objective)
            params["objective"] = "none"
        feval = None
        if callable(eval_metric):
            feval = _EvalFunctionWrapper(eval_metric)
        elif eval_metric:
            params["metric"] = eval_metric if isinstance(eval_metric, list) \
                else [eval_metric]

        X_arr = X
        self._n_features = (X.shape[1] if hasattr(X, "shape")
                            else np.asarray(X).shape[1])
        train_set = Dataset(X_arr, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            free_raw_data=False)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    vw = eval_sample_weight[i] if eval_sample_weight else None
                    vg = eval_group[i] if eval_group else None
                    vi = eval_init_score[i] if eval_init_score else None
                    valid_sets.append(Dataset(
                        vx, label=self._prep_eval_label(vy), weight=vw,
                        group=vg, init_score=vi, reference=train_set,
                        params=params, free_raw_data=False))
                valid_names.append(eval_names[i] if eval_names
                                   and i < len(eval_names) else f"valid_{i}")

        self._evals_result = {}
        self._Booster = engine_train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=valid_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result,
            verbose_eval=verbose, callbacks=callbacks, init_model=init_model)
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def _prep_eval_label(self, y):
        return y

    # -------------------------------------------------------------- predict
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)

    # ----------------------------------------------------------- properties
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self._n_features

    @property
    def feature_importances_(self) -> np.ndarray:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster.feature_name()

    @property
    def objective_(self):
        return self.objective or self._default_objective()


def _not_fitted_error(est):
    try:
        from sklearn.exceptions import NotFittedError
        return NotFittedError(f"This {type(est).__name__} instance is not "
                              f"fitted yet.")
    except ImportError:   # pragma: no cover
        return RuntimeError("Estimator not fitted")


class LGBMRegressor(LGBMModel, RegressorMixin):
    """reference: sklearn.py:818-843 LGBMRegressor."""

    def _default_objective(self) -> str:
        return "regression"

    def score(self, X, y, sample_weight=None):
        if _SKLEARN:
            from sklearn.metrics import r2_score
            return r2_score(y, self.predict(X), sample_weight=sample_weight)
        raise RuntimeError("scikit-learn is required for score()")


class LGBMClassifier(LGBMModel, ClassifierMixin):
    """reference: sklearn.py:844-964 LGBMClassifier."""

    def _default_objective(self) -> str:
        return "binary" if self._n_classes <= 2 else "multiclass"

    def fit(self, X, y, sample_weight=None, init_score=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None,
            eval_metric=None, early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMClassifier":
        self._le = LabelEncoder() if _SKLEARN else None
        if self._le is not None:
            y_enc = self._le.fit_transform(y)
            self._classes = self._le.classes_
        else:
            self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)

        params_extra = {}
        if self._n_classes > 2:
            params_extra["num_class"] = self._n_classes
        if self.class_weight is not None:
            # per-row weights from class weights (reference: sklearn.py uses
            # compute_sample_weight)
            if _SKLEARN:
                from sklearn.utils.class_weight import compute_sample_weight
                cw = compute_sample_weight(self.class_weight, y)
                sample_weight = cw if sample_weight is None else \
                    np.asarray(sample_weight) * cw
        self._other_params.update(params_extra)
        for key, val in params_extra.items():
            setattr(self, key, val)
        super().fit(X, y_enc, sample_weight=sample_weight,
                    init_score=init_score, eval_set=eval_set,
                    eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score, eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks, init_model=init_model)
        return self

    def _prep_eval_label(self, y):
        if self._le is not None:
            return self._le.transform(y)
        return np.searchsorted(self._classes, y)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(X, raw_score=raw_score,
                                    start_iteration=start_iteration,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        idx = np.argmax(result, axis=1)
        return np.asarray(self._classes)[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        result = super().predict(X, raw_score=raw_score,
                                 start_iteration=start_iteration,
                                 num_iteration=num_iteration,
                                 pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.stack([1.0 - result, result], axis=1)
        return result

    @property
    def classes_(self):
        if self._classes is None:
            raise _not_fitted_error(self)
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    """reference: sklearn.py:965-1014 LGBMRanker."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), early_stopping_rounds=None,
            verbose=False, feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        self._other_params["eval_at"] = list(eval_at)
        self.eval_at = list(eval_at)
        super().fit(X, y, sample_weight=sample_weight, init_score=init_score,
                    group=group, eval_set=eval_set, eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score, eval_group=eval_group,
                    eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks, init_model=init_model)
        return self
