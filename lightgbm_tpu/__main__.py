"""``python -m lightgbm_tpu config=train.conf`` — the CLI entry point
(reference: src/main.cpp)."""

import sys

from .cli import main

sys.exit(main())
