"""Device-resident batched inference engine.

TPU-native serving path for a trained ensemble (the batched analog of
GBDT::PredictRaw's per-tree loop, gbdt_prediction.cpp:13-53, and of the
on-accelerator accumulation in the GPU tree-boosting literature —
arxiv 1706.08359 §4, arxiv 1806.11248 §3.3): a full-ensemble predict is a
CONSTANT, tiny number of compiled-program dispatches with near-zero
device->host traffic.

What the engine does differently from the earlier stacked-predict path
(tree.py predict_values_stacked + host numpy accumulation):

- **On-device accumulation, in tree order.** The scan over stacked trees
  adds each tree's output to a float64 carry IN TREE ORDER, so only the
  final ``[N, K]`` result crosses to the host — not the ``[T, N]``
  per-tree value matrix (a ``T x N x 4``-byte transfer per call before).
  The addends and their order are unchanged from the host-f64 loop and no
  multiply feeds the adds (leaf values arrive pre-shrunk, biases are
  subtracted before the add), so there is no mul+add pair for XLA to
  FMA-contract: the result is BIT-IDENTICAL to the host path. Where the
  backend lacks float64, ``accum="compensated"`` falls back to two-float
  (Kahan) f32 accumulation — near-f64 error, not bit-identical.
- **Depth-bounded traversal.** Trees are walked with
  ``predict_leaf_bins_depth`` (a ``fori_loop`` whose static trip count is
  the stacked ensemble's true max leaf depth, measured once at engine
  build) instead of the data-dependent ``while_loop`` — XLA can pipeline
  and fuse across trees instead of stalling every batch on its slowest
  row.
- **Shape-bucketed compile cache.** Batch rows are padded up to
  power-of-two buckets (>= ``predict_bucket_min_rows``), so serving
  traffic with varying batch sizes hits a handful of compiled programs
  instead of recompiling per distinct N.
- **Chunked streaming.** Inputs larger than ``predict_chunk_rows`` are
  processed in row chunks with the carry fetched per chunk — the device
  never holds more than one chunk of the feature matrix.
- **Row-sharded multi-device predict.** With ``predict_sharded`` the same
  scan runs under ``shard_map`` over all visible devices (rows sharded,
  trees replicated) — per-row accumulation order is unchanged, so the
  result is bit-identical to the single-device path.

The engine is built per (booster, tree-range) by ``GBDT._predict_engine``
and also serves ``score_dataset`` (training-time eval over binned valid
matrices, with per-tree bias subtraction) and ``predict_leaf``.
"""

from __future__ import annotations

import functools
import threading
from contextlib import nullcontext
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .tree import TreeArrays, predict_leaf_bins_depth

ACCUM_MODES = ("float64", "compensated", "float32")

# Trace-time compile counters: the core functions' Python bodies run
# exactly once per jit-cache miss (a trace == an XLA compile of a new
# program), so these count real compiles. The observable behind the
# serving suite's regression tests: concurrent first-touch of one shape
# bucket compiles exactly once (the engine lock serializes it), and a
# hot-swapped model version with the same statics/bucket re-uses the
# already-compiled programs (delta == 0) — the jitted entries are
# MODULE-level, shared across every engine and model version.
TRACE_COUNTS: Dict[str, int] = {"accum": 0, "leaves": 0, "refill": 0}


def _x64_ctx():
    """jax.enable_x64 moved out of experimental after 0.4.x."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64()
    from jax.experimental import enable_x64
    return enable_x64()


def _x64_scope(accum: str):
    """Trace/execute scope for the f64 accumulation programs: a no-op when
    x64 is already enabled globally (or not needed)."""
    if accum != "float64" or jax.config.jax_enable_x64:
        return nullcontext()
    return _x64_ctx()


def resolve_accum(mode: str) -> str:
    """Map the ``predict_accum`` param to an engine mode. ``auto`` means
    float64 — exact, bit-identical to the host-f64 accumulation (XLA
    emulates f64 adds where the hardware lacks them); ``compensated`` is
    the two-float f32 fallback for backends where even emulated f64 is
    unavailable or too slow."""
    mode = (mode or "auto").lower()
    if mode in ("auto", "float64", "f64", "double"):
        return "float64"
    if mode in ("compensated", "kahan", "twofloat"):
        return "compensated"
    if mode in ("float32", "f32", "single"):
        return "float32"
    raise ValueError(f"unknown predict_accum mode: {mode!r}")


def host_tree_depth(left_child: np.ndarray, right_child: np.ndarray,
                    num_leaves: int) -> int:
    """Max leaf depth (edge count from the root) of one tree, walked from
    the host child arrays — authoritative for the fori_loop trip count."""
    if num_leaves <= 1:
        return 0
    best = 1
    stack = [(0, 1)]
    while stack:
        node, d = stack.pop()
        for ch in (int(left_child[node]), int(right_child[node])):
            if ch >= 0:
                stack.append((ch, d + 1))
            elif d > best:
                best = d
    return best


# ----------------------------------------------------------- core programs
def _accum_core(stacked, class_of, biases, bins, missing_bin, carry, active,
                *, depth: int, k: int, use_bias: bool, use_active: bool,
                accum: str, init_zero: bool):
    """Scan over the stacked ensemble, accumulating tree outputs into the
    carry IN TREE ORDER (class ``t % k`` of tree ``t`` gets the add —
    exactly the host loop's ``out[:, t % k] += vals[t] - bias[t]``).

    No multiply feeds the accumulation adds (the active mask is applied
    with a select, not a 0/1 multiply), so XLA cannot FMA-contract a
    rounding away — see the PR 3 parity lesson in _apply_score_delta."""
    TRACE_COUNTS["accum"] += 1          # trace-time only: counts compiles
    n = bins.shape[0]
    if init_zero:
        if accum == "compensated":
            z = jnp.zeros((n,) if k == 1 else (n, k), jnp.float32)
            carry = (z, z)
        else:
            dt = jnp.float64 if accum == "float64" else jnp.float32
            carry = jnp.zeros((n,) if k == 1 else (n, k), dt)

    val_dtype = jnp.float32 if accum == "compensated" else (
        jnp.float64 if accum == "float64" else jnp.float32)

    def step(carry, xs):
        tree, c = xs[0], xs[1]
        leaf = predict_leaf_bins_depth(tree, bins, missing_bin, depth)
        v = tree.leaf_value[leaf].astype(val_dtype)
        if use_bias:
            v = v - xs[2].astype(val_dtype)
        if accum == "compensated":
            s, comp = carry
            sc = s if k == 1 else s[:, c]
            cc = comp if k == 1 else comp[:, c]
            y = v - cc
            t = sc + y
            nc = (t - sc) - y
            if use_active:
                t = jnp.where(active, t, sc)
                nc = jnp.where(active, nc, cc)
            if k == 1:
                return (t, nc), None
            return (s.at[:, c].set(t), comp.at[:, c].set(nc)), None
        col = carry if k == 1 else carry[:, c]
        new = col + v
        if use_active:
            new = jnp.where(active, new, col)
        if k == 1:
            return new, None
        return carry.at[:, c].set(new), None

    xs = (stacked, class_of) + ((biases,) if use_bias else ())
    carry, _ = jax.lax.scan(step, carry, xs)
    return carry


_accum_jit = jax.jit(_accum_core, static_argnames=(
    "depth", "k", "use_bias", "use_active", "accum", "init_zero"))


def _leaves_core(stacked, bins, missing_bin, *, depth: int):
    TRACE_COUNTS["leaves"] += 1         # trace-time only: counts compiles

    def step(_, tree):
        return _, predict_leaf_bins_depth(tree, bins, missing_bin, depth)
    _, leaves = jax.lax.scan(step, 0, stacked)
    return leaves


_leaves_jit = jax.jit(_leaves_core, static_argnames=("depth",))


# ------------------------------------------------- donated serve programs
# Steady-state serving re-uses two device buffers per shape bucket — the
# padded bin matrix and the accumulation carry — via buffer DONATION, so
# the serve loop never re-allocates its large operands: each flush writes
# the new rows into the donated bin buffer and the accumulation writes its
# output into the donated carry buffer (with ``init_zero`` the incoming
# carry VALUE is ignored — only its buffer is recycled). Donation is a
# no-op on backends without input-output aliasing (CPU), where passing
# donate_argnums would only emit per-program warnings — so the jits are
# built lazily, once the backend is known, with donation enabled only
# where it is implemented. Numerics are identical either way, which is
# what keeps the donated path CPU-testable.

_serve_jits: Dict[str, object] = {}
_serve_jit_lock = threading.Lock()

# first-dispatch serialization is MODULE-level to match the jitted
# programs it guards (_accum_jit is shared by every engine): two engines
# of the same ensemble shape first-touching one bucket concurrently must
# also compile it exactly once, which a per-engine lock cannot give
_first_dispatch_lock = threading.RLock()
_compiled_keys: set = set()


def _donation_ok() -> bool:
    return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")


def _refill_core(buf, rows):
    TRACE_COUNTS["refill"] += 1         # trace-time only: counts compiles
    # full-buffer overwrite that CONSUMES buf: XLA aliases the output to
    # the donated input buffer (a bare `return rows` would leave the
    # donated buffer unused — no reuse, and a warning per program)
    return jax.lax.dynamic_update_slice(buf, rows.astype(buf.dtype), (0, 0))


def _serve_refill_jit():
    with _serve_jit_lock:
        prog = _serve_jits.get("refill")
        if prog is None:
            prog = jax.jit(_refill_core,
                           donate_argnums=(0,) if _donation_ok() else ())
            _serve_jits["refill"] = prog
        return prog


def _serve_accum_jit():
    """The accumulation program with the carry operand (positional arg 5)
    donated — one jit entry shared by every engine and model version, so
    same-bucket traffic across hot swaps hits the same compiled programs."""
    with _serve_jit_lock:
        prog = _serve_jits.get("accum")
        if prog is None:
            prog = jax.jit(
                _accum_core,
                static_argnames=("depth", "k", "use_bias", "use_active",
                                 "accum", "init_zero"),
                donate_argnums=(5,) if _donation_ok() else ())
            _serve_jits["accum"] = prog
        return prog


class PredictEngine:
    """Compiled inference engine over one stacked ensemble.

    ``biases``: optional per-tree float64 bias (the boost-from-average
    fold recorded in GBDT.tree_bias) subtracted before accumulation —
    used by ``score_dataset``, off for raw prediction (the stored trees
    already carry the bias)."""

    def __init__(self, stacked: TreeArrays, k: int, num_trees: int,
                 max_depth: int, *, biases: Optional[np.ndarray] = None,
                 accum: str = "auto", bucket_min_rows: int = 1024,
                 chunk_rows: int = 0, sharded: bool = False):
        self.stacked = stacked
        self.k = int(k)
        self.T = int(num_trees)
        self.depth = int(max_depth)
        self.accum = resolve_accum(accum)
        self.bucket_min = max(int(bucket_min_rows), 16)
        self.chunk_rows = int(chunk_rows)
        self.sharded = bool(sharded) and len(jax.devices()) > 1
        self.class_of_np = (np.arange(self.T, dtype=np.int32)
                            % max(self.k, 1))
        self.biases_np = (None if biases is None
                          else np.asarray(biases, np.float64))
        self._mesh = None
        self._dev_cache: Dict[Tuple, jax.Array] = {}
        # shape-bucket program keys ever dispatched: the observable compile
        # cache the bucketing exists to keep small (same key => same arg
        # shapes + statics => guaranteed jit cache hit, no recompile)
        self._programs: Dict[Tuple, bool] = {}
        self._shard_programs: Dict[Tuple, object] = {}
        # guards every cache fill (device operands, program keys, serve
        # slots): concurrent FIRST calls from serve threads used to race
        # the fill and double-compile (or publish a half-built operand) —
        # the first dispatch of each new program key now runs under the
        # lock, warm traffic takes the lock-free fast path (reentrant:
        # accumulate -> _range_operands -> _dev nests)
        self._lock = threading.RLock()
        # serving mode (set by serving.ServeFrontend via
        # GBDT.enable_serve_mode): steady-state predicts of one chunk
        # re-use donated per-bucket device buffers instead of allocating
        # a padded bin matrix + carry per call (see _serve_chunk)
        self.serve_mode = False
        self._serve_slots: Dict[int, dict] = {}

    # ------------------------------------------------------------ shapes
    def bucket_rows(self, n: int) -> int:
        """Pad target: the smallest power-of-two bucket >= n (>= the
        configured floor), quarter-step refined above 4x the floor —
        4 buckets per octave keep the compile-cache size logarithmic in
        batch size while capping the padded-row waste at ~14% (pure
        pow2 wastes up to 2x minus one row). Rounded up to a
        device-count multiple when sharding so rows split evenly."""
        b = self.bucket_min
        while b < n:
            b <<= 1
        if b > n and b >= (self.bucket_min << 2):
            half = b >> 1
            for q in (5, 6, 7):              # 1.25x, 1.5x, 1.75x of b/2
                cand = (half * q) >> 2
                if cand >= n:
                    b = cand
                    break
        if self.sharded:
            d = len(jax.devices())
            b = -(-b // d) * d
        return b

    def _chunk_rows(self, n: int) -> int:
        if self.chunk_rows > 0:
            return self.chunk_rows
        return 1 << 22          # auto: ~4M-row chunks bound HBM residency

    # ------------------------------------------------------------ device
    def _dev(self, key, build):
        hit = self._dev_cache.get(key)
        if hit is None:
            with self._lock:
                hit = self._dev_cache.get(key)
                if hit is None:
                    hit = build()
                    self._dev_cache[key] = hit
        return hit

    def _range_operands(self, a: int, b: int, use_bias: bool):
        """(stacked, class_of, biases) device operands for tree range
        [a, b) — the full-range case reuses the engine's resident arrays
        (no per-call slicing dispatches)."""
        full = (a, b) == (0, self.T)
        stacked = self.stacked if full else jax.tree.map(
            lambda x: x[a:b], self.stacked)
        class_of = self._dev(("class_of", a, b),
                             lambda: jnp.asarray(self.class_of_np[a:b]))
        biases = None
        if use_bias and self.biases_np is not None:
            biases = self._dev(("biases", a, b, self.accum),
                               lambda: jnp.asarray(self.biases_np[a:b]))
        return stacked, class_of, biases

    def _mesh_axis(self):
        if self._mesh is None:
            from ..parallel.data_parallel import make_mesh
            self._mesh = make_mesh(axis="predict")
        return self._mesh, "predict"

    def _shard_program(self, key, statics):
        """shard_map-wrapped accumulation program (rows sharded, trees
        replicated) — bit-identical to the single-device scan because
        each row's accumulation order is unchanged."""
        prog = self._shard_programs.get(key)
        if prog is not None:
            return prog
        with self._lock:
            prog = self._shard_programs.get(key)
            if prog is not None:
                return prog
            from jax.sharding import PartitionSpec as P
            from ..parallel.learners import _shard_map
            mesh, axis = self._mesh_axis()
            row = P(axis)
            row2 = P(axis, None)
            carry_spec = row if self.k == 1 else row2
            use_bias = statics["use_bias"]
            use_active = statics["use_active"]
            init_zero = statics["init_zero"]
            in_specs = (P(), P(), P(), row2, P(),
                        P() if init_zero else carry_spec,
                        row if use_active else P())
            prog = jax.jit(_shard_map(
                functools.partial(_accum_core, **statics),
                mesh=mesh, in_specs=in_specs, out_specs=carry_spec))
            self._shard_programs[key] = prog
            return prog

    def _upload_rows(self, arr: np.ndarray, sharded: bool):
        """Host array -> device, placed row-sharded over the mesh when the
        sharded path is active."""
        if not sharded:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh, axis = self._mesh_axis()
        spec = P(axis) if arr.ndim == 1 else P(axis, *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    # ----------------------------------------------------- operand prep
    def prepare_bins(self, bins, bucket: int):
        """Pad (host or device) bins to ``bucket`` rows and place them on
        device (sharded over the mesh when the sharded path is active) —
        the ONE definition of the row-pad/upload rule, shared by
        _predict_chunk, leaves() and the early-stop loop."""
        pad = bucket - bins.shape[0]
        if isinstance(bins, jax.Array):
            b = jnp.pad(bins, ((0, pad), (0, 0))) if pad else bins
            # device -> device reshard when sharding (no host round trip)
            return self._upload_rows(b, self.sharded) if self.sharded else b
        b = np.pad(bins, ((0, pad), (0, 0))) if pad else bins
        return self._upload_rows(np.ascontiguousarray(b), self.sharded)

    def make_carry(self, base: Optional[np.ndarray], bucket: int):
        """Device carry seeded from a host f64 base (None = let the
        program build zeros): row-padded, cast per the accumulation mode
        (compensated pairs the seed with a zero compensation term), and
        placed like the bins."""
        if base is None:
            return None
        with _x64_scope(self.accum):
            b = np.asarray(base, np.float64)
            pad = bucket - b.shape[0]
            if pad:
                b = np.pad(b, ((0, pad),) + ((0, 0),) * (b.ndim - 1))
            if self.accum == "compensated":
                s = self._upload_rows(b.astype(np.float32), self.sharded)
                return (s, jnp.zeros_like(s))
            dt = np.float64 if self.accum == "float64" else np.float32
            return self._upload_rows(b.astype(dt), self.sharded)

    # ------------------------------------------------------- accumulation
    def accumulate(self, bins_dev, missing_bin, carry=None, active=None,
                   tree_range: Optional[Tuple[int, int]] = None,
                   use_bias: bool = True):
        """One dispatch: scan trees [a, b) over ``bins_dev`` (already
        padded to a row bucket), adding into ``carry`` (None = zeros built
        in-program). Returns the device carry."""
        a, b = tree_range if tree_range is not None else (0, self.T)
        if b <= a:
            if carry is not None:
                return carry
            a = b = 0           # empty scan: the program just builds zeros
        with _x64_scope(self.accum):
            # operand prep INSIDE the scope: the f64 bias upload would
            # silently round to f32 outside it
            stacked, class_of, biases = self._range_operands(a, b, use_bias)
            use_bias = biases is not None
            statics = dict(depth=self.depth, k=self.k, use_bias=use_bias,
                           use_active=active is not None, accum=self.accum,
                           init_zero=carry is None)
            # the key carries the stacked operand's full shape, not just
            # the tree count: two ensembles with equal T but different
            # max leaf width are DIFFERENT jit entries, and the
            # first-dispatch serialization below must know it
            key = ("accum", bins_dev.shape, b - a,
                   tuple(np.shape(stacked.leaf_value)), self.sharded,
                   tuple(sorted(statics.items())))

            def dispatch():
                if self.sharded:
                    prog = self._shard_program(key, statics)
                    return prog(stacked, class_of, biases, bins_dev,
                                missing_bin, carry, active)
                return _accum_jit(stacked, class_of, biases, bins_dev,
                                  missing_bin, carry, active, **statics)

            if key not in _compiled_keys:
                # serialize the FIRST dispatch of each new program key:
                # jax's jit cache lookup-then-trace is not atomic, so two
                # threads first-touching one shape bucket — from the same
                # engine or from two same-shape engines — would both miss
                # and compile it twice. Warm traffic (key present =>
                # program compiled) stays lock-free.
                with _first_dispatch_lock:
                    if key not in _compiled_keys:
                        out = dispatch()
                        _compiled_keys.add(key)
                        self._programs[key] = True
                        return out
            self._programs[key] = True
            return dispatch()

    def warm_aot(self, rows: int, n_features: int, bins_dtype,
                 missing_bin, serve: bool = False) -> bool:
        """AOT-compile the full-ensemble accumulation program for the
        row BUCKET ``rows`` pads to — the same shape-bucket key the
        predict compile cache builds on first touch, compiled via
        ``jit(...).lower(...).compile()`` without touching device data.

        ``serve``: warm the SERVE variant instead — ``_serve_accum_jit``
        with a concrete donated carry operand, the program the
        steady-state ``_serve_chunk`` loop actually dispatches (the plain
        variant builds its carry in-program from ``carry=None``; the two
        are different HLO modules, so warming one does not warm the
        other). ``ServeFrontend.register`` warms the
        ``serve_max_batch_rows`` bucket through this before traffic.

        With the persistent compilation cache configured
        (``compile_cache_dir``), a fresh process warms its buckets from
        DISK here instead of paying the XLA compile on the first
        full-size batch. Sharded engines skip (their shard_map wrappers
        are built per mesh at dispatch)."""
        if self.sharded:
            return False
        from .. import compile_cache
        with _x64_scope(self.accum):
            stacked, class_of, biases = self._range_operands(0, self.T,
                                                             True)
            statics = dict(depth=self.depth, k=self.k,
                           use_bias=biases is not None, use_active=False,
                           accum=self.accum, init_zero=True)
            bucket = self.bucket_rows(int(rows))
            bins_sds = jax.ShapeDtypeStruct((bucket, int(n_features)),
                                            np.dtype(bins_dtype))
            if serve:
                shape = (bucket,) if self.k == 1 else (bucket, self.k)
                if self.accum == "compensated":
                    s = jax.ShapeDtypeStruct(shape, jnp.float32)
                    carry_sds = (s, s)
                else:
                    dt = jnp.float64 if self.accum == "float64" \
                        else jnp.float32
                    carry_sds = jax.ShapeDtypeStruct(shape, dt)
                return compile_cache.aot_compile(
                    _serve_accum_jit(),
                    (stacked, class_of, biases, bins_sds, missing_bin,
                     carry_sds, None),
                    label="predict_engine serve accum",
                    static_kwargs=statics)
            return compile_cache.aot_compile(
                _accum_jit,
                (stacked, class_of, biases, bins_sds, missing_bin,
                 None, None),
                label="predict_engine accum", static_kwargs=statics)

    def fetch(self, carry, n: int) -> np.ndarray:
        """Slice off the row padding and fetch the result — the ONLY
        device->host transfer of a predict: ``n * K * itemsize`` bytes."""
        s = carry[0] if self.accum == "compensated" else carry
        with _x64_scope(self.accum):    # eager f64 slice needs the scope
            return np.asarray(jax.device_get(s[:n]), np.float64)

    # ------------------------------------------------------------ predict
    def predict(self, bins, missing_bin, *, base: Optional[np.ndarray] = None,
                use_bias: bool = True, postprocess=None,
                tree_range: Optional[Tuple[int, int]] = None) -> np.ndarray:
        """Full predict over a host (or device) bin matrix: row-chunked,
        bucket-padded, accumulated on device; returns the host ``[n, K]``
        (or ``[n]``) result. ``base``: optional f64 initial scores
        (score_dataset's init-score seed). ``postprocess``: an
        already-jitted device fn applied to the padded carry before the
        fetch (objective output conversion)."""
        n = bins.shape[0]
        chunk = self._chunk_rows(n)
        outs = []
        for a0 in range(0, max(n, 1), chunk):
            b0 = min(n, a0 + chunk)
            outs.append(self._predict_chunk(
                bins[a0:b0], missing_bin,
                None if base is None else base[a0:b0],
                postprocess, tree_range, use_bias))
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def _predict_chunk(self, bins, missing_bin, base, postprocess,
                       tree_range, use_bias) -> np.ndarray:
        n = bins.shape[0]
        if (self.serve_mode and base is None and not self.sharded
                and self.T > 0 and not isinstance(bins, jax.Array)
                and (tree_range is None
                     or tuple(tree_range) == (0, self.T))):
            return self._serve_chunk(bins, missing_bin, postprocess,
                                     use_bias)
        bucket = self.bucket_rows(n)
        bins_dev = self.prepare_bins(bins, bucket)
        carry = self.make_carry(base, bucket)
        carry = self.accumulate(bins_dev, missing_bin, carry,
                                tree_range=tree_range, use_bias=use_bias)
        if postprocess is not None:
            with _x64_scope(self.accum):
                s = carry[0] if self.accum == "compensated" else carry
                # keep the conversion's own dtype (f32 unless x64 is on
                # globally — the dtype the legacy host conversion returned)
                return np.asarray(jax.device_get(postprocess(s)[:n]))
        return self.fetch(carry, n)

    # ----------------------------------------------------- serve (donated)
    def _fresh_carry(self, bucket: int):
        """Zero carry buffer in the accumulation dtype — the cold seed of
        a serve slot (its VALUE is ignored under ``init_zero``; only its
        buffer is donated and recycled). Caller holds the x64 scope."""
        shape = (bucket,) if self.k == 1 else (bucket, self.k)
        if self.accum == "compensated":
            return (jnp.zeros(shape, jnp.float32),
                    jnp.zeros(shape, jnp.float32))
        dt = jnp.float64 if self.accum == "float64" else jnp.float32
        return jnp.zeros(shape, dt)

    def _serve_chunk(self, bins, missing_bin, postprocess,
                     use_bias) -> np.ndarray:
        """Steady-state serving predict of one host-bin chunk: the padded
        bin matrix and the carry live in per-bucket slots whose device
        buffers are DONATED back to the next flush, so the serve loop's
        large allocations happen once per bucket, not once per call.
        Bit-identical to the ordinary chunk path — the host staging array
        keeps rows beyond the current batch at zero (exactly np.pad), and
        per-row accumulation never reads another row. Runs under the
        engine lock for its whole duration: a donated buffer is invalid
        the moment the next program consumes it, so two threads in one
        slot would read freed buffers — with the lock they serialize."""
        n = bins.shape[0]
        bucket = self.bucket_rows(n)
        with self._lock, _x64_scope(self.accum):
            stacked, class_of, biases = self._range_operands(
                0, self.T, use_bias)
            use_bias = biases is not None
            statics = dict(depth=self.depth, k=self.k, use_bias=use_bias,
                           use_active=False, accum=self.accum,
                           init_zero=True)
            slot = self._serve_slots.get(bucket)
            if slot is not None and (
                    slot["staging"].shape[1] != bins.shape[1]
                    or slot["staging"].dtype != bins.dtype):
                slot = None          # feature width/dtype changed: go cold
            skey = ("serve", (bucket, bins.shape[1]),
                    tuple(np.shape(stacked.leaf_value)),
                    bool(slot is None), tuple(sorted(statics.items())))
            # the serve programs are module-level jits too: their FIRST
            # dispatch per signature takes the same module lock as
            # accumulate's — two same-shape engines (two frontends) must
            # compile each serve program exactly once. Safe with the held
            # engine lock: serve engines are never sharded, so no path
            # acquires an engine lock while holding the module lock.
            guard = _first_dispatch_lock if skey not in _compiled_keys \
                else nullcontext()
            try:
                with guard:
                    if slot is None:
                        staging = np.zeros((bucket, bins.shape[1]),
                                           bins.dtype)
                        staging[:n] = bins
                        bins_dev = jnp.asarray(staging)
                        carry = self._fresh_carry(bucket)
                    else:
                        staging = slot["staging"]
                        staging[:n] = bins
                        if slot["rows"] > n:
                            # stale rows from the previous (larger) batch
                            # must read as padding zeros, exactly np.pad
                            staging[n:slot["rows"]] = 0
                        bins_dev = _serve_refill_jit()(slot["bins"],
                                                       staging)
                        carry = slot["carry"]
                    self._programs[skey] = True
                    carry = _serve_accum_jit()(stacked, class_of, biases,
                                               bins_dev, missing_bin,
                                               carry, None, **statics)
                    _compiled_keys.add(skey)
                self._serve_slots[bucket] = {
                    "staging": staging, "bins": bins_dev, "carry": carry,
                    "rows": n}
            except BaseException:
                # donation may have invalidated the old buffers mid-call
                # (e.g. a RESOURCE_EXHAUSTED between the refill and the
                # accumulate): drop the slot so the next call goes cold
                self._serve_slots.pop(bucket, None)
                raise
            if postprocess is not None:
                s = carry[0] if self.accum == "compensated" else carry
                return np.asarray(jax.device_get(postprocess(s)[:n]))
            return self.fetch(carry, n)

    def release_serve_slots(self) -> None:
        """Drop the donated per-bucket serve buffers (the owning frontend
        closed): staging arrays and device bins/carry go back to the
        allocator; the next serve-mode predict simply goes cold."""
        with self._lock:
            self._serve_slots.clear()

    # ------------------------------------------------------------- leaves
    def leaves(self, bins, missing_bin,
               tree_range: Optional[Tuple[int, int]] = None,
               n_rows: Optional[int] = None) -> np.ndarray:
        """[t, n] int32 per-tree leaf indices over the range, via the same
        depth-bounded stacked scan (one dispatch; the [t, n] transfer is
        inherent to the predict_leaf API). Callers looping tree-range
        chunks should ``prepare_bins`` ONCE and pass the resident device
        array with ``n_rows`` = the true row count — the bin matrix is
        then uploaded once, not once per chunk."""
        a, b = tree_range if tree_range is not None else (0, self.T)
        n = bins.shape[0] if n_rows is None else n_rows
        bins_dev = bins if (isinstance(bins, jax.Array)
                            and bins.shape[0] == self.bucket_rows(n)) \
            else self.prepare_bins(bins, self.bucket_rows(n))
        stacked = self.stacked if (a, b) == (0, self.T) else jax.tree.map(
            lambda x: x[a:b], self.stacked)
        key = ("leaves", bins_dev.shape, b - a, self.depth)
        self._programs[key] = True
        leaves = _leaves_jit(stacked, bins_dev, missing_bin,
                             depth=self.depth)
        return np.asarray(jax.device_get(leaves[:, :n]))
