"""Random Forest mode.

TPU-native re-implementation of the reference RF booster
(reference: src/boosting/rf.hpp). Differences from GBDT:

- no shrinkage (rf.hpp:48 ``shrinkage_rate_ = 1.0``),
- gradients are computed ONCE from the constant boost-from-average score
  (rf.hpp:85-104 ``Boosting()`` called a single time at init),
- bagging is mandatory (rf.hpp:35 CHECK),
- each tree gets the per-class init score added as a bias (rf.hpp:135
  ``AddBias``) and the score caches hold the RUNNING MEAN of tree outputs
  (rf.hpp:139-141 MultiplyScore dance),
- prediction averages tree outputs instead of summing and adds no separate
  init score (``average_output``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..basic import Dataset
from ..config import Config
from ..objectives import ObjectiveFunction
from ..utils import log
from .gbdt import GBDT
from .tree import TreeArrays


class RF(GBDT):
    """reference: rf.hpp:25 `class RF : public GBDT`."""

    name = "rf"
    average_output = True
    # RF folds its per-tree bias into host trees each iteration
    # (rf.hpp:133-137) — keep the synchronous finalize path
    _supports_lazy_host = False

    def __init__(self, config: Config, train_set: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        if not (config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0):
            log.fatal("RF mode requires bagging "
                      "(bagging_freq > 0 and 0 < bagging_fraction < 1)")
        if not (0.0 < config.feature_fraction <= 1.0):
            log.fatal("RF mode requires 0 < feature_fraction <= 1")
        super().__init__(config, train_set, objective)

    def _init_train(self, train_set: Dataset) -> None:
        super()._init_train(train_set)
        if train_set.init_score is not None:
            log.fatal("Cannot use init_score in RF mode")
        self.shrinkage_rate = 1.0
        n = self._n_score_rows      # process-local rows when pre-partitioned
        k = self.num_tree_per_iteration
        # score caches start at zero: the init score lives INSIDE the trees
        # as a bias (rf.hpp:135), and scores hold running means of outputs.
        self.train_score = jnp.zeros(self._score_shape, jnp.float32)
        # constant-score gradients, computed once (rf.hpp:85-104)
        const = np.broadcast_to(
            np.asarray(self.init_scores, dtype=np.float32), (n, k))
        const_score = jnp.asarray(np.ascontiguousarray(
            const.reshape(self._score_shape)))
        if self.objective is None:
            log.fatal("RF mode does not support custom objective functions")
        self._const_score = const_score
        self._fixed_grad, self._fixed_hess = \
            self.objective.get_grad_hess(const_score)

    def reset_config(self, config: Config) -> None:
        super().reset_config(config)
        self.shrinkage_rate = 1.0

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        super().add_valid(valid_set, name)
        n = valid_set.num_data
        self._valid_scores[-1] = jnp.zeros(
            (n, self.num_tree_per_iteration) if self.num_tree_per_iteration > 1
            else (n,), jnp.float32)
        if self.iter > 0:
            # rebuild mean over existing trees (rf.hpp AddValidDataset)
            from .tree import predict_value_bins
            k = self.num_tree_per_iteration
            acc = self._valid_scores[-1]
            for it in range(self.iter):
                for c in range(k):
                    tree = self.trees[it * k + c]
                    d = predict_value_bins(tree, valid_set.bins, valid_set.missing_bin)
                    acc = acc.at[:, c].add(d) if k > 1 else acc + d
            self._valid_scores[-1] = acc / float(self.iter)

    def _gradients(self):
        return self._fixed_grad, self._fixed_hess

    def _renew_score(self, class_idx: int) -> np.ndarray:
        k = self.num_tree_per_iteration
        return np.asarray(self._const_score if k == 1
                          else self._const_score[:, class_idx], dtype=np.float64)

    def _finalize_tree(self, tree: TreeArrays, leaf_id, class_idx: int
                       ) -> Tuple[TreeArrays, TreeArrays, bool]:
        tree, t_host, had_split = super()._finalize_tree(tree, leaf_id,
                                                         class_idx)
        bias = self.init_scores[class_idx]
        if abs(bias) > 1e-15:
            if had_split:
                tree = tree._replace(leaf_value=tree.leaf_value + bias,
                                     node_value=tree.node_value + bias)
                t_host = t_host._replace(
                    leaf_value=t_host.leaf_value + bias,
                    node_value=t_host.node_value + bias)
            else:
                # splitless tree becomes the constant init tree (rf.hpp:131
                # AsConstantTree path)
                tree = tree._replace(leaf_value=tree.leaf_value.at[0].set(bias))
                lv = np.asarray(t_host.leaf_value).copy()
                lv[0] = bias
                t_host = t_host._replace(leaf_value=lv)
        return tree, t_host, had_split

    def _bias_after_score(self, class_idx: int, had_split: bool) -> None:
        """RF folds its bias per-tree in _finalize_tree (BEFORE the running
        mean update — the mean must include it); no post-score fold."""
        self.tree_bias.append(0.0)

    def _add_tree(self, tree: TreeArrays, leaf_id, class_idx: int,
                  linear=None, t_host=None, lazy: bool = False) -> None:
        # ``lazy`` is always False here (_supports_lazy_host = False);
        # accepted for signature compatibility with the GBDT call site
        """Running-mean score update (rf.hpp:139-141):
        score <- (score * m + tree_pred) / (m + 1)."""
        from .tree import leaf_values_of_rows, predict_value_bins
        m = float(self.iter)
        delta = leaf_values_of_rows(tree.leaf_value, leaf_id)
        k = self.num_tree_per_iteration
        if k > 1:
            col = (self.train_score[:, class_idx] * m + delta) / (m + 1.0)
            self.train_score = self.train_score.at[:, class_idx].set(col)
        else:
            self.train_score = (self.train_score * m + delta) / (m + 1.0)
        for i, vs in enumerate(self.valid_sets):
            vdelta = predict_value_bins(tree, vs.bins, vs.missing_bin)
            if k > 1:
                col = (self._valid_scores[i][:, class_idx] * m + vdelta) / (m + 1.0)
                self._valid_scores[i] = self._valid_scores[i].at[:, class_idx].set(col)
            else:
                self._valid_scores[i] = (self._valid_scores[i] * m + vdelta) / (m + 1.0)
        self.trees.append(tree)
        self._append_host_tree(t_host if t_host is not None else tree)
        self._stacked_cache = None

    def rollback_one_iter(self) -> None:
        """Mean-aware rollback (reference: rf.hpp:168-184 RollbackOneIter):
        score was mean of m trees; removing the last gives
        (score * m - tree_pred) / (m - 1), or zero when m == 1."""
        from .tree import predict_value_bins
        if self.iter <= 0:
            return
        m = float(self.iter)
        k = self.num_tree_per_iteration
        for c in range(k):
            tree = self.trees.pop()
            self.host_trees.pop()
            if self.tree_bias:
                self.tree_bias.pop()
            class_idx = k - 1 - c
            delta = predict_value_bins(tree, self.train_set.bins,
                                       self.train_set.missing_bin)
            if m > 1:
                if k > 1:
                    col = (self.train_score[:, class_idx] * m - delta) / (m - 1.0)
                    self.train_score = self.train_score.at[:, class_idx].set(col)
                else:
                    self.train_score = (self.train_score * m - delta) / (m - 1.0)
            else:
                self.train_score = jnp.zeros_like(self.train_score)
            for i, vs in enumerate(self.valid_sets):
                vdelta = predict_value_bins(tree, vs.bins, vs.missing_bin)
                if m > 1:
                    if k > 1:
                        col = (self._valid_scores[i][:, class_idx] * m - vdelta) / (m - 1.0)
                        self._valid_scores[i] = self._valid_scores[i].at[:, class_idx].set(col)
                    else:
                        self._valid_scores[i] = (self._valid_scores[i] * m - vdelta) / (m - 1.0)
                else:
                    self._valid_scores[i] = jnp.zeros_like(self._valid_scores[i])
        self.iter -= 1
        self._stacked_cache = None

    def predict_raw(self, X, num_iteration: Optional[int] = None,
                    start_iteration: int = 0, **_kwargs) -> np.ndarray:
        """Average of tree outputs (average_output_, gbdt_prediction.cpp);
        prediction early stop does not apply to averaged outputs. Summed
        on device by the inference engine in tree order (bit-identical to
        the former per-tree host loop), averaged on host."""
        bins = self.train_set.bin_new_data(X)
        k = self.num_tree_per_iteration
        n = bins.shape[0]
        total_iters = len(self.trees) // k
        if num_iteration is None or num_iteration <= 0:
            end_iter = total_iters
        else:
            end_iter = min(start_iteration + num_iteration, total_iters)
        used = max(end_iter - start_iteration, 1)
        out = np.zeros((n, k), dtype=np.float64)
        mb = self.train_set.missing_bin
        if start_iteration < end_iter:
            eng = self._predict_engine(end_iter)
            res = eng.predict(bins, mb, use_bias=False,
                              tree_range=(start_iteration * k, end_iter * k))
            out = np.array(res, np.float64).reshape(n, k)
        out /= used
        return out if k > 1 else out[:, 0]
