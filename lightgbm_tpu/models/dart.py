"""DART boosting (Dropouts meet Multiple Additive Regression Trees).

TPU-native re-implementation of the reference DART booster
(reference: src/boosting/dart.hpp). Per iteration:

  1. select a drop set of earlier iterations (skip_drop / drop_rate /
     uniform_drop / max_drop semantics, dart.hpp:97-148 DroppingTrees),
  2. remove the dropped trees' contribution from the training score so the
     gradients see a "thinned" ensemble,
  3. train the new tree with shrinkage lr/(1+k) (or the xgboost-mode rate),
  4. normalize: every dropped tree's stored values shrink by k/(k+1)
     (xgboost mode: k/(k+lr)) and all score caches are fixed up so they hold
     exactly the new contribution (dart.hpp:150-199 Normalize).

The three-step Shrinkage(-1)/Shrinkage(1/(k+1))/Shrinkage(-k) dance of the
reference is algebraically collapsed here: with stored contribution v and
k dropped trees, the net effect is v <- v * factor on the tree and on every
score cache, with the training score additionally missing v entirely during
gradient computation.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import Dataset
from ..config import Config
from ..objectives import ObjectiveFunction
from .gbdt import GBDT
from .tree import predict_value_bins


class DART(GBDT):
    """reference: dart.hpp:23 `class DART: public GBDT`."""

    name = "dart"
    # dropout renormalization rescales stored host trees every iteration
    # (dart.hpp Normalize) — the lazy host-mirror pipeline would flush
    # per-iteration anyway, so keep the synchronous path
    _supports_lazy_host = False

    def __init__(self, config: Config, train_set: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        super().__init__(config, train_set, objective)
        if getattr(self, "_pre_part", False):
            # drop/normalize re-traverses the train bins, which are
            # globally sharded here; per-shard traversal is not wired up
            from ..utils import log as _log
            _log.fatal("boosting=dart is not supported with "
                       "pre-partitioned Datasets")
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight: List[float] = []   # per-iteration weights (dart.hpp:201)
        self.sum_weight = 0.0

    def reset_config(self, config: Config) -> None:
        super().reset_config(config)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.sum_weight = sum(self.tree_weight)

    # ------------------------------------------------- checkpoint/resume
    def get_trainer_state(self) -> dict:
        """DART adds the drop RNG's full numpy state and the per-iteration
        tree weights (dart.hpp:201) — without them a resume would draw a
        DIFFERENT drop set and silently train a different model."""
        state = super().get_trainer_state()
        state["dart"] = {"drop_rng_state": self._drop_rng.get_state(),
                         "tree_weight": list(self.tree_weight),
                         "sum_weight": float(self.sum_weight)}
        return state

    def set_trainer_state(self, state: dict) -> None:
        super().set_trainer_state(state)
        d = state["dart"]
        self._drop_rng.set_state(d["drop_rng_state"])
        self.tree_weight = list(d["tree_weight"])
        self.sum_weight = float(d["sum_weight"])

    # ------------------------------------------------------------- drop
    def _select_drop_iters(self) -> List[int]:
        """reference: dart.hpp:97-134 DroppingTrees (selection part)."""
        cfg = self.config
        if self._drop_rng.rand() < cfg.skip_drop:
            return []
        drop = []
        if not cfg.uniform_drop and self.sum_weight > 0:
            drop_rate = cfg.drop_rate
            inv_avg = len(self.tree_weight) / self.sum_weight
            if cfg.max_drop > 0:
                drop_rate = min(drop_rate, cfg.max_drop * inv_avg / self.sum_weight)
            for i in range(self.iter):
                if self._drop_rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                    drop.append(i)
                    if len(drop) >= cfg.max_drop > 0:
                        break
        else:
            drop_rate = cfg.drop_rate
            if cfg.max_drop > 0 and self.iter > 0:
                drop_rate = min(drop_rate, cfg.max_drop / float(self.iter))
            for i in range(self.iter):
                if self._drop_rng.rand() < drop_rate:
                    drop.append(i)
                    if len(drop) >= cfg.max_drop > 0:
                        break
        return drop

    def _tree_contribs(self, it: int):
        """Traversal-based contribution of iteration ``it`` trees on train
        and valid sets (scores are caches, dart.hpp drops via AddScore)."""
        k = self.num_tree_per_iteration
        ts = self.train_set
        out = []
        for c in range(k):
            tree = self.trees[it * k + c]
            train_delta = predict_value_bins(tree, ts.bins, ts.missing_bin)
            valid_deltas = [predict_value_bins(tree, vs.bins, vs.missing_bin)
                            for vs in self.valid_sets]
            out.append((train_delta, valid_deltas))
        return out

    def _scale_stored_tree(self, idx: int, factor: float) -> None:
        tree = self.trees[idx]
        self.trees[idx] = tree._replace(
            leaf_value=tree.leaf_value * factor,
            node_value=tree.node_value * factor,
            shrinkage=tree.shrinkage * factor)
        host = self.host_trees[idx]
        self.host_trees[idx] = host.scaled(factor)

    # ------------------------------------------------------------ train
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        cfg = self.config
        k_cls = self.num_tree_per_iteration
        drop = self._select_drop_iters()
        k = float(len(drop))

        # step 1-2: remove dropped contribution from the train score
        contribs = {}
        for it in drop:
            contribs[it] = self._tree_contribs(it)
            for c in range(k_cls):
                delta, _ = contribs[it][c]
                if k_cls > 1:
                    self.train_score = self.train_score.at[:, c].add(-delta)
                else:
                    self.train_score = self.train_score - delta

        # shrinkage for the new tree (dart.hpp:136-147)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k)
        else:
            self.shrinkage_rate = cfg.learning_rate if not drop else \
                cfg.learning_rate / (cfg.learning_rate + k)

        ret = super().train_one_iter(grad, hess)
        if ret:
            # no split found; undo the drop to restore score caches. The
            # (constant) trees were still appended and iter advanced, so the
            # weight bookkeeping below must still run to stay in sync.
            for it in drop:
                for c in range(k_cls):
                    delta, _ = contribs[it][c]
                    if k_cls > 1:
                        self.train_score = self.train_score.at[:, c].add(delta)
                    else:
                        self.train_score = self.train_score + delta
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
            return ret

        # step 4: normalize (dart.hpp:150-199)
        factor = (k / (k + 1.0)) if not cfg.xgboost_dart_mode else \
            (k / (k + cfg.learning_rate))
        for it in drop:
            for c in range(k_cls):
                delta, vdeltas = contribs[it][c]
                if k_cls > 1:
                    self.train_score = self.train_score.at[:, c].add(factor * delta)
                else:
                    self.train_score = self.train_score + factor * delta
                for i, vd in enumerate(vdeltas):
                    if k_cls > 1:
                        self._valid_scores[i] = self._valid_scores[i].at[:, c].add(
                            (factor - 1.0) * vd)
                    else:
                        self._valid_scores[i] = self._valid_scores[i] + (factor - 1.0) * vd
                self._scale_stored_tree(it * k_cls + c, factor)
            # weight bookkeeping runs in BOTH drop modes (the reference only
            # tracks it when !uniform_drop, dart.hpp:178-181) so a later
            # reset_config switching drop modes sees consistent weights.
            self.sum_weight -= self.tree_weight[it] * (1.0 - factor)
            self.tree_weight[it] *= factor
        self._stacked_cache = None

        self.tree_weight.append(self.shrinkage_rate)
        self.sum_weight += self.shrinkage_rate
        return False
