"""Tree model object: fixed-capacity array representation + traversal.

TPU-native analog of the reference's flat-array binary tree
(reference: include/LightGBM/tree.h:62-231, src/io/tree.cpp). A tree with
leaf capacity L has L-1 internal-node slots and L leaf slots; child links
follow the reference's encoding: ``child >= 0`` is an internal node index,
``child < 0`` is ``~leaf_index`` (tree.h ``left_child_``/``right_child_``).

Thresholds are stored in BIN space for exact device traversal over the binned
matrix (the training-data path), plus real-valued thresholds filled from the
bin mappers for raw-feature traversal (reference: Tree::RealThreshold via
``BinMapper::BinToValue``). Missing-value routing mirrors
``Tree::NumericalDecision`` (tree.h:133+, decision_type missing flags).
"""

from __future__ import annotations

import copy
from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class TreeArrays(NamedTuple):
    """Single tree as device arrays. Internal-node arrays have shape
    [L-1], leaf arrays [L]; ``num_leaves`` is the used count."""
    num_leaves: jax.Array        # int32 scalar (actual leaves used)
    node_feature: jax.Array      # int32 [L-1] inner feature index
    node_threshold_bin: jax.Array  # int32 [L-1]
    node_default_left: jax.Array   # bool [L-1]
    node_left: jax.Array         # int32 [L-1]  (>=0 node, <0 = ~leaf)
    node_right: jax.Array        # int32 [L-1]
    node_gain: jax.Array         # f32 [L-1] split gain
    node_value: jax.Array        # f32 [L-1] internal output (pre-shrinkage)
    node_weight: jax.Array       # f32 [L-1] sum_hessian at node
    node_count: jax.Array        # f32 [L-1]
    node_cat: jax.Array          # bool [L-1] categorical split flag
    node_cat_bitset: jax.Array   # uint32 [L-1, CAT_WORDS] bin membership (left side)
    node_seg_lo: jax.Array       # int32 [L-1] EFB bundle segment start (-1 = regular)
    node_seg_hi: jax.Array       # int32 [L-1] EFB bundle segment end (inclusive)
    leaf_value: jax.Array        # f32 [L] (shrinkage already applied by booster)
    leaf_weight: jax.Array       # f32 [L] sum_hessian
    leaf_count: jax.Array        # f32 [L]
    leaf_depth: jax.Array        # int32 [L]
    leaf_parent: jax.Array       # int32 [L]
    shrinkage: jax.Array         # f32 scalar


def empty_tree(max_leaves: int, cat_words: int = 8) -> TreeArrays:
    li, lf = max_leaves - 1, max_leaves
    i32 = lambda n, v=0: jnp.full((n,), v, dtype=jnp.int32)
    f32 = lambda n: jnp.zeros((n,), dtype=jnp.float32)
    return TreeArrays(
        num_leaves=jnp.int32(1),
        node_feature=i32(li), node_threshold_bin=i32(li),
        node_default_left=jnp.zeros((li,), dtype=bool),
        node_left=i32(li, -1), node_right=i32(li, -1),
        node_gain=f32(li), node_value=f32(li), node_weight=f32(li),
        node_count=f32(li),
        node_cat=jnp.zeros((li,), dtype=bool),
        node_cat_bitset=jnp.zeros((li, cat_words), dtype=jnp.uint32),
        node_seg_lo=i32(li, -1), node_seg_hi=i32(li, -1),
        leaf_value=f32(lf), leaf_weight=f32(lf), leaf_count=f32(lf),
        leaf_depth=i32(lf), leaf_parent=i32(lf, -1),
        shrinkage=jnp.float32(1.0),
    )


def _decide_left_bins(bin_val, threshold_bin, default_left, missing_bin,
                      is_cat, cat_bitset, seg_lo=None, seg_hi=None):
    """Split decision in bin space.

    ``missing_bin``: per-feature bin routed by default direction (-1 when the
    feature has no missing routing; see ops/split.py mode analysis).
    Categorical: left iff the bin's bit is set in the membership bitset
    (reference: Tree::CategoricalDecision bitset FindInBitset, tree.h:133+).
    ``seg_lo/seg_hi``: EFB bundle segment for bundle-column splits — rows
    outside the owning member's bin range are that member's default mass and
    route by ``default_left`` (the model-file analog is a missing_type=Zero
    node, tree.h NumericalDecision).
    """
    num_default = (bin_val == missing_bin) & (missing_bin >= 0)
    num_left = jnp.where(num_default, default_left, bin_val <= threshold_bin)
    if seg_lo is not None:
        in_seg = (bin_val >= seg_lo) & (bin_val <= seg_hi)
        bundle_left = jnp.where(in_seg, bin_val <= threshold_bin, default_left)
        num_left = jnp.where(seg_lo >= 0, bundle_left, num_left)
    word = (bin_val >> 5).astype(jnp.int32)
    bit = (bin_val & 31).astype(jnp.int32)
    cat_words = jnp.take_along_axis(cat_bitset, word[:, None], axis=1)[:, 0]
    cat_left = ((cat_words >> bit.astype(jnp.uint32)) & 1) == 1
    return jnp.where(is_cat, cat_left, num_left)


def _traversal_setup(tree: TreeArrays, bins: jax.Array,
                     missing_bin: jax.Array):
    """Shared setup of the level-by-level traversal: the 0-feature guard,
    the step body (descend every active row one edge) and the initial
    (cur, leaf) state. Used by both the data-dependent while_loop
    traversal and the depth-bounded fori_loop traversal below."""
    n = bins.shape[0]
    if bins.shape[1] == 0:
        # 0-feature dataset (every feature pre-filtered as trivial): all
        # trees are splitless, every row lands in leaf 0; pad one dummy
        # column so the gathers below stay well-formed for the traversal
        # machinery (which never routes anywhere for a 1-leaf tree anyway)
        bins = jnp.zeros((n, 1), dtype=bins.dtype)
        missing_bin = jnp.full((1,), -1, dtype=jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)

    def step(state):
        cur, leaf = state
        active = cur >= 0
        node = jnp.maximum(cur, 0)
        feat = tree.node_feature[node]
        b = bins[rows, feat].astype(jnp.int32)
        go_left = _decide_left_bins(
            b, tree.node_threshold_bin[node], tree.node_default_left[node],
            missing_bin[feat], tree.node_cat[node], tree.node_cat_bitset[node],
            tree.node_seg_lo[node], tree.node_seg_hi[node])
        nxt = jnp.where(go_left, tree.node_left[node], tree.node_right[node])
        nxt = jnp.where(active, nxt, cur)
        new_leaf = jnp.where(active & (nxt < 0), ~nxt, leaf)
        return nxt, new_leaf

    # single-leaf tree: no nodes to traverse
    init_cur = jnp.where(tree.num_leaves <= 1, -1, 0) * jnp.ones((n,), jnp.int32)
    return step, (init_cur, jnp.zeros((n,), dtype=jnp.int32))


def predict_leaf_bins(tree: TreeArrays, bins: jax.Array,
                      missing_bin: jax.Array) -> jax.Array:
    """Leaf index per row by traversing over the binned matrix.

    Args:
      bins: [N, F] int bins.
      missing_bin: [F] int32, per-feature default-routed bin or -1.
    Returns [N] int32 leaf indices.
    """
    step, init = _traversal_setup(tree, bins, missing_bin)

    def cond(state):
        return jnp.any(state[0] >= 0)

    _, leaf = jax.lax.while_loop(cond, lambda s: step(s), init)
    return leaf


def predict_leaf_bins_depth(tree: TreeArrays, bins: jax.Array,
                            missing_bin: jax.Array, depth: int) -> jax.Array:
    """Depth-bounded traversal: a ``fori_loop`` with a STATIC trip count
    instead of the data-dependent ``while_loop`` above. ``depth`` must be
    >= the deepest leaf's edge count in ``tree`` — rows whose leaf is
    reached earlier mask out (cur < 0) and the remaining steps are
    no-ops, so the leaf indices are IDENTICAL to predict_leaf_bins.

    The point: inside a stacked-ensemble scan the while_loop stalls every
    batch on its slowest row AND blocks XLA from pipelining/fusing across
    trees (a data-dependent trip count is a hard scheduling barrier); a
    fixed trip count turns the whole ensemble traversal into a statically
    schedulable loop nest (the batched analog of the reference's
    unconditional per-node descent, gbdt_prediction.cpp:13-53)."""
    step, init = _traversal_setup(tree, bins, missing_bin)
    _, leaf = jax.lax.fori_loop(0, depth, lambda _, s: step(s), init)
    return leaf


def predict_value_bins(tree: TreeArrays, bins: jax.Array,
                       missing_bin: jax.Array) -> jax.Array:
    """Tree output per row (leaf_value already includes shrinkage)."""
    leaf = predict_leaf_bins(tree, bins, missing_bin)
    return tree.leaf_value[leaf]


import functools


@functools.partial(jax.jit, static_argnames=("block",))
def _leaf_values_of_rows_tpu(leaf_value: jax.Array, leaf_id: jax.Array,
                             block: int) -> jax.Array:
    n = leaf_id.shape[0]
    l = leaf_value.shape[0]
    c = min(block, -(-n // 512) * 512)
    pad = -n % c
    lid = jnp.pad(leaf_id, (0, pad), constant_values=-1) if pad else leaf_id
    iota = jnp.arange(l, dtype=jnp.int32)

    def body(_, lid_blk):
        oh = (lid_blk[:, None] == iota[None, :]).astype(jnp.float32)
        # HIGHEST precision: the default TPU matmul would bf16-round
        # leaf_value (~0.4% rel) in every train-score update, biasing
        # gradients each iteration (the reference accumulates scores in
        # double, score_updater.hpp)
        vals = jax.lax.dot_general(
            oh, leaf_value[:, None], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)[:, 0]
        return _, vals

    _, vals = jax.lax.scan(body, 0, lid.reshape(-1, c))
    return vals.reshape(-1)[:n]


def leaf_values_of_rows(leaf_value: jax.Array, leaf_id: jax.Array,
                        block: int = 65536) -> jax.Array:
    """Per-row tree output ``leaf_value[leaf_id]`` without a gather.

    XLA's gather from a small table costs ~90ms for 10M rows on a v5e (it
    serializes); a jitted blocked compare x matmul runs at memory bandwidth
    (unjitted, the scan dispatches eagerly step by step — ~0.8s at 2M rows
    through a TPU tunnel). Used for the training-score update (the analog of
    Tree::AddPredictionToScore, tree.h, which indexes the data partition
    instead)."""
    if jax.default_backend() != "tpu":
        return leaf_value[leaf_id]
    return _leaf_values_of_rows_tpu(leaf_value, leaf_id, block)


def stack_trees(trees: List[TreeArrays]) -> TreeArrays:
    """Stack per-tree arrays with a leading T axis for scan-based ensemble
    prediction (the analog of GBDT::PredictRaw's per-tree loop,
    gbdt_prediction.cpp:13-53, but batched on device)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def predict_value_ensemble(stacked: TreeArrays, bins: jax.Array,
                           missing_bin: jax.Array,
                           num_trees: int | None = None) -> jax.Array:
    """Sum of tree outputs over a stacked ensemble via lax.scan."""

    def step(carry, tree):
        return carry + predict_value_bins(tree, bins, missing_bin), None

    total, _ = jax.lax.scan(step, jnp.zeros((bins.shape[0],), jnp.float32), stacked)
    return total


@jax.jit
def predict_leaves_stacked(stacked: TreeArrays, bins: jax.Array,
                           missing_bin: jax.Array) -> jax.Array:
    """Per-tree leaf indices over a stacked ensemble in one device program
    (the batched analog of the per-tree predict_leaf loop). Returns
    [T, N] int32."""
    def step(_, tree):
        return _, predict_leaf_bins(tree, bins, missing_bin)

    _, leaves = jax.lax.scan(step, 0, stacked)
    return leaves


@jax.jit
def predict_values_stacked(stacked: TreeArrays, bins: jax.Array,
                           missing_bin: jax.Array) -> jax.Array:
    """Per-tree outputs over a stacked ensemble in ONE device program (the
    batched analog of GBDT::PredictRaw's per-tree loop,
    gbdt_prediction.cpp:13-53 — a 500-tree predict is a handful of
    dispatches, not 500 tunnel round trips). The per-tree values are
    returned (not summed on device) so the caller can accumulate in float64
    in tree order, bit-identical to the host per-tree path.

    Returns [T, N] float32.
    """
    def step(_, tree):
        return _, predict_value_bins(tree, bins, missing_bin)

    _, vals = jax.lax.scan(step, 0, stacked)
    return vals


# --------------------------------------------------------------------- host
class HostTree:
    """Host-side (numpy) view of a trained tree for model IO, SHAP and
    raw-feature prediction. Built once per tree after training."""

    def __init__(self, arrays: TreeArrays, real_thresholds: np.ndarray,
                 feature_indices: np.ndarray,
                 missing_types: np.ndarray | None = None):
        # one batched device_get: per-array fetches each pay a full host
        # round-trip (~75ms over a TPU tunnel), ~18x per tree
        t = jax.device_get(arrays)
        self.num_leaves = int(t.num_leaves)
        n = max(self.num_leaves - 1, 0)
        self.split_feature = t.node_feature[:n].astype(np.int32)
        self.threshold_bin = t.node_threshold_bin[:n]
        self.threshold = real_thresholds[:n]
        self.default_left = t.node_default_left[:n]
        self.left_child = t.node_left[:n]
        self.right_child = t.node_right[:n]
        self.split_gain = t.node_gain[:n]
        self.internal_value = t.node_value[:n]
        self.internal_weight = t.node_weight[:n]
        self.internal_count = t.node_count[:n]
        self.is_cat = t.node_cat[:n]
        self.cat_bitset = t.node_cat_bitset[:n]
        self.leaf_value = t.leaf_value[:self.num_leaves]
        self.leaf_weight = t.leaf_weight[:self.num_leaves]
        self.leaf_count = t.leaf_count[:self.num_leaves]
        self.leaf_depth = t.leaf_depth[:self.num_leaves]
        self.leaf_parent = t.leaf_parent[:self.num_leaves]
        self.shrinkage = float(t.shrinkage)
        # map inner feature index -> original column index
        self.feature_indices = feature_indices
        # per-node missing type (binning.MISSING_*), for decision_type dumps
        # (reference: tree.h:269 GetMissingType packed in decision_type_)
        self.missing_type = (missing_types[:n].astype(np.int8)
                             if missing_types is not None
                             else np.zeros(n, dtype=np.int8))
        # linear-leaf model (reference: tree.h:194-204 leaf_coeff_/leaf_const_)
        self.is_linear = False
        self.leaf_const: np.ndarray | None = None
        self.leaf_coeff: list = []
        self.leaf_features_raw: list = []

    def scaled(self, factor: float) -> "HostTree":
        """Copy with outputs scaled (reference: Tree::Shrinkage, tree.h:187;
        used by DART normalization). Linear coefficients scale too."""
        out = copy.copy(self)
        out.leaf_value = self.leaf_value * factor
        out.internal_value = self.internal_value * factor
        out.shrinkage = self.shrinkage * factor
        if self.is_linear and self.leaf_const is not None:
            out.leaf_const = self.leaf_const * factor
            out.leaf_coeff = [[c * factor for c in cs] for cs in self.leaf_coeff]
        return out
