"""Boosting factory (reference: src/boosting/boosting.cpp CreateBoosting)."""

from __future__ import annotations

from ..config import Config
from ..utils import log
from .gbdt import GBDT


def create_boosting(config: Config, train_set=None):
    name = config.boosting
    if name == "gbdt":
        return GBDT(config, train_set)
    if name == "dart":
        from .dart import DART
        return DART(config, train_set)
    if name == "goss":
        from .goss import GOSS
        return GOSS(config, train_set)
    if name == "rf":
        from .rf import RF
        return RF(config, train_set)
    log.fatal(f"Unknown boosting type: {name}")
